"""Post-training quantization for static programs.

Reference parity: fluid/contrib/slim/quantization/
post_training_quantization.py (calibrate activation scales by feeding
sample data, compute weight scales, then rewrite the program) and
quantization_pass.py (QuantizationTransformPass — insert quant/dequant
around every quantizable op's inputs).

TPU-native: the rewrite inserts ``fake_quantize_dequantize_abs_max``-
style simulation ops with *calibrated constant scales* in front of each
matmul/mul/conv2d input; XLA folds the scale math into the surrounding
fusion. The quantized program is a drop-in for the Executor/Predictor.
"""
from __future__ import annotations

import json
import os

import numpy as np

import jax.numpy as jnp

_QUANTIZABLE = ("mul", "matmul", "conv2d")

#: scale metadata sidecar written next to the saved int8 program
QUANT_METADATA_FILENAME = "__quant__.json"

# calibration floor: a dead activation (abs-max 0.0) must never produce
# a 0 scale — dequantizing by it is NaN/inf (see _clamped_scale)
_SCALE_EPS = 1e-8


def _clamped_scale(name, raw):
    """Clamp a calibrated scale away from zero.

    A variable whose calibration abs-max is 0.0 (dead activation, an
    all-zero calibration batch) would otherwise bake a 0 scale into the
    program — and dequantizing by it is NaN/inf at serving time, far
    from the calibration run that caused it. Clamp to a tiny epsilon
    (the quantized values are all 0 anyway, so the clamp is exact) and
    leave a flight-recorder breadcrumb naming the variable.
    """
    s = float(raw)
    if s > _SCALE_EPS:
        return s
    from ..monitor import flight_recorder as _flight

    _flight.record_event("ptq_zero_scale", var=name, raw_scale=s,
                         clamped_to=_SCALE_EPS)
    return _SCALE_EPS


def _collect_var_abs_max(program, scope, exe, feed_batches, var_names):
    """Run calibration batches; record abs-max per listed var.

    ONE ``exe.run`` per batch fetches every calibration var. The fetch
    set is validated against what the program's ops actually produce
    BEFORE running: a requested var nothing computes would either fail
    deep inside the trace or — worse, when a stale same-named value
    sits in the scope — silently calibrate on garbage. Error loudly
    naming the missing vars instead.
    """
    var_names = list(var_names)
    produced = set()
    for blk in program.blocks:
        for op in blk.ops:
            produced.update(op.output_names())
        for name, var in blk.vars.items():
            if getattr(var, "_meta", {}).get("is_data"):
                produced.add(name)  # feed vars land in env directly
    for feed in feed_batches:
        produced.update(feed)
    missing = sorted(set(var_names) - produced)
    if missing:
        from ..errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"calibration vars {missing} are not produced by any op in "
            "the program (pruned or renamed?); the fetched set must "
            f"equal the requested set ({len(var_names)} vars)")
    maxes = {n: 0.0 for n in var_names}
    for feed in feed_batches:
        outs = exe.run(program, feed=feed, fetch_list=var_names)
        if len(outs) != len(var_names):
            raise RuntimeError(
                f"calibration fetch returned {len(outs)} values for "
                f"{len(var_names)} requested vars — fetched set must "
                "equal the requested set")
        for n, v in zip(var_names, outs):
            maxes[n] = max(maxes[n], float(np.max(np.abs(np.asarray(v)))))
    return maxes


def quantize_static_program(program, scope, exe, feed_batches, *,
                            weight_bits=8, activation_bits=8):
    """QuantizationTransformPass + calibration in one step.

    Mutates ``program``: every quantizable op's activation input gets a
    quant-dequant op with its calibrated scale; weight inputs (persistable
    vars) are quant-dequantized in the scope directly (per-tensor abs
    max). Returns {var_name: scale} for deployment metadata.
    """
    block = program.global_block()
    # find activation inputs of quantizable ops (non-persistable vars)
    act_inputs = []
    weight_inputs = set()
    for op in block.ops:
        if op.type not in _QUANTIZABLE:
            continue
        for n in op.inputs.get("X", []):
            if block.has_var(n) and block.var(n).persistable:
                weight_inputs.add(n)
            elif scope.has(n):
                weight_inputs.add(n)
            else:
                act_inputs.append(n)
    act_inputs = sorted(set(act_inputs))

    scales = _collect_var_abs_max(program, scope, exe, feed_batches,
                                  act_inputs)
    scales = {n: _clamped_scale(n, s) for n, s in scales.items()}

    # weights: quant-dequant in place (per-tensor abs-max, like the
    # reference's weight_quantize_type="abs_max" path)
    bnt_w = float((1 << (weight_bits - 1)) - 1)
    for n in sorted(weight_inputs):
        w = np.asarray(scope.get(n))
        s = _clamped_scale(n, float(np.max(np.abs(w))))
        q = np.round(np.clip(w / s * bnt_w, -bnt_w, bnt_w))
        scope.set(n, jnp.asarray((q * s / bnt_w).astype(w.dtype)))
        scales[n] = s

    # activations: insert scale-clamped quant-dequant ops before use
    from ..static.program import OpDesc

    bnt = float((1 << (activation_bits - 1)) - 1)
    new_ops = []
    renamed = {}
    for op in block.ops:
        if op.type in _QUANTIZABLE:
            new_inputs = {}
            for slot, names in op.inputs.items():
                out_names = []
                for n in names:
                    if n in scales and n not in weight_inputs:
                        if n not in renamed:
                            qn = program._unique_name(f"{n}.quantized")
                            src = block.var(n)
                            block.create_var(
                                name=qn, shape=src.shape,
                                dtype=str(src.dtype),
                            )
                            new_ops.append(OpDesc(
                                "quant_dequant_static",
                                {"X": [n]}, {"Out": [qn]},
                                {"scale": float(scales[n]),
                                 "bit_length": activation_bits},
                            ))
                            renamed[n] = qn
                        out_names.append(renamed[n])
                    else:
                        out_names.append(n)
                new_inputs[slot] = out_names
            op.inputs = new_inputs
        new_ops.append(op)
    block.ops[:] = new_ops
    program._version = getattr(program, "_version", 0) + 1
    return scales


def rewrite_int8_program(program, scope, scales, *, weight_bits=8,
                         activation_bits=8):
    """Lower a calibrated fake-quant program to a DEPLOYABLE int8 one.

    Input: a program ``quantize_static_program`` already rewrote
    (``quant_dequant_static`` sim ops in front of quantizable ops,
    qdq'd f32 weights in the scope) plus its ``scales``. Output: a NEW
    program (the input is untouched) where

    - every quantized weight is stored as a REAL int8 array in the scope
      under ``<w>@int8`` (exact: the scope value already sits on the
      int8 grid, so re-quantizing loses nothing);
    - matmul/mul ops whose activation input carries a calibrated scale
      and whose second operand is a quantized weight become
      ``matmul_int8``/``mul_int8``: the activation is quantized by ONE
      ``quantize_static`` op (f32→int8) and the contraction runs
      int8×int8→int32 (ops/pallas/int8_matmul.py behind
      ``FLAGS_use_int8_matmul``), dequantized once by the combined
      scale — no fake-quant simulation left on the path;
    - ops with no int8 compute path (conv2d, or a matmul whose weight is
      the first operand) keep the sim op for their activation but still
      ship the int8 weight, restored by a load-time
      ``dequantize_static`` (the Predictor's constant-folding pass
      materializes it once at load).

    Returns ``(new_program, int8_weights)`` where ``int8_weights`` maps
    ``<w>@int8`` names to the int8 arrays that were installed in
    ``scope`` (the save path persists them; f32 originals drop out of
    the pruned program).
    """
    from ..static.program import OpDesc, Program

    bnt_w = float((1 << (weight_bits - 1)) - 1)
    prog = Program.from_dict(program.to_dict())
    prog._constants = dict(getattr(program, "_constants", {}))
    block = prog.global_block()

    # recover the sim pass's bookkeeping from the program itself: every
    # quant_dequant_static op is (base var -> qdq'd var, scale attr)
    qdq_of = {}      # qdq output name -> (base name, scale)
    for op in block.ops:
        if op.type == "quant_dequant_static":
            qdq_of[op.outputs["Out"][0]] = (op.inputs["X"][0],
                                            float(op.attrs["scale"]))

    def is_weight(n):
        return (n in scales
                and ((block.has_var(n) and block.var(n).persistable)
                     or scope.has(n)))

    # decide per quantizable op whether the int8 compute rewrite applies
    int8_ops = {}    # id(op) -> (act_qdq_name, weight_name)
    for op in block.ops:
        if op.type not in ("mul", "matmul"):
            continue
        ins = op.inputs.get("X", [])
        if len(ins) != 2:
            continue
        a, w = ins
        if a in qdq_of and is_weight(w):
            int8_ops[id(op)] = (a, w)

    # int8 consumers per qdq var: a qdq op ALL of whose consumers went
    # int8 is replaced by quantize_static; mixed consumers keep both
    qdq_consumers = {}   # qdq name -> [total, int8]
    for op in block.ops:
        for n in op.input_names():
            if n in qdq_of:
                stats = qdq_consumers.setdefault(n, [0, 0])
                stats[0] += 1
                if id(op) in int8_ops:
                    stats[1] += 1

    int8_weights = {}

    def quantized_weight(w):
        qname = f"{w}@int8"
        if qname not in int8_weights:
            arr = np.asarray(scope.get(w))
            s = scales[w]
            q = np.round(np.clip(arr / s * bnt_w, -bnt_w, bnt_w)).astype(
                np.int8)
            int8_weights[qname] = q
            scope.set(qname, jnp.asarray(q))
            block.create_var(name=qname, shape=list(q.shape), dtype="int8",
                             persistable=True)
        return qname

    new_ops = []
    for op in block.ops:
        if op.type == "quant_dequant_static":
            qn = op.outputs["Out"][0]
            base, scale = qdq_of[qn]
            total, as_int8 = qdq_consumers.get(qn, [0, 0])
            if as_int8:
                q8 = f"{base}@q8"
                src = block.var(base)
                block.create_var(name=q8, shape=src.shape, dtype="int8")
                new_ops.append(OpDesc(
                    "quantize_static", {"X": [base]}, {"Out": [q8]},
                    {"scale": scale, "bit_length": activation_bits}))
            if as_int8 < total or total == 0:
                new_ops.append(op)  # non-int8 consumers still need the sim
            continue

        if id(op) in int8_ops:
            a, w = int8_ops[id(op)]
            base, scale_a = qdq_of[a]
            attrs = {k: v for k, v in op.attrs.items()}
            attrs.update(scale_x=scale_a, scale_y=scales[w],
                         bit_length=activation_bits,
                         y_bit_length=weight_bits)
            new_ops.append(OpDesc(
                f"{op.type}_int8",
                {"X": [f"{base}@q8", quantized_weight(w)]},
                dict(op.outputs), attrs))
            continue

        if op.type in _QUANTIZABLE:
            # no int8 compute path: ship the weight as int8 anyway and
            # restore f32 at load time (constant folding collapses it)
            new_inputs = {}
            for slot, names in op.inputs.items():
                out_names = []
                for n in names:
                    if is_weight(n):
                        qname = quantized_weight(n)
                        deq = f"{n}@deq"
                        if not block.has_var(deq):
                            src = block.var(n)
                            block.create_var(name=deq, shape=src.shape,
                                             dtype=str(src.dtype))
                            new_ops.append(OpDesc(
                                "dequantize_static", {"X": [qname]},
                                {"Out": [deq]},
                                {"scale": scales[n],
                                 "bit_length": weight_bits,
                                 "dtype": str(src.dtype)}))
                        out_names.append(deq)
                    else:
                        out_names.append(n)
                new_inputs[slot] = out_names
            new_ops.append(OpDesc(op.type, new_inputs, dict(op.outputs),
                                  dict(op.attrs)))
            continue

        new_ops.append(op)
    block.ops[:] = new_ops
    prog._version = getattr(prog, "_version", 0) + 1
    return prog, int8_weights


class PostTrainingQuantization:
    """post_training_quantization.py facade over the pass above."""

    def __init__(self, executor, program, feed_batches, scope=None,
                 weight_bits=8, activation_bits=8):
        from ..static.executor import global_scope

        self._exe = executor
        self._program = program
        self._batches = list(feed_batches)
        self._scope = scope or global_scope()
        self._wbits = weight_bits
        self._abits = activation_bits
        self.scales = None

    def quantize(self):
        self.scales = quantize_static_program(
            self._program, self._scope, self._exe, self._batches,
            weight_bits=self._wbits, activation_bits=self._abits,
        )
        return self._program

    def save_quantized_model(self, dirname, feed_names, fetch_vars):
        from ..static import io as static_io

        return static_io.save_inference_model(
            dirname, feed_names, fetch_vars, self._exe,
            main_program=self._program,
        )

    def save_int8_model(self, dirname, feed_names, fetch_vars):
        """Save a DEPLOYABLE int8 inference program.

        Folds the calibrated scales into the saved program as real int8
        weights + per-tensor activation scales (``rewrite_int8_program``
        — ``quantize_static``/``matmul_int8``/``mul_int8`` ops, not
        fake-quant simulation). The result loads into an UNCHANGED
        ``inference.Predictor``; a ``__quant__.json`` sidecar persists
        the scale metadata (bits, per-var scales, int8 weight names) for
        tooling. Returns the fetch names like ``save_quantized_model``.
        """
        if self.scales is None:
            raise RuntimeError(
                "save_int8_model needs calibrated scales; call "
                "quantize() first")
        from ..monitor import flight_recorder as _flight
        from ..static import io as static_io

        prog, int8_weights = rewrite_int8_program(
            self._program, self._scope, self.scales,
            weight_bits=self._wbits, activation_bits=self._abits)
        out = static_io.save_inference_model(
            dirname, feed_names, fetch_vars, self._exe, main_program=prog)
        meta = {
            "version": 1,
            "weight_bits": self._wbits,
            "activation_bits": self._abits,
            "scales": {n: float(s) for n, s in self.scales.items()},
            "int8_weights": sorted(int8_weights),
        }
        with open(os.path.join(dirname, QUANT_METADATA_FILENAME), "w") as f:
            json.dump(meta, f)
        _flight.record_event(
            "int8_model_saved", dir=dirname,
            int8_weights=len(int8_weights), scales=len(self.scales))
        return out


def load_quant_metadata(dirname):
    """Read the ``__quant__.json`` sidecar ``save_int8_model`` wrote
    (None when the dir holds no quantized model)."""
    path = os.path.join(dirname, QUANT_METADATA_FILENAME)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)
