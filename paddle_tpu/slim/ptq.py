"""Post-training quantization for static programs.

Reference parity: fluid/contrib/slim/quantization/
post_training_quantization.py (calibrate activation scales by feeding
sample data, compute weight scales, then rewrite the program) and
quantization_pass.py (QuantizationTransformPass — insert quant/dequant
around every quantizable op's inputs).

TPU-native: the rewrite inserts ``fake_quantize_dequantize_abs_max``-
style simulation ops with *calibrated constant scales* in front of each
matmul/mul/conv2d input; XLA folds the scale math into the surrounding
fusion. The quantized program is a drop-in for the Executor/Predictor.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

_QUANTIZABLE = ("mul", "matmul", "conv2d")


def _collect_var_abs_max(program, scope, exe, feed_batches, var_names):
    """Run calibration batches; record abs-max per listed var."""
    maxes = {n: 0.0 for n in var_names}
    for feed in feed_batches:
        outs = exe.run(program, feed=feed, fetch_list=list(var_names))
        for n, v in zip(var_names, outs):
            maxes[n] = max(maxes[n], float(np.max(np.abs(np.asarray(v)))))
    return maxes


def quantize_static_program(program, scope, exe, feed_batches, *,
                            weight_bits=8, activation_bits=8):
    """QuantizationTransformPass + calibration in one step.

    Mutates ``program``: every quantizable op's activation input gets a
    quant-dequant op with its calibrated scale; weight inputs (persistable
    vars) are quant-dequantized in the scope directly (per-tensor abs
    max). Returns {var_name: scale} for deployment metadata.
    """
    block = program.global_block()
    # find activation inputs of quantizable ops (non-persistable vars)
    act_inputs = []
    weight_inputs = set()
    for op in block.ops:
        if op.type not in _QUANTIZABLE:
            continue
        for n in op.inputs.get("X", []):
            if block.has_var(n) and block.var(n).persistable:
                weight_inputs.add(n)
            elif scope.has(n):
                weight_inputs.add(n)
            else:
                act_inputs.append(n)
    act_inputs = sorted(set(act_inputs))

    scales = _collect_var_abs_max(program, scope, exe, feed_batches,
                                  act_inputs)

    # weights: quant-dequant in place (per-tensor abs-max, like the
    # reference's weight_quantize_type="abs_max" path)
    bnt_w = float((1 << (weight_bits - 1)) - 1)
    for n in sorted(weight_inputs):
        w = np.asarray(scope.get(n))
        s = max(float(np.max(np.abs(w))), 1e-8)
        q = np.round(np.clip(w / s * bnt_w, -bnt_w, bnt_w))
        scope.set(n, jnp.asarray((q * s / bnt_w).astype(w.dtype)))
        scales[n] = s

    # activations: insert scale-clamped quant-dequant ops before use
    from ..static.program import OpDesc

    bnt = float((1 << (activation_bits - 1)) - 1)
    new_ops = []
    renamed = {}
    for op in block.ops:
        if op.type in _QUANTIZABLE:
            new_inputs = {}
            for slot, names in op.inputs.items():
                out_names = []
                for n in names:
                    if n in scales and n not in weight_inputs:
                        if n not in renamed:
                            qn = program._unique_name(f"{n}.quantized")
                            src = block.var(n)
                            block.create_var(
                                name=qn, shape=src.shape,
                                dtype=str(src.dtype),
                            )
                            new_ops.append(OpDesc(
                                "quant_dequant_static",
                                {"X": [n]}, {"Out": [qn]},
                                {"scale": float(scales[n]),
                                 "bit_length": activation_bits},
                            ))
                            renamed[n] = qn
                        out_names.append(renamed[n])
                    else:
                        out_names.append(n)
                new_inputs[slot] = out_names
            op.inputs = new_inputs
        new_ops.append(op)
    block.ops[:] = new_ops
    program._version = getattr(program, "_version", 0) + 1
    return scales


class PostTrainingQuantization:
    """post_training_quantization.py facade over the pass above."""

    def __init__(self, executor, program, feed_batches, scope=None,
                 weight_bits=8, activation_bits=8):
        from ..static.executor import global_scope

        self._exe = executor
        self._program = program
        self._batches = list(feed_batches)
        self._scope = scope or global_scope()
        self._wbits = weight_bits
        self._abits = activation_bits
        self.scales = None

    def quantize(self):
        self.scales = quantize_static_program(
            self._program, self._scope, self._exe, self._batches,
            weight_bits=self._wbits, activation_bits=self._abits,
        )
        return self._program

    def save_quantized_model(self, dirname, feed_names, fetch_vars):
        from ..static import io as static_io

        return static_io.save_inference_model(
            dirname, feed_names, fetch_vars, self._exe,
            main_program=self._program,
        )
