"""Model-slimming: quantization (QAT + PTQ).

Reference parity: python/paddle/fluid/contrib/slim/quantization/ —
imperative/qat.py (ImperativeQuantAware), imperative/quant_nn.py
(QuantizedLinear/QuantizedConv2D), post_training_quantization.py, and
quantization_pass.py (static program rewrite).
"""
from .quant_nn import QuantizedConv2D, QuantizedLinear  # noqa: F401
from .qat import ImperativeQuantAware  # noqa: F401
from .ptq import (  # noqa: F401
    PostTrainingQuantization,
    load_quant_metadata,
    quantize_static_program,
    rewrite_int8_program,
)
