"""Imperative quantization-aware training.

Reference parity: fluid/contrib/slim/quantization/imperative/qat.py —
ImperativeQuantAware.quantize(model) swaps every Linear/Conv2D for its
quantized wrapper in place (training then runs with fake quant), and
save_quantized_model exports the inference program.
"""
from __future__ import annotations

from ..nn.layer_base import Layer
from ..nn.layers import Conv2D, Linear
from .quant_nn import QuantizedConv2D, QuantizedLinear

_DEFAULT_TYPES = (Linear, Conv2D)


class ImperativeQuantAware:
    """imperative/qat.py:ImperativeQuantAware."""

    def __init__(self, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 quantizable_layer_type=("Linear", "Conv2D")):
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self._types = tuple(
            t for t in _DEFAULT_TYPES
            if t.__name__ in set(quantizable_layer_type)
        )

    def _wrap(self, layer):
        if isinstance(layer, Linear):
            return QuantizedLinear(layer, self._wbits, self._abits,
                                   self._rate)
        return QuantizedConv2D(layer, self._wbits, self._abits, self._rate)

    def quantize(self, model: Layer):
        """Swap quantizable sublayers in place; returns the model."""
        for parent in [model] + [l for l in model.sublayers(True)]:
            subs = getattr(parent, "_sub_layers", None)
            if not subs:
                continue
            for name, child in list(subs.items()):
                if isinstance(child, self._types) and not isinstance(
                    child, (QuantizedLinear, QuantizedConv2D)
                ):
                    subs[name] = self._wrap(child)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        """Export with the quant-dequant ops baked in (jit trace path)."""
        from .. import jit_api

        return jit_api.save(model, path, input_spec=input_spec)
