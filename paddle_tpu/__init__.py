"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up reimplementation of the capabilities of the reference framework
(PaddlePaddle ≈2.0-beta, see /root/repo/SURVEY.md) designed for TPU:
eager + static graph execution lowered to XLA via JAX, mesh-based
distributed training over ICI/DCN collectives, bf16-first AMP, and pallas
kernels for hot ops.

Public API mirrors the paddle 2.0 namespace layout
(python/paddle/__init__.py in the reference).
"""
from __future__ import annotations

__version__ = "0.1.0"

from . import framework
from .framework import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Parameter,
    TPUPlace,
    Tensor,
    bfloat16,
    bool_,
    complex64,
    complex128,
    enable_grad,
    float16,
    float32,
    float64,
    get_device,
    grad,
    int8,
    int16,
    int32,
    int64,
    is_compiled_with_tpu,
    no_grad,
    seed,
    set_device,
    to_tensor,
    uint8,
)
from .framework.dtype import get_default_dtype, set_default_dtype  # noqa: F401

# Tensor/math API at top level (paddle.add, paddle.matmul, ...)
from .ops import (  # noqa: F401
    abs,
    accuracy,
    add,
    addmm,
    all,
    any,
    arange,
    argmax,
    argmin,
    argsort,
    asin,
    acos,
    atan,
    atan2,
    bernoulli,
    bitwise_and,
    bitwise_not,
    bitwise_or,
    bitwise_xor,
    bmm,
    broadcast_to,
    cast,
    ceil,
    chunk,
    clip,
    concat,
    cos,
    cosh,
    cross,
    cumsum,
    cumprod,
    diag,
    diag_embed,
    divide,
    dot,
    einsum,
    equal,
    erf,
    exp,
    expm1,
    expand,
    expand_as,
    eye,
    flatten,
    flip,
    floor,
    floor_divide,
    full,
    full_like,
    gather,
    gather_nd,
    greater_equal,
    greater_than,
    index_sample,
    index_select,
    inverse,
    isfinite,
    isinf,
    isnan,
    kthvalue,
    less_equal,
    less_than,
    linspace,
    log,
    log1p,
    log2,
    log10,
    logical_and,
    logical_not,
    logical_or,
    logical_xor,
    logsumexp,
    masked_select,
    matmul,
    max,
    maximum,
    mean,
    meshgrid,
    min,
    minimum,
    mod,
    multinomial,
    multiply,
    neg,
    normal,
    not_equal,
    numel,
    ones,
    ones_like,
    pow,
    prod,
    rand,
    randint,
    randn,
    randperm,
    reciprocal,
    remainder,
    repeat_interleave,
    reshape,
    roll,
    round,
    rsqrt,
    scale,
    scatter,
    scatter_nd_add,
    shard_index,
    sign,
    sin,
    sinh,
    slice,
    sort,
    split,
    sqrt,
    square,
    squeeze,
    stack,
    strided_slice,
    subtract,
    sum,
    t,
    take_along_axis,
    tan,
    tanh,
    tile,
    topk,
    transpose,
    tril,
    triu,
    trunc,
    unbind,
    uniform,
    unsqueeze,
    unstack,
    where,
    zeros,
    zeros_like,
)
from .ops import shape as shape  # noqa: F401
from .ops import sigmoid  # noqa: F401  (paddle.sigmoid, 2.0 top-level alias)

import paddle_tpu.ops as ops  # noqa: F401,E402
from . import amp  # noqa: E402
from . import io  # noqa: E402
from .framework.serialization import load, save  # noqa: E402
from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import parallel  # noqa: E402
from . import distributed  # noqa: E402
from .distributed import DataParallel  # noqa: E402  (dygraph DP wrapper)
from . import models  # noqa: E402
from . import static  # noqa: E402
from . import metric  # noqa: E402
from . import inference  # noqa: E402
from . import jit_api as jit  # noqa: E402  (paddle.jit.to_static/save/load)
from .hapi import Model  # noqa: E402
from .hapi.model import summary  # noqa: E402  (hapi/model_summary.py)
from . import device  # noqa: E402  (memory facade: paddle.device surface)
from . import vision  # noqa: E402
from . import text  # noqa: E402  (text datasets: imdb/imikolov/wmt/conll05)
from . import profiler  # noqa: E402
from . import monitor  # noqa: E402  (metrics registry + training monitor)
from . import serving  # noqa: E402  (online inference: batcher/replicas/HTTP)
from . import distribution  # noqa: E402
from . import errors  # noqa: E402  (platform/enforce.h error taxonomy)
from . import incubate  # noqa: E402  (auto-checkpoint)
from . import slim  # noqa: E402  (quantization: QAT + PTQ)
from . import tensor  # noqa: E402  (2.0 tensor-API namespace split)
from . import crypto  # noqa: E402  (encrypted model io, framework/io/crypto)
from . import linalg  # noqa: E402  (2.0 linalg namespace)
from .ops import (  # noqa: E402,F401  (2.0 tail additions, flat aliases)
    clone,
    diagflat,
    dist,
    empty,
    empty_like,
    increment,
    inner,
    is_complex,
    is_integer,
    multiplex,
    mv,
    outer,
    poisson,
    put_along_axis,
    rank,
    standard_normal,
    stanh,
)
from . import utils  # noqa: E402  (run_check, gated download)
from . import reader  # noqa: E402  (reader decorator library, paddle.reader)
from . import nets  # noqa: E402  (composite helpers, fluid/nets.py)
from . import flags as _flags_mod  # noqa: E402
from .flags import get_flags, set_flags  # noqa: E402  (core.globals() API)
