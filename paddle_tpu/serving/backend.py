"""Backend-process entrypoint: one serving process of the fleet.

``python -m paddle_tpu.serving.backend --model-dir DIR [--port 0] ...``
boots a full :class:`InferenceServer` (predictor -> batcher -> replica
pool -> HTTP frontend) over a ``jit.save``/``save_inference_model``
export, warms every bucket, then parks until SIGTERM/SIGINT — on which
it drains gracefully (queued work completes, then the listener closes)
and exits 0. This is the unit the router spreads traffic over and the
autoscaler's :class:`~paddle_tpu.serving.scaler.SubprocessLauncher`
boots and reaps.

Port discovery: with ``--port 0`` (the default — N backends on one host
must not fight over a port) the chosen port is announced through
``--port-file``: the file is written atomically (tmp + rename) AFTER the
server is constructed, so a launcher polling for it never reads a
half-written path or a port that isn't bound yet.

``--mesh-dp N`` serves a GSPMD-sharded backend: the predictor is wrapped
with :func:`~paddle_tpu.serving.sharded.shard_predictor` over an
N-device data-parallel mesh before the server boots (pair it with batch
buckets divisible by N so every hot-path batch actually splits).
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import tempfile
import threading

__all__ = ["main", "build_server"]


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.serving.backend",
        description="boot one serving backend process over a saved "
                    "inference model (predict) or a saved GPT "
                    "(generate / prefill / decode)")
    p.add_argument("--kind", default="predict",
                   choices=("predict", "generate", "prefill", "decode"),
                   help="backend role: predict serves /predict over "
                        "--model-dir; the generation kinds serve a "
                        "causal LM from --gpt-dir (prefill/decode are "
                        "the disaggregated tiers)")
    p.add_argument("--model-dir", default=None,
                   help="directory produced by jit.save / "
                        "save_inference_model (predict kind)")
    p.add_argument("--gpt-dir", default=None,
                   help="directory produced by models.save_gpt_model "
                        "(generation kinds)")
    p.add_argument("--draft-dir", default=None,
                   help="draft-model directory (save_gpt_model) — "
                        "enables speculative decoding on generate/"
                        "decode kinds when FLAGS_speculative_enabled "
                        "or --speculative is set")
    p.add_argument("--speculative", action="store_true",
                   help="enable speculative decoding (needs "
                        "--draft-dir)")
    p.add_argument("--draft-k", type=int, default=None,
                   help="proposals per speculative round (default: "
                        "FLAGS_speculative_draft_k)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 binds an ephemeral port (see --port-file)")
    p.add_argument("--port-file", default="",
                   help="file to write the bound port into (atomic; "
                        "written once the server is constructed)")
    p.add_argument("--replicas", type=int, default=None)
    p.add_argument("--buckets", default=None,
                   help="comma-separated batch bucket ladder override")
    p.add_argument("--queue-capacity", type=int, default=None)
    p.add_argument("--batch-timeout-ms", type=float, default=None)
    p.add_argument("--mesh-dp", type=int, default=0,
                   help="shard the backend over an N-device dp mesh "
                        "(0: unsharded)")
    # generation-engine knobs (generation kinds only)
    p.add_argument("--slots", type=int, default=None,
                   help="decode slots (generation kinds)")
    p.add_argument("--cache-len", type=int, default=None,
                   help="KV window (generation kinds)")
    p.add_argument("--prefill-buckets", default=None,
                   help="comma-separated prompt-length ladder "
                        "(generation kinds)")
    p.add_argument("--kv-cache-dtype", default=None,
                   help="float32 | int8 (generation kinds; handoff "
                        "tiers must match)")
    args = p.parse_args(argv)
    if args.kind == "predict" and not args.model_dir:
        p.error("--kind predict needs --model-dir")
    if args.kind != "predict" and not args.gpt_dir:
        p.error(f"--kind {args.kind} needs --gpt-dir")
    if args.speculative and not args.draft_dir:
        # silently booting a PLAIN engine here would leave the operator
        # believing speculation is on (only /statz would tell)
        p.error("--speculative needs --draft-dir")
    return args


def build_server(args):
    """Server for the requested kind, not yet started — split from
    :func:`main` so tests can drive it in-process. ``predict`` builds
    the Predictor/InferenceServer stack; the generation kinds build a
    GenerationEngine (optionally speculative) under a
    :class:`GenerationServer` whose role gates its routes and warmup
    program set."""
    if args.kind != "predict":
        return _build_generation_server(args)
    from ..inference import Config, create_predictor
    from .server import InferenceServer

    pred = create_predictor(Config(args.model_dir))
    if args.mesh_dp and args.mesh_dp > 1:
        import jax

        from ..parallel.mesh import MeshConfig, create_mesh
        from .sharded import shard_predictor

        mesh = create_mesh(MeshConfig(
            dp=args.mesh_dp, devices=jax.devices()[:args.mesh_dp]))
        pred = shard_predictor(pred, mesh=mesh)
    return InferenceServer(
        pred, port=args.port, host=args.host, replicas=args.replicas,
        buckets=args.buckets, queue_capacity=args.queue_capacity,
        batch_timeout_ms=args.batch_timeout_ms)


def _build_generation_server(args):
    from ..flags import flag
    from ..generation.engine import GenerationEngine
    from ..models.gpt import load_gpt_model
    from .server import GenerationServer

    model = load_gpt_model(args.gpt_dir)
    draft = None
    if args.draft_dir and (args.speculative
                           or flag("speculative_enabled")):
        draft = load_gpt_model(args.draft_dir)
    engine = GenerationEngine(
        model, slots=args.slots, cache_len=args.cache_len,
        prefill_buckets=args.prefill_buckets,
        kv_cache_dtype=args.kv_cache_dtype,
        draft_model=draft, draft_k=args.draft_k)
    return GenerationServer(
        engine, port=args.port, host=args.host, kind=args.kind,
        queue_capacity=args.queue_capacity)


def _announce_port(path, port):
    """Atomic write: the launcher polls for this file, so it must never
    observe a partial write."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".port_")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(str(port))
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def main(argv=None) -> int:
    args = _parse_args(argv)
    from ..analysis import MemoryBudgetError

    try:
        srv = build_server(args)
    except MemoryBudgetError as e:
        # the static capacity plan refuses a slots x cache-len x dtype
        # geometry that cannot fit the device HBM
        # (FLAGS_memory_budget_check=strict) — a clean boot-time
        # refusal naming the fitting geometry, not a traceback the
        # launcher has to grep out of an OOMed warmup
        print(f"backend refused: {e}", file=sys.stderr, flush=True)
        return 2
    srv.start(warmup=True)  # /healthz flips ready only after warmup
    # per-backend SLOs from FLAGS_slo_objectives (the launcher passes
    # the flag through the child env); no-op when empty
    from ..monitor import slo as _slo

    _slo.install_from_flags()
    if args.port_file:
        _announce_port(args.port_file, srv.port)
    print(f"serving backend ready on {srv.url} "
          f"(kind={args.kind}, "
          f"model={args.model_dir or args.gpt_dir}, "
          f"pid={os.getpid()})", flush=True)

    stop = threading.Event()

    def _on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    stop.wait()
    # graceful drain: admission refused (503 -> the router evicts us),
    # queued work flushes through the replicas, listener closes
    srv.stop(drain=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
