"""HTTP frontend for the online serving subsystem.

A stdlib ``ThreadingHTTPServer`` (same pattern as
``monitor/debug_server.py``: no web framework dependency, daemon serving
threads) exposing:

- ``POST /predict`` — JSON ``{"inputs": {feed: nested-list}, ...}``
  through the dynamic batcher; responds ``{"outputs": {fetch: ...}}``.
  Backpressure maps onto status codes instead of unbounded queueing:
  **429** queue full, **504** deadline expired, **400** malformed
  request, **503** draining/not ready.
- ``GET /healthz`` — READINESS, not liveness: 200 only once every batch
  bucket is compiled (warmup-complete) and the server is not draining;
  503 otherwise. Load balancers gate on this, so a replica never
  receives traffic it would stall on with an XLA compile.
- ``GET /statz`` — serving stats JSON: queue depth, bucket ladder,
  request/batch counters, batch fill, latency quantiles (p50/p99 from
  the stage histograms), compile accounting (warmup vs unexpected), and
  MFU from the cost-model ledger — the ``/clusterz``-style capacity
  math, extended to serving.
- ``GET /metrics`` — the Prometheus dump (every ``serving/*`` metric
  rides the same exporter the training stack uses).

``stop(drain=True)`` is a graceful drain: new work is refused (503),
queued work is flushed through the replicas, waiting HTTP handlers get
their real responses, then the listener closes.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..errors import InvalidArgumentError
from ..monitor import cost_model as _cost
from ..monitor import flight_recorder as _flight
from ..monitor import histogram_quantile, registry_snapshot
from .batcher import (
    DeadlineExceededError,
    DynamicBatcher,
    QueueFullError,
    ServingClosedError,
)
from .replica import ReplicaPool

__all__ = ["InferenceServer"]


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


class _ServingHandler(BaseHTTPRequestHandler):
    server_version = "ptpu-serving/1"

    def log_message(self, *args):  # no per-request stderr chatter
        pass

    @property
    def _srv(self):
        return self.server._inference_server

    def _reply(self, status, payload, ctype="application/json"):
        body = (payload if isinstance(payload, str)
                else json.dumps(payload, default=_json_default))
        data = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", f"{ctype}; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        srv = self._srv
        if path == "/healthz":
            ready = srv.ready
            self._reply(200 if ready else 503, srv.healthz())
        elif path == "/statz":
            self._reply(200, srv.statz())
        elif path == "/metrics":
            from ..monitor.export import (
                PROMETHEUS_CONTENT_TYPE,
                prometheus_text,
            )

            self._reply(200, prometheus_text(), PROMETHEUS_CONTENT_TYPE)
        elif path == "/":
            self._reply(200, {
                "service": "paddle_tpu serving",
                "routes": ["/predict (POST)", "/healthz", "/statz",
                           "/metrics"]})
        else:
            self._reply(404, {"error": f"unknown path {path!r}"})

    def do_POST(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        if path != "/predict":
            self._reply(404, {"error": f"unknown path {path!r}"})
            return
        srv = self._srv
        if not srv.ready:
            self._reply(503, {"error": "not ready"
                              if not srv.draining else "draining"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(body, dict):
                raise InvalidArgumentError(
                    "request body must be a JSON object with an "
                    '"inputs" key')
            inputs = self._parse_inputs(body)
            deadline_ms = body.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)  # "abc" -> 400, not 500
        except (ValueError, TypeError, InvalidArgumentError) as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            req = srv.batcher.submit(inputs, deadline_ms=deadline_ms)
        except QueueFullError as e:
            self._reply(429, {"error": str(e)})
            return
        except ServingClosedError as e:
            self._reply(503, {"error": str(e)})
            return
        except InvalidArgumentError as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            outs = req.wait(srv.request_timeout_s)
        except DeadlineExceededError as e:
            self._reply(504, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — a bad batch must answer
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._reply(200, {
            "outputs": {n: o.tolist()
                        for n, o in zip(srv.fetch_names, outs)},
            "rows": int(req.rows),
        })

    def _parse_inputs(self, body) -> dict:
        srv = self._srv
        raw = body.get("inputs")
        if raw is None:
            raise InvalidArgumentError('request body needs an "inputs" key')
        # single-input convenience: a bare nested list maps to the feed
        if not isinstance(raw, dict):
            if len(srv.feed_names) != 1:
                raise InvalidArgumentError(
                    f'"inputs" must be a dict naming the feeds '
                    f"{srv.feed_names}")
            raw = {srv.feed_names[0]: raw}
        parsed = {}
        for name, val in raw.items():
            spec = srv.input_specs.get(name)
            dtype = spec[1] if spec else None
            try:
                arr = np.asarray(val, dtype=dtype)
            except (ValueError, TypeError) as e:
                raise InvalidArgumentError(
                    f"input {name!r} is not a well-formed {dtype} "
                    f"array: {e}") from None
            parsed[name] = arr
        return parsed


class InferenceServer:
    """Composed serving stack: HTTP frontend -> DynamicBatcher ->
    ReplicaPool over one shared-executable Predictor.

    ``port=0`` binds an ephemeral port (tests, smoke). ``start()`` runs
    warmup by default so ``/healthz`` flips to ready only after every
    bucket is compiled; pass ``warmup=False`` and call :meth:`warmup`
    later to observe the readiness gate from outside.
    """

    def __init__(self, predictor, port=0, host="127.0.0.1", replicas=None,
                 buckets=None, queue_capacity=None, batch_timeout_ms=None,
                 request_timeout_s=60.0):
        self.feed_names = list(predictor.get_input_names())
        self.fetch_names = list(predictor.get_output_names())
        self.batcher = DynamicBatcher(
            self.feed_names, buckets=buckets,
            queue_capacity=queue_capacity,
            batch_timeout_ms=batch_timeout_ms)
        self.pool = ReplicaPool(predictor, self.batcher, replicas=replicas)
        self.input_specs = self.pool._specs
        self.request_timeout_s = request_timeout_s
        self._httpd = ThreadingHTTPServer((host, int(port)),
                                          _ServingHandler)
        self._httpd.daemon_threads = True
        self._httpd._inference_server = self
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None
        self._t0 = time.monotonic()
        # MFU baseline: the executed-work ledger is process-global (a
        # model.fit before model.serve leaves training FLOPs in it);
        # statz attributes only the delta since construction to serving
        self._flops0 = registry_snapshot().get(
            "cost/executed_flops", {}).get("value", 0.0)
        self.draining = False
        self._stopped = False
        from . import _register_live

        _register_live(self)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def ready(self) -> bool:
        return self.pool.warmed and not self.draining

    # -- lifecycle -----------------------------------------------------------

    def start(self, warmup=True):
        """Start replica workers and the HTTP listener; by default also
        warm every bucket so the server comes up ready."""
        self.pool.start()
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"ptpu-serving:{self.port}", daemon=True)
            self._thread.start()
        _flight.record_event(
            "serving_start", port=self.port,
            replicas=self.pool.replicas,
            buckets=list(self.batcher.buckets))
        if warmup:
            self.warmup()
        return self

    def warmup(self):
        self.pool.warmup()
        return self

    def stop(self, drain=True, timeout=10.0):
        """Graceful shutdown: refuse new work (healthz -> 503,
        /predict -> 503), flush queued work through the replicas when
        ``drain``, then close the listener."""
        if self._stopped:
            return
        self._stopped = True
        self.draining = True
        self.pool.stop(drain=drain, timeout=timeout)  # closes the batcher
        self._httpd.shutdown()
        self._httpd.server_close()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None
        _flight.record_event("serving_stop", port=self.port, drain=drain)

    # -- introspection payloads ---------------------------------------------

    def healthz(self) -> dict:
        return {
            "ready": self.ready,
            "warmed": self.pool.warmed,
            "draining": self.draining,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "buckets": list(self.batcher.buckets),
            "replicas": self.pool.replicas,
            "queue_depth": self.batcher.queue_depth(),
            "queue_capacity": self.batcher.queue_capacity,
        }

    def statz(self) -> dict:
        snap = registry_snapshot()

        def val(name):
            return snap.get(name, {}).get("value", 0)

        from ..monitor import all_metrics

        metrics = all_metrics()

        def quantiles(name):
            h = metrics.get(name)
            if h is None or h.kind != "histogram" or h.count == 0:
                return None
            return {"p50_ms": round(histogram_quantile(h, 0.5), 3),
                    "p99_ms": round(histogram_quantile(h, 0.99), 3),
                    "count": h.count}

        batches = val("serving/batches_total")
        slots = val("serving/batch_slots_total")
        rows = val("serving/batched_rows_total")
        out = {
            **self.healthz(),
            "requests": {
                "submitted": val("serving/requests_total"),
                "completed": val("serving/responses_total"),
                "rejected_429": val("serving/rejected_total"),
                "deadline_expired": val("serving/deadline_expired_total"),
                "errors": val("serving/errors_total"),
            },
            "batches": {
                "dispatched": batches,
                "rows": rows,
                "padded_rows": val("serving/padded_rows_total"),
                "mean_fill": round(rows / slots, 4) if slots else 0.0,
            },
            "latency": {
                "queue": quantiles("serving/queue_ms"),
                "assemble": quantiles("serving/assemble_ms"),
                "dispatch": quantiles("serving/dispatch_ms"),
                "e2e": quantiles("serving/e2e_ms"),
            },
            "compiles": {
                "buckets": len(self.batcher.buckets),
                "unexpected": val("serving/unexpected_compiles"),
            },
        }
        # capacity math from the cost-model ledger: the executor dispatches
        # every serving batch, so executed FLOPs accumulate there; over
        # server uptime that is average achieved FLOP/s -> MFU against the
        # device peak (the /clusterz denominator, extended to serving)
        uptime = max(time.monotonic() - self._t0, 1e-9)
        executed = val("cost/executed_flops") - self._flops0
        peaks = _cost.device_peaks()
        out["utilization"] = {
            "executed_flops": executed,
            "mfu_avg": round(_cost.mfu(executed / uptime, peaks), 6),
            "device_kind": peaks.get("kind"),
            "peaks_nominal": peaks.get("nominal"),
        }
        return out
