"""HTTP frontend for the online serving subsystem.

A stdlib ``ThreadingHTTPServer`` (same pattern as
``monitor/debug_server.py``: no web framework dependency, daemon serving
threads) exposing:

- ``POST /predict`` — JSON ``{"inputs": {feed: nested-list}, ...}``
  through the dynamic batcher; responds ``{"outputs": {fetch: ...}}``.
  Backpressure maps onto status codes instead of unbounded queueing:
  **429** queue full, **504** deadline expired, **400** malformed
  request, **503** draining/not ready.
- ``GET /healthz`` — READINESS, not liveness: 200 only once every batch
  bucket is compiled (warmup-complete) and the server is not draining;
  503 otherwise. Load balancers gate on this, so a replica never
  receives traffic it would stall on with an XLA compile.
- ``GET /statz`` — serving stats JSON: queue depth, bucket ladder,
  request/batch counters, batch fill, latency quantiles (p50/p99 from
  the stage histograms), compile accounting (warmup vs unexpected), and
  MFU from the cost-model ledger — the ``/clusterz``-style capacity
  math, extended to serving.
- ``GET /metrics`` — the Prometheus dump (every ``serving/*`` metric
  rides the same exporter the training stack uses).
- ``GET /profilez`` — per-op device-time profiles (monitor.opprof):
  replay-measured op table, attribution coverage, time-accuracy
  closure; ``?program=``/``?topk=`` views. Served by both server kinds.

``stop(drain=True)`` is a graceful drain: new work is refused (503),
queued work is flushed through the replicas, waiting HTTP handlers get
their real responses, then the listener closes.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..errors import InvalidArgumentError
from ..monitor import all_metrics, counter, gauge
from ..monitor import cost_model as _cost
from ..monitor import flight_recorder as _flight
from ..monitor import histogram_quantile, registry_snapshot
from ..monitor import tracing as _tracing
from .batcher import (
    DeadlineExceededError,
    DynamicBatcher,
    QueueFullError,
    ServingClosedError,
)
from .continuous import ContinuousBatcher
from .replica import ReplicaPool

__all__ = ["InferenceServer", "GenerationServer"]


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


#: The machine-oriented load-signal schema (``GET /loadz``) the router
#: tier scrapes instead of the human-oriented ``/statz`` blob. STABLE:
#: fields are only ever added, never renamed or removed, and additions
#: bump ``schema``. Every backend kind serves exactly these keys:
#:
#: - ``schema``      int   — schema version (currently 1)
#: - ``kind``        str   — "predict" | "generate" (routes the router
#:                            may send here)
#: - ``ready``       bool  — warmed AND not draining (admission works)
#: - ``draining``    bool  — shutdown in progress; admissions get 503
#: - ``queue_depth`` int   — requests waiting for a batch/slot
#: - ``queue_capacity`` int
#: - ``load``        float — queue_depth / queue_capacity (the p2c
#:                            comparison signal, normalized)
#: - ``mean_fill``   float|None — predict: batch-slot utilization
#: - ``slot_occupancy`` float|None — generate: busy decode slots ratio
#: - ``compiles``    {"expected": int, "unexpected": int,
#:                    "jit_misses": int} — per-process compile
#:                    accounting (the bench's per-backend assertion)
LOADZ_SCHEMA_VERSION = 1


def _histz_payload() -> dict:
    """``GET /histz``: raw snapshots (bounds + per-bucket counts + sum +
    count) of every ``serving/*`` histogram in this process — the
    machine-oriented feed for cross-backend quantile merging
    (``monitor.merge_histogram_snapshots`` on the router side). The
    human-oriented quantiles stay on ``/statz``."""
    return {
        "histograms": {
            name: m.snapshot() for name, m in all_metrics().items()
            if m.kind == "histogram" and name.startswith("serving/")
        },
    }


def _jit_misses() -> int:
    from ..profiler import counters as _pc

    return int(_pc().get("executor::jit_cache_miss", 0))


def _tuned_kernels() -> dict:
    """The /statz tuned-kernel table: every autotuned schedule active
    for THIS device kind (tuning cache entries) plus the tuner's
    dispatch counters — a reader sees which kernels run on measured
    geometry and which still ride the defaults."""
    from ..profiler import counters as _pc
    from ..tuning import tuned_table

    from ..flags import flag as _flag

    c = _pc()
    try:
        rows = tuned_table()
    except Exception:  # a broken tuning cache must not 500 /statz
        rows = []
    return {
        "mode": _flag("kernel_autotune"),
        "entries": rows,
        "counters": {
            "cache_hit": int(c.get("autotune::cache_hit", 0)),
            "cache_miss": int(c.get("autotune::cache_miss", 0)),
            "cache_reject": int(c.get("autotune::cache_reject", 0)),
            "searches": int(c.get("autotune::search", 0)),
        },
    }


def _ir_opt_stats() -> dict:
    """The /statz IR-optimizer table: per-pass rewrite totals from the
    program-IR optimizer (analysis.optimizer) plus its program-version
    cache counters — a reader sees which fusion/remat passes actually
    fired on the programs this process serves and whether steady-state
    dispatch is paying the pipeline or riding the cache."""
    from ..analysis.optimizer import optimizer_stats
    from ..flags import flag as _flag
    from ..profiler import counters as _pc

    c = _pc()
    try:
        passes = optimizer_stats()
    except Exception:  # a broken stats table must not 500 /statz
        passes = {}
    return {
        "level": _flag("ir_opt_level"),
        "passes": passes,
        "counters": {
            "cache_hit": int(c.get("ir_opt::cache_hit", 0)),
            "cache_miss": int(c.get("ir_opt::cache_miss", 0)),
        },
    }


def _opprof_stats() -> dict:
    """The /statz per-op profiler block: stored replay profiles + the
    top-K ops by measured device time (monitor.opprof) — a reader sees
    which ops actually dominate the programs this process serves, with
    the time-accuracy closure next to the predicted cost sheets."""
    from ..monitor import opprof as _opprof

    try:
        return _opprof.opprof_stats()
    except Exception:  # a broken profile store must not 500 /statz
        return {"programs": [], "latest": None, "top_ops": []}


def _stats_readers():
    """One registry snapshot + the counter/quantile readers both statz
    endpoints share (a change to the quantile fields must not have to be
    made twice)."""
    snap = registry_snapshot()
    metrics = all_metrics()

    def val(name):
        return snap.get(name, {}).get("value", 0)

    def quantiles(name):
        h = metrics.get(name)
        if h is None or h.kind != "histogram" or h.count == 0:
            return None
        return {"p50_ms": round(histogram_quantile(h, 0.5), 3),
                "p99_ms": round(histogram_quantile(h, 0.99), 3),
                "count": h.count}

    return val, quantiles


def _utilization(t0, flops0, val):
    """Capacity math from the cost-model ledger: the engine/executor
    dispatches every serving program, so executed FLOPs accumulate
    there; the delta since server construction over uptime is average
    achieved FLOP/s -> MFU against the device peak (the ``/clusterz``
    denominator, extended to serving). Returns (uptime_s, block)."""
    uptime = max(time.monotonic() - t0, 1e-9)
    executed = val("cost/executed_flops") - flops0
    peaks = _cost.device_peaks()
    # plan_accuracy: predicted-vs-actual peak HBM of the most recently
    # compiled statically-planned program (analysis.memory.note_actual);
    # 0 means no planned compile has closed the loop yet
    accuracy = val("memplan/plan_accuracy")
    return uptime, {
        "executed_flops": executed,
        "mfu_avg": round(_cost.mfu(executed / uptime, peaks), 6),
        "device_kind": peaks.get("kind"),
        "peaks_nominal": peaks.get("nominal"),
        "hbm_budget_bytes": peaks.get("hbm_bytes"),
        "plan_accuracy": round(accuracy, 4) if accuracy else None,
    }


def _utilization_window(state, val):
    """Windowed serving MFU/goodput: the executed-FLOPs delta over the
    wall since the PREVIOUS statz read (the stats window), published as
    the ``serving/mfu`` and ``serving/goodput_flops_per_s`` gauges so
    the fleet scrape (/metricz, /fleetz) sees utilization without
    redoing the ledger math. ``state`` is the server's mutable
    ``[t_last, flops_last]`` cell; returns the statz block (None until
    a full window has elapsed)."""
    now = time.monotonic()
    flops = val("cost/executed_flops")
    dt = now - state[0]
    block = None
    if dt > 1e-3:
        rate = max(0.0, flops - state[1]) / dt
        m = _cost.mfu(rate, _cost.device_peaks())
        gauge("serving/goodput_flops_per_s").set(round(rate, 3))
        gauge("serving/mfu").set(round(m, 6))
        block = {"window_s": round(dt, 3),
                 "goodput_flops_per_s": round(rate, 3),
                 "mfu": round(m, 6)}
        state[0] = now
        state[1] = flops
    return block


class ServingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a fleet-sized accept backlog. The
    stdlib default (request_queue_size=5) refuses connections under a
    burst of connection-per-request clients — which the router would
    read as a dead backend and evict. Refusals belong to the bounded
    ADMISSION queue (429), never to the TCP accept queue."""

    request_queue_size = 128
    daemon_threads = True


class _BaseHandler(BaseHTTPRequestHandler):
    """Shared plumbing for the serving frontends: JSON replies, silent
    request logging, and the introspection GET routes every server
    exposes (``/healthz`` readiness, ``/statz``, ``/metrics``).

    HTTP/1.1 across the board: every reply carries Content-Length (or
    chunked transfer encoding), so keep-alive is safe — and the fleet
    NEEDS it: connection-per-request across the client->router->backend
    hops costs a TCP handshake plus a handler-thread spawn per hop per
    request, which caps a fleet well below one backend's capacity."""

    server_version = "ptpu-serving/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # no per-request stderr chatter
        pass

    @property
    def _srv(self):
        return self.server._inference_server

    def _reply(self, status, payload, ctype="application/json"):
        # status lands on the current request span (>=500 marks the
        # trace errored, so the tail sampler keeps it); a no-op on the
        # untraced GET routes
        _tracing.note_status(status)
        body = (payload if isinstance(payload, str)
                else json.dumps(payload, default=_json_default))
        data = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", f"{ctype}; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _reply_raw(self, status, data: bytes, ctype):
        """Raw-bytes reply (proxied payloads, KV slabs): the caller
        owns the exact Content-Type; everything else matches
        :meth:`_reply`."""
        _tracing.note_status(status)
        self.send_response(status)
        self.send_header("Content-Type", ctype or "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _read_body(self):
        """Read (and thereby DRAIN) the POST body before any reply — an
        unread body left on a keep-alive connection parses as the next
        request line and poisons every later request on that socket.
        Returns the raw bytes, or ``None`` after answering 400 to a
        malformed Content-Length (the connection is closed then: with
        an unparseable length the body cannot be drained)."""
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except (TypeError, ValueError):
            self.close_connection = True
            self._reply(400, {"error": "malformed Content-Length"})
            return None
        return self.rfile.read(length) if length > 0 else b"{}"

    def _trace_request(self, name):
        """Open this request's local trace root. An incoming
        ``traceparent`` (the router's per-attempt span) parents this
        process's span tree under the caller's — one trace_id, correct
        parentage, across the process hop."""
        parent = _tracing.parse_traceparent(
            self.headers.get(_tracing.TRACEPARENT_HEADER))
        return _tracing.start_trace(name, parent=parent,
                                    client=self.client_address[0])

    def _try_submit(self, fn):
        """Run an admission call, mapping the shared backpressure
        contract onto statuses: full queue 429, draining/closed 503,
        malformed 400. Returns the submitted request, or ``None`` after
        replying with the error."""
        try:
            return fn()
        except QueueFullError as e:
            self._reply(429, {"error": str(e)})
        except ServingClosedError as e:
            self._reply(503, {"error": str(e)})
        except InvalidArgumentError as e:
            self._reply(400, {"error": str(e)})
        return None

    def _get_common(self, path) -> bool:
        """Serve the shared GET routes; True when handled."""
        srv = self._srv
        if path == "/healthz":
            self._reply(200 if srv.ready else 503, srv.healthz())
        elif path == "/statz":
            self._reply(200, srv.statz())
        elif path == "/loadz":
            self._reply(200, srv.loadz())
        elif path == "/histz":
            self._reply(200, _histz_payload())
        elif path == "/tracez":
            status, payload = _tracing.tracez_payload(
                _tracing.parse_query(self.path))
            self._reply(status, payload)
        elif path == "/profilez":
            from ..monitor import opprof as _opprof

            status, payload = _opprof.profilez_payload(
                _tracing.parse_query(self.path))
            self._reply(status, payload)
        elif path == "/metrics":
            from ..monitor.export import (
                PROMETHEUS_CONTENT_TYPE,
                prometheus_text,
            )

            self._reply(200, prometheus_text(), PROMETHEUS_CONTENT_TYPE)
        elif path == "/metricz":
            # the fleet scrape surface: prometheus text by default;
            # ?format=snapshot is the machine feed (labeled series
            # included) the router's prober merges into /fleetz
            if _tracing.parse_query(self.path).get("format") == "snapshot":
                self._reply(200, {"metrics": registry_snapshot()})
            else:
                from ..monitor.export import (
                    PROMETHEUS_CONTENT_TYPE,
                    prometheus_text,
                )

                self._reply_raw(200, prometheus_text().encode("utf-8"),
                                PROMETHEUS_CONTENT_TYPE)
        elif path == "/sloz":
            from ..monitor import slo as _slo

            self._reply(200, _slo.sloz_payload())
        else:
            return False
        return True


class _ServingHandler(_BaseHandler):
    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if self._get_common(path):
            return
        if path == "/":
            self._reply(200, {
                "service": "paddle_tpu serving",
                "routes": ["/predict (POST)", "/healthz", "/statz",
                           "/loadz", "/histz", "/tracez", "/profilez",
                           "/metrics", "/metricz", "/sloz"]})
        else:
            self._reply(404, {"error": f"unknown path {path!r}"})

    def do_POST(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        raw = self._read_body()
        if raw is None:
            return
        if path != "/predict":
            self._reply(404, {"error": f"unknown path {path!r}"})
            return
        # the request's local trace root: batcher/replica/executor spans
        # nest under it; exiting runs the tail-sampling retention
        with self._trace_request("serving::predict"):
            self._predict(raw)

    def _predict(self, raw):
        srv = self._srv
        if not srv.ready:
            self._reply(503, {"error": "not ready"
                              if not srv.draining else "draining"})
            return
        try:
            body = json.loads(raw or b"{}")
            if not isinstance(body, dict):
                raise InvalidArgumentError(
                    "request body must be a JSON object with an "
                    '"inputs" key')
            inputs = self._parse_inputs(body)
            deadline_ms = body.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)  # "abc" -> 400, not 500
            tenant = body.get("tenant")
        except (ValueError, TypeError, InvalidArgumentError) as e:
            self._reply(400, {"error": str(e)})
            return
        req = self._try_submit(
            lambda: srv.batcher.submit(inputs, deadline_ms=deadline_ms,
                                       tenant=tenant))
        if req is None:
            return
        _tracing.annotate(rows=int(req.rows))
        try:
            outs = req.wait(srv.request_timeout_s)
        except DeadlineExceededError as e:
            self._reply(504, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — a bad batch must answer
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._reply(200, {
            "outputs": {n: o.tolist()
                        for n, o in zip(srv.fetch_names, outs)},
            "rows": int(req.rows),
        })

    def _parse_inputs(self, body) -> dict:
        srv = self._srv
        raw = body.get("inputs")
        if raw is None:
            raise InvalidArgumentError('request body needs an "inputs" key')
        # single-input convenience: a bare nested list maps to the feed
        if not isinstance(raw, dict):
            if len(srv.feed_names) != 1:
                raise InvalidArgumentError(
                    f'"inputs" must be a dict naming the feeds '
                    f"{srv.feed_names}")
            raw = {srv.feed_names[0]: raw}
        parsed = {}
        for name, val in raw.items():
            spec = srv.input_specs.get(name)
            dtype = spec[1] if spec else None
            try:
                arr = np.asarray(val, dtype=dtype)
            except (ValueError, TypeError) as e:
                raise InvalidArgumentError(
                    f"input {name!r} is not a well-formed {dtype} "
                    f"array: {e}") from None
            parsed[name] = arr
        return parsed


class InferenceServer:
    """Composed serving stack: HTTP frontend -> DynamicBatcher ->
    ReplicaPool over one shared-executable Predictor.

    ``port=0`` binds an ephemeral port (tests, smoke). ``start()`` runs
    warmup by default so ``/healthz`` flips to ready only after every
    bucket is compiled; pass ``warmup=False`` and call :meth:`warmup`
    later to observe the readiness gate from outside.
    """

    def __init__(self, predictor, port=0, host="127.0.0.1", replicas=None,
                 buckets=None, queue_capacity=None, batch_timeout_ms=None,
                 request_timeout_s=60.0):
        self.feed_names = list(predictor.get_input_names())
        self.fetch_names = list(predictor.get_output_names())
        self.batcher = DynamicBatcher(
            self.feed_names, buckets=buckets,
            queue_capacity=queue_capacity,
            batch_timeout_ms=batch_timeout_ms)
        self.pool = ReplicaPool(predictor, self.batcher, replicas=replicas)
        self.input_specs = self.pool._specs
        self.request_timeout_s = request_timeout_s
        self._httpd = ServingHTTPServer((host, int(port)),
                                        _ServingHandler)
        self._httpd._inference_server = self
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None
        self._t0 = time.monotonic()
        # MFU baseline: the executed-work ledger is process-global (a
        # model.fit before model.serve leaves training FLOPs in it);
        # statz attributes only the delta since construction to serving
        self._flops0 = registry_snapshot().get(
            "cost/executed_flops", {}).get("value", 0.0)
        self._mfu_window = [self._t0, self._flops0]
        self.draining = False
        self._stopped = False
        from . import _register_live

        _register_live(self)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def ready(self) -> bool:
        return self.pool.warmed and not self.draining

    # -- lifecycle -----------------------------------------------------------

    def start(self, warmup=True):
        """Start replica workers and the HTTP listener; by default also
        warm every bucket so the server comes up ready."""
        self.pool.start()
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"ptpu-serving:{self.port}", daemon=True)
            self._thread.start()
        _flight.record_event(
            "serving_start", port=self.port,
            replicas=self.pool.replicas,
            buckets=list(self.batcher.buckets))
        if warmup:
            self.warmup()
        return self

    def warmup(self):
        self.pool.warmup()
        return self

    def stop(self, drain=True, timeout=10.0):
        """Graceful shutdown: refuse new work (healthz -> 503,
        /predict -> 503), flush queued work through the replicas when
        ``drain``, then close the listener."""
        if self._stopped:
            return
        self._stopped = True
        self.draining = True
        self.pool.stop(drain=drain, timeout=timeout)  # closes the batcher
        t = self._thread
        if t is not None and t.is_alive():
            # shutdown() blocks on an event only serve_forever() sets —
            # calling it on a never-started listener would hang forever
            self._httpd.shutdown()
        self._httpd.server_close()
        if t is not None:
            t.join(timeout=5)
        self._thread = None
        _flight.record_event("serving_stop", port=self.port, drain=drain)

    # -- introspection payloads ---------------------------------------------

    def healthz(self) -> dict:
        return {
            "ready": self.ready,
            "warmed": self.pool.warmed,
            "draining": self.draining,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "buckets": list(self.batcher.buckets),
            "replicas": self.pool.replicas,
            "queue_depth": self.batcher.queue_depth(),
            "queue_capacity": self.batcher.queue_capacity,
        }

    def loadz(self) -> dict:
        """The compact router-facing load signal (see
        :data:`LOADZ_SCHEMA_VERSION` for the schema contract). Direct
        counter reads only — no registry walk, cheap enough to scrape
        every probe interval."""
        rows = counter("serving/batched_rows_total").value
        slots = counter("serving/batch_slots_total").value
        depth = self.batcher.queue_depth()
        return {
            "schema": LOADZ_SCHEMA_VERSION,
            "kind": "predict",
            "ready": self.ready,
            "draining": self.draining,
            "queue_depth": depth,
            "queue_capacity": self.batcher.queue_capacity,
            "load": round(depth / self.batcher.queue_capacity, 4),
            "mean_fill": round(rows / slots, 4) if slots else None,
            "slot_occupancy": None,
            "compiles": {
                "expected": len(self.batcher.buckets),
                "unexpected": counter(
                    "serving/unexpected_compiles").value,
                "jit_misses": _jit_misses(),
            },
        }

    def statz(self) -> dict:
        val, quantiles = _stats_readers()
        batches = val("serving/batches_total")
        slots = val("serving/batch_slots_total")
        rows = val("serving/batched_rows_total")
        out = {
            **self.healthz(),
            "requests": {
                "submitted": val("serving/requests_total"),
                "completed": val("serving/responses_total"),
                "rejected_429": val("serving/rejected_total"),
                "deadline_expired": val("serving/deadline_expired_total"),
                "errors": val("serving/errors_total"),
            },
            "batches": {
                "dispatched": batches,
                "rows": rows,
                "padded_rows": val("serving/padded_rows_total"),
                "mean_fill": round(rows / slots, 4) if slots else 0.0,
            },
            "latency": {
                "queue": quantiles("serving/queue_ms"),
                "assemble": quantiles("serving/assemble_ms"),
                "dispatch": quantiles("serving/dispatch_ms"),
                "e2e": quantiles("serving/e2e_ms"),
            },
            "compiles": {
                "buckets": len(self.batcher.buckets),
                "unexpected": val("serving/unexpected_compiles"),
            },
            # top-5 end-to-end requests from the trace store: trace_id +
            # per-stage breakdown, the jump-off point to /tracez?id=...
            "slowest": _tracing.slowest_table(5, root_prefix="serving::"),
            # which pallas kernels run on autotuned geometry here
            "tuned_kernels": _tuned_kernels(),
            # which IR-optimizer passes rewrote the served programs
            "ir_opt": _ir_opt_stats(),
            # per-op replay profiles + top-K ops by measured device time
            "opprof": _opprof_stats(),
        }
        _, out["utilization"] = _utilization(self._t0, self._flops0, val)
        out["utilization"]["window"] = _utilization_window(
            self._mfu_window, val)
        return out


# ---------------------------------------------------------------------------
# generative inference frontend
# ---------------------------------------------------------------------------


#: POST route each generation backend kind answers (the disaggregation
#: contract: a prefill tier only prefills, a decode tier only continues
#: handed-off slabs — anything else 404s, which the router's kind-aware
#: pick treats as "re-pick", never "fail the request")
_KIND_ROUTES = {"generate": "/generate", "prefill": "/prefill",
                "decode": "/generate_kv"}


class _GenerationHandler(_BaseHandler):
    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if self._get_common(path):
            return
        if path == "/":
            self._reply(200, {
                "service": "paddle_tpu generation",
                "kind": self._srv.kind,
                "routes": [f"{_KIND_ROUTES[self._srv.kind]} (POST)",
                           "/healthz", "/statz", "/loadz", "/histz",
                           "/tracez", "/profilez", "/metrics",
                           "/metricz", "/sloz"]})
        else:
            self._reply(404, {"error": f"unknown path {path!r}"})

    def do_POST(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        raw = self._read_body()
        if raw is None:
            return
        if path == "/prefix_known":
            # prefix-cache peer negotiation: a prefill tier (via the
            # router) asks which page chain-hashes this backend's index
            # already holds, then ships only the rest header-only
            self._prefix_known(raw)
            return
        if path != _KIND_ROUTES[self._srv.kind]:
            self._reply(404, {
                "error": f"unknown path {path!r} (this backend's kind "
                         f"is {self._srv.kind!r})"})
            return
        if path == "/generate":
            with self._trace_request("serving::generate"):
                self._generate(raw)
        elif path == "/prefill":
            with self._trace_request("serving::prefill"):
                self._prefill(raw)
        else:
            with self._trace_request("serving::generate_kv"):
                self._generate_kv(raw)

    def _prefix_known(self, raw):
        """``POST /prefix_known`` ``{"hashes": [...]}``: the subset (as
        a prefix chain) this backend's page index holds. Ring layouts
        answer an empty set — every page must ship."""
        try:
            body = json.loads(raw or b"{}")
            hashes = [str(h) for h in (body.get("hashes") or [])]
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": f"malformed body: {e}"})
            return
        known = self._srv.engine.known_page_hashes(hashes)
        self._reply(200, {"known": sorted(known),
                          "layout": self._srv.engine.kv_cache_layout})

    @staticmethod
    def _parse_gen_body(raw) -> dict:
        """Parse/validate the ``/generate`` (and ``/prefill``) JSON
        body into its parameters; raises on malformed input (mapped to
        400 by the callers)."""
        body = json.loads(raw or b"{}")
        if not isinstance(body, dict):
            raise InvalidArgumentError(
                'request body must be a JSON object with a "prompt" key')
        prompt = body.get("prompt")
        if (not isinstance(prompt, (list, tuple)) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            raise InvalidArgumentError(
                '"prompt" must be a non-empty list of token ids (ints)')
        max_new = body.get("max_new_tokens")
        temperature = body.get("temperature")
        deadline_ms = body.get("deadline_ms")
        return {
            "prompt": list(prompt),
            "max_new_tokens": int(max_new) if max_new is not None
            else None,
            "temperature": float(temperature)
            if temperature is not None else None,
            "deadline_ms": float(deadline_ms)
            if deadline_ms is not None else None,
            "stream": bool(body.get("stream", False)),
            # tenant dimension for the labeled serving histograms (the
            # cardinality bound makes a hostile value cost one series)
            "tenant": str(body["tenant"])
            if body.get("tenant") is not None else None,
        }

    def _check_ready(self, srv) -> bool:
        if not srv.ready:
            self._reply(503, {"error": "not ready"
                              if not srv.draining else "draining"})
            return False
        return True

    def _wait_and_reply(self, srv, req):
        """Block on a submitted request and answer with the standard
        non-streamed payload / error mapping."""
        try:
            tokens = req.wait(srv.request_timeout_s)
        except DeadlineExceededError as e:
            self._reply(504, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — a failed step must answer
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._reply(200, {
            "tokens": tokens,
            "finish_reason": req.finish_reason,
            "prompt_tokens": req.prompt_len,
        })

    def _generate(self, raw):
        srv = self._srv
        if not self._check_ready(srv):
            return
        try:
            p = self._parse_gen_body(raw)
        except (ValueError, TypeError, InvalidArgumentError) as e:
            self._reply(400, {"error": str(e)})
            return
        _tracing.annotate(prompt_tokens=len(p["prompt"]),
                          stream=p["stream"])
        submit = lambda **kw: srv.scheduler.submit(  # noqa: E731
            p["prompt"], max_new_tokens=p["max_new_tokens"],
            temperature=p["temperature"], deadline_ms=p["deadline_ms"],
            tenant=p["tenant"], **kw)
        if p["stream"]:
            self._generate_stream(srv, submit)
            return
        req = self._try_submit(submit)
        if req is None:
            return
        self._wait_and_reply(srv, req)

    def _prefill(self, raw):
        """Prefill-tier leg of a disaggregated ``/generate``: run the
        bucket-ladder forward, sample the first token, and answer with
        the slot's KV slab (``generation.handoff`` wire format). The
        original request's generation parameters — and the prompt
        itself, which a speculative decode tier needs — ride in the
        slab header, so the router can forward bytes without
        re-parsing anything.

        A paged prefill tier answers PAGE-GRANULAR (``PTKP``) when the
        body asks with ``"page_format": true``; ``"known_hashes"`` (the
        decode tier's ``known_page_hashes`` answer, forwarded by the
        router) lets it ship header-only entries for pages the far side
        already holds — the prefix-cache wire saving."""
        from ..generation.handoff import (
            HANDOFF_CONTENT_TYPE,
            HANDOFF_PAGED_CONTENT_TYPE,
            pack_kv_pages,
            pack_kv_slab,
        )

        srv = self._srv
        if not self._check_ready(srv):
            return
        try:
            p = self._parse_gen_body(raw)
            body = json.loads(raw or b"{}")
            page_format = bool(body.get("page_format", False))
            known_hashes = [str(h) for h in
                            (body.get("known_hashes") or [])]
            if page_format and not srv.engine.paged:
                raise InvalidArgumentError(
                    "page_format needs kv_cache_layout=paged on the "
                    "prefill tier")
            srv.engine.validate(
                p["prompt"],
                p["max_new_tokens"]
                if p["max_new_tokens"] is not None
                else srv.engine.default_max_new_tokens)
        except (ValueError, TypeError, InvalidArgumentError) as e:
            self._reply(400, {"error": str(e)})
            return
        _tracing.annotate(prompt_tokens=len(p["prompt"]), prefill=True,
                          page_format=page_format)
        meta = {
            "params": {k: p[k] for k in
                       ("prompt", "max_new_tokens", "temperature",
                        "deadline_ms", "stream", "tenant")},
            "cache": srv.cache_geometry(),
        }
        try:
            if page_format:
                pages, length, first = srv.run_prefill_pages(
                    p["prompt"], p["temperature"],
                    known_hashes=known_hashes)
                blob = pack_kv_pages(pages, length, first,
                                     srv.engine.page_size, meta=meta)
                ctype = HANDOFF_PAGED_CONTENT_TYPE
            else:
                planes, length, first = srv.run_prefill(
                    p["prompt"], p["temperature"])
                blob = pack_kv_slab(planes, length, first, meta=meta)
                ctype = HANDOFF_CONTENT_TYPE
        except ServingClosedError as e:
            self._reply(503, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — a failed forward must answer
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._reply_raw(200, blob, ctype)

    def _generate_kv(self, raw):
        """Decode-tier leg: land a handed-off KV slab in a decode slot
        and continue the generation — the slab's riding parameters
        reconstruct the original request (including streaming). Both
        wire formats land here, told apart by magic: ``PTKV``
        (contiguous slab) and ``PTKP`` (page-granular, paged tiers
        only)."""
        from ..generation.handoff import (
            HandoffError,
            unpack_kv_pages,
            unpack_kv_slab,
        )

        srv = self._srv
        if not self._check_ready(srv):
            return
        paged_wire = raw[:4] == b"PTKP"
        try:
            if paged_wire:
                if not srv.engine.paged:
                    raise HandoffError(
                        "page-granular slab needs kv_cache_layout=paged "
                        "on this decode tier (ring tiers speak PTKV)")
                slab = unpack_kv_pages(raw)
                length, meta = slab.length, slab.meta
                if slab.page_size != srv.engine.page_size:
                    raise HandoffError(
                        f"KV page slab page_size {slab.page_size} does "
                        f"not match this tier's {srv.engine.page_size}")
            else:
                planes, length, first, meta = unpack_kv_slab(raw)
            mine = srv.cache_geometry()
            theirs = meta.get("cache") or {}
            bad = {k: (theirs.get(k), mine[k]) for k in mine
                   if theirs.get(k) != mine[k]}
            if bad:
                raise HandoffError(
                    f"KV slab geometry does not match this decode tier: "
                    f"{bad} (sender vs receiver)")
            if srv.engine.speculative:
                # a speculative decode tier re-prefills the DRAFT from
                # the prompt at admission, which needs a covering
                # bucket on THIS tier's ladder — reject now as the 400
                # the handoff promises, not a 500 out of the decode
                # loop after a prefill-tier forward was already spent
                srv.engine.bucket_for(length)
        except (HandoffError, InvalidArgumentError) as e:
            self._reply(400, {"error": str(e)})
            return
        p = dict(meta.get("params") or {})
        stream = bool(p.get("stream", False))
        _tracing.annotate(prompt_tokens=length, handoff=True,
                          stream=stream, page_granular=paged_wire)
        if paged_wire:
            submit = lambda **kw: srv.scheduler.submit_prefilled_pages(  # noqa: E731,E501
                slab,
                max_new_tokens=p.get("max_new_tokens"),
                temperature=p.get("temperature"),
                deadline_ms=p.get("deadline_ms"),
                prompt=p.get("prompt"), tenant=p.get("tenant"), **kw)
        else:
            submit = lambda **kw: srv.scheduler.submit_prefilled(  # noqa: E731,E501
                planes, length, first,
                max_new_tokens=p.get("max_new_tokens"),
                temperature=p.get("temperature"),
                deadline_ms=p.get("deadline_ms"),
                prompt=p.get("prompt"), tenant=p.get("tenant"), **kw)
        if stream:
            self._generate_stream(srv, submit)
            return
        req = self._try_submit(submit)
        if req is None:
            return
        self._wait_and_reply(srv, req)

    def _generate_stream(self, srv, submit):
        """Chunked ndjson streaming: one ``{"token": id}`` line per
        decoded token as it is produced, then a final ``{"done": ...}``
        line with the full result — the scheduler's ``on_token`` hook
        feeding an HTTP chunk per decode step. ``submit`` is the
        parameter-bound scheduler call (plain or handed-off)."""
        import queue as _queue

        q = _queue.Queue()
        req = self._try_submit(lambda: submit(on_token=q.put))
        if req is None:
            return
        # the chunked path bypasses _reply — record the status here
        _tracing.note_status(200)
        self.send_response(200)
        self.send_header("Content-Type",
                         "application/x-ndjson; charset=utf-8")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(obj):
            data = (json.dumps(obj, default=_json_default) + "\n").encode()
            self.wfile.write(f"{len(data):x}\r\n".encode()
                             + data + b"\r\n")

        t_end = time.monotonic() + srv.request_timeout_s
        try:
            while True:
                try:
                    chunk({"token": q.get(timeout=0.1)})
                    continue
                except _queue.Empty:
                    pass
                if req.finished or time.monotonic() > t_end:
                    break
            while not q.empty():  # tokens landed between poll and finish
                chunk({"token": q.get_nowait()})
            if req.error is not None:
                # the 200 status line is long gone: mark the trace
                # errored so the tail sampler keeps this stream
                sp = _tracing.current_span()
                if sp is not None:
                    sp.set_error(f"{type(req.error).__name__}: "
                                 f"{req.error}")
                chunk({"error": f"{type(req.error).__name__}: "
                                f"{req.error}"})
            elif not req.finished:
                sp = _tracing.current_span()
                if sp is not None:
                    sp.set_error("stream timeout")
                _tracing.flag_current_trace("timeout")
                chunk({"error": "stream timeout"})
            else:
                chunk({"done": True, "tokens": req.tokens,
                       "finish_reason": req.finish_reason,
                       "prompt_tokens": req.prompt_len})
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; decoding continues
        finally:
            # every exit abandons the local queue — a still-decoding
            # request must stop feeding it (timeout/error paths would
            # otherwise accumulate every remaining token unread)
            req.on_token = None


class GenerationServer:
    """Composed generative-serving stack: HTTP frontend ->
    ContinuousBatcher (slot scheduler) -> GenerationEngine over a causal
    LM.

    ``model_or_engine`` is either a ready :class:`GenerationEngine` or a
    causal LM (``GPTForCausalLM``-shaped), in which case an engine is
    built from the ``generation_*`` flags / keyword overrides. As with
    :class:`InferenceServer`, ``start()`` warms by default so
    ``/healthz`` readiness means every prefill bucket AND the decode
    step are compiled.

    ``kind`` is the backend's role in a (possibly disaggregated) fleet
    — ``generate`` serves ``/generate`` end to end; ``prefill`` runs
    only the bucket-ladder forward and ships KV slabs (``/prefill``);
    ``decode`` admits handed-off slabs into decode slots
    (``/generate_kv``). Each kind warms exactly its own program set
    (``engine.expected_compiles(kind)``) and reports its kind on
    ``/loadz`` so the router can route and the autoscaler can size the
    tiers independently.
    """

    def __init__(self, model_or_engine, port=0, host="127.0.0.1",
                 slots=None, cache_len=None, prefill_buckets=None,
                 queue_capacity=None, max_new_tokens=None,
                 temperature=None, top_k=None, kv_cache_dtype=None,
                 draft_model=None, draft_k=None, kind=None,
                 request_timeout_s=120.0):
        from ..flags import flag as _flag

        self.kind = str(kind if kind is not None else _flag("backend_kind"))
        if self.kind not in _KIND_ROUTES:
            raise InvalidArgumentError(
                f"backend kind must be one of {sorted(_KIND_ROUTES)}, "
                f"got {self.kind!r}")
        if hasattr(model_or_engine, "step") and hasattr(
                model_or_engine, "admit"):
            dropped = {
                "slots": slots, "cache_len": cache_len,
                "prefill_buckets": prefill_buckets,
                "max_new_tokens": max_new_tokens,
                "temperature": temperature, "top_k": top_k,
                "kv_cache_dtype": kv_cache_dtype,
                "draft_model": draft_model, "draft_k": draft_k,
            }
            bad = sorted(k for k, v in dropped.items() if v is not None)
            if bad:
                raise InvalidArgumentError(
                    f"GenerationServer got a ready engine AND engine-"
                    f"construction kwargs {bad}; configure them on the "
                    "engine, or pass the model instead")
            self.engine = model_or_engine
        else:
            from ..generation.engine import GenerationEngine

            self.engine = GenerationEngine(
                model_or_engine, slots=slots, cache_len=cache_len,
                prefill_buckets=prefill_buckets,
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, kv_cache_dtype=kv_cache_dtype,
                draft_model=draft_model, draft_k=draft_k)
        self.scheduler = ContinuousBatcher(
            self.engine, queue_capacity=queue_capacity, kind=self.kind)
        # prefill tier: prefill_export mutates no cache state, so
        # handler threads run a few forwards CONCURRENTLY (XLA overlaps
        # one dispatch's compute with the next one's host prep) behind
        # a bounded semaphore; the waiter count is the tier's /loadz
        # queue-depth pressure (what the autoscaler sizes on)
        self._prefill_concurrency = 4
        self._prefill_sem = threading.BoundedSemaphore(
            self._prefill_concurrency)
        # waiter count mutated by concurrent handler threads: the +=/-=
        # read-modify-write needs a guard or the /loadz gauge the tier
        # autoscaler sizes on drifts permanently
        self._prefill_count_lock = threading.Lock()
        self._prefill_waiting = 0
        self._prefill_active = 0
        self.request_timeout_s = request_timeout_s
        self._httpd = ServingHTTPServer((host, int(port)),
                                        _GenerationHandler)
        self._httpd._inference_server = self
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None
        self._t0 = time.monotonic()
        snap = registry_snapshot()
        self._flops0 = snap.get(
            "cost/executed_flops", {}).get("value", 0.0)
        self._mfu_window = [self._t0, self._flops0]
        self._tokens0 = snap.get(
            "serving/gen_tokens_total", {}).get("value", 0)
        self.draining = False
        self._stopped = False
        from . import _register_live

        _register_live(self)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def ready(self) -> bool:
        return self.engine.warmed and not self.draining

    # -- lifecycle -----------------------------------------------------------

    def start(self, warmup=True):
        if self.kind != "prefill":
            # a prefill tier never decodes: no slot scheduler loop —
            # its engine runs synchronously under the prefill lock
            self.scheduler.start()
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"ptpu-generation:{self.port}", daemon=True)
            self._thread.start()
        _flight.record_event(
            "generation_server_start", port=self.port,
            backend_kind=self.kind, slots=self.engine.slots,
            prefill_buckets=list(self.engine.prefill_buckets),
            cache_len=self.engine.cache_len,
            speculative=self.engine.speculative)
        if warmup:
            self.warmup()
        return self

    def warmup(self):
        self.engine.warmup(kind=self.kind)
        return self

    def run_prefill(self, prompt, temperature=None):
        """Bounded-concurrency prefill-tier forward (the waiter count
        is this tier's /loadz pressure)."""
        if self.draining:
            raise ServingClosedError("prefill backend draining")
        with self._prefill_count_lock:
            self._prefill_waiting += 1
        acquired = False
        try:
            with self._prefill_sem:
                # holding a slot is utilization, not backlog: move out
                # of the waiter count so queue_depth means QUEUED (the
                # decode tier's semantics — a tier at full concurrency
                # with nothing waiting must not read as backlogged)
                with self._prefill_count_lock:
                    self._prefill_waiting -= 1
                    self._prefill_active += 1
                    acquired = True
                return self.engine.prefill_export(prompt, temperature)
        finally:
            with self._prefill_count_lock:
                if acquired:
                    self._prefill_active -= 1
                else:
                    self._prefill_waiting -= 1

    def run_prefill_pages(self, prompt, temperature=None,
                          known_hashes=()):
        """Page-granular :meth:`run_prefill`: same bounded-concurrency
        forward, answered as content-hashed pages with the ones in
        ``known_hashes`` shipped header-only."""
        if self.draining:
            raise ServingClosedError("prefill backend draining")
        with self._prefill_count_lock:
            self._prefill_waiting += 1
        acquired = False
        try:
            with self._prefill_sem:
                with self._prefill_count_lock:
                    self._prefill_waiting -= 1
                    self._prefill_active += 1
                    acquired = True
                return self.engine.prefill_export_pages(
                    prompt, temperature, known_hashes=known_hashes)
        finally:
            with self._prefill_count_lock:
                if acquired:
                    self._prefill_active -= 1
                else:
                    self._prefill_waiting -= 1

    def _suggested_slots(self):
        """Decode slots the device HBM budget would fit at this
        geometry, or None when the budget is unknown (statz field)."""
        try:
            return self.engine.suggest_decode_slots()
        except Exception:
            return None

    def cache_geometry(self) -> dict:
        """The slab-compatibility contract both handoff tiers must
        agree on — checked before any insert."""
        e = self.engine
        return {
            "layers": e._num_layers, "heads": e._num_heads,
            "head_dim": e._head_dim, "cache_len": e.cache_len,
            "kv_dtype": e.kv_cache_dtype,
        }

    def stop(self, drain=True, timeout=30.0):
        if self._stopped:
            return
        self._stopped = True
        self.draining = True
        self.scheduler.stop(drain=drain, timeout=timeout)
        t = self._thread
        if t is not None and t.is_alive():
            # shutdown() blocks on an event only serve_forever() sets —
            # calling it on a never-started listener would hang forever
            self._httpd.shutdown()
        self._httpd.server_close()
        if t is not None:
            t.join(timeout=5)
        self._thread = None
        _flight.record_event("generation_server_stop", port=self.port,
                             drain=drain)

    # -- introspection payloads ---------------------------------------------

    def healthz(self) -> dict:
        return {
            "ready": self.ready,
            "kind": self.kind,
            "warmed": self.engine.warmed,
            "draining": self.draining,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "slots": self.engine.slots,
            "slots_busy": self.scheduler.live_slots,
            "cache_len": self.engine.cache_len,
            "kv_cache_layout": self.engine.kv_cache_layout,
            "prefill_buckets": list(self.engine.prefill_buckets),
            "queue_depth": self.scheduler.queue_depth(),
            "queue_capacity": self.scheduler.queue_capacity,
        }

    def loadz(self) -> dict:
        """Router-facing load signal; same stable schema as the predict
        server's (``mean_fill`` is the predict-side field, decode-slot
        occupancy is the generation analog). The ``kind`` field routes
        a disaggregated fleet: prefill tiers report their serialized-
        forward waiter count as queue depth (compute pressure), decode
        tiers the slot queue (HBM pressure) — each tier's autoscaler
        sizes on its own signal."""
        if self.kind == "prefill":
            depth = self._prefill_waiting
            occupancy = round(
                self._prefill_active / self._prefill_concurrency, 4)
        else:
            depth = self.scheduler.queue_depth()
            occupancy = round(self.scheduler.occupancy(), 4)
        return {
            "schema": LOADZ_SCHEMA_VERSION,
            "kind": self.kind,
            "ready": self.ready,
            "draining": self.draining,
            "queue_depth": depth,
            "queue_capacity": self.scheduler.queue_capacity,
            "load": round(depth / self.scheduler.queue_capacity, 4),
            "mean_fill": None,
            "slot_occupancy": occupancy,
            "compiles": {
                "expected": self.engine.expected_compiles(self.kind),
                "unexpected": counter(
                    "serving/gen_unexpected_compiles").value,
                "jit_misses": _jit_misses(),
            },
        }

    def statz(self) -> dict:
        val, quantiles = _stats_readers()
        uptime, utilization = _utilization(self._t0, self._flops0, val)
        utilization["window"] = _utilization_window(self._mfu_window, val)
        tokens = val("serving/gen_tokens_total") - self._tokens0
        out = {
            **self.healthz(),
            "requests": {
                "submitted": val("serving/gen_requests_total"),
                "completed": val("serving/gen_responses_total"),
                "rejected_429": val("serving/gen_rejected_total"),
                "deadline_expired": val("serving/gen_expired_total"),
                "errors": val("serving/gen_errors_total"),
            },
            "generation": {
                "tokens_generated": tokens,
                "tokens_per_sec": round(tokens / uptime, 3),
                "slot_occupancy": round(self.scheduler.occupancy(), 4),
                "midbatch_admissions": val(
                    "serving/gen_midbatch_admissions_total"),
                # KV-cache economics: what decode capacity costs in HBM
                # (int8 mode ~4x fewer bytes/token -> ~2x the slots at
                # equal HBM; FLAGS_generation_kv_cache_dtype)
                "kv_cache_dtype": self.engine.kv_cache_dtype,
                "kv_bytes_per_token": self.engine.kv_bytes_per_token(),
                "kv_cache_bytes": self.engine.cache_nbytes(),
                # static capacity plan: what the geometry needs vs what
                # the device offers, and the slots the budget would fit
                # (analysis/memory + engine.suggest_decode_slots)
                "hbm_required_bytes": self.engine.hbm_required_bytes(),
                "suggested_decode_slots": self._suggested_slots(),
            },
            # speculative decoding economics: proposals accepted per
            # round decide how many full-model dispatches each token
            # costs (acceptance_rate * k + 1 tokens per verify)
            "speculative": self.engine.spec_stats(),
            # paged-KV economics: pool occupancy, CoW traffic, and the
            # prefix index's hit accounting, global + per tenant
            # (layout "ring" reports just the layout name)
            "paging": self.engine.paging_stats(),
            "latency": {
                "token": quantiles("serving/gen_token_ms"),
                "ttft": quantiles("serving/gen_ttft_ms"),
                "e2e": quantiles("serving/gen_e2e_ms"),
            },
            "compiles": {
                "prefill_buckets": len(self.engine.prefill_buckets),
                "decode": 2 if self.engine.speculative else 1,
                "expected": self.engine.expected_compiles(self.kind),
                "unexpected": val("serving/gen_unexpected_compiles"),
            },
            "slowest": _tracing.slowest_table(5, root_prefix="serving::"),
            "utilization": utilization,
            # which pallas kernels run on autotuned geometry here
            "tuned_kernels": _tuned_kernels(),
            # which IR-optimizer passes rewrote the served programs
            "ir_opt": _ir_opt_stats(),
            # per-op replay profiles + top-K ops by measured device time
            "opprof": _opprof_stats(),
        }
        return out
