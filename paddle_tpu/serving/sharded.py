"""GSPMD-sharded serving: one logical backend spanning a device mesh.

``inference.Predictor`` compiles through the static executor's jax.jit
path, and jax.jit's partitioner follows its INPUT shardings: commit the
loaded weights to a mesh with :class:`parallel.ShardingRules`
PartitionSpecs and stage the feeds as batch-sharded arrays, and the very
same compiled program becomes a GSPMD program — XLA inserts the
collectives, the executor's plan/jit caches, donation discipline, and
cost capture are untouched. That is the whole trick: sharding is
threaded through the predictor as *array placement*, not as a second
compile path.

Placement rules:

- **weights** (scope-resident persistables of the inference program)
  are ``device_put`` once at wrap time with the rule table's clamped
  spec — unmatched parameters replicate (pure data parallelism), a
  megatron-style table shards them tensor-parallel;
- **feeds** are staged batch-sharded over ``data_axis`` when the row
  count divides the axis size, replicated otherwise (odd direct calls
  stay correct; the serving bucket ladder should be chosen divisible so
  the hot path always splits);
- everything else (rng keys, executor-synthesized constants) is
  uncommitted and follows the computation onto the mesh.

``ShardedPredictor.clone()`` preserves the replica-pool contract: clones
share the Executor (one compiled-program cache) and the already-sharded
scope weights, so an ``InferenceServer`` over a sharded predictor is a
*sharded backend* — N worker threads dispatching one multi-device
program. Parity with the unsharded predictor is golden-tested on a
2-device CPU mesh (tests/test_sharded_serving.py).
"""
from __future__ import annotations

import numpy as np

import jax

from ..errors import InvalidArgumentError, PreconditionNotMetError
from ..inference.predictor import Predictor
from ..monitor import flight_recorder as _flight
from ..parallel.mesh import get_mesh
from ..parallel.sharding import DEFAULT_RULES, named_sharding
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ShardedPredictor", "shard_predictor"]


def _persistable_names(program):
    """Names of the program's scope-resident parameters (every var the
    inference program reads from the scope rather than the feed)."""
    block = program.global_block()
    return [name for name, v in block.vars.items()
            if getattr(v, "persistable", False)]


class ShardedPredictor(Predictor):
    """A :class:`Predictor` whose compiled program is GSPMD-partitioned
    over a mesh. Build one with :func:`shard_predictor`; construction
    from a Config directly is intentionally unsupported (the wrap point
    is explicit so the weight re-placement is visible at the call site).
    """

    def __init__(self, *a, **k):  # pragma: no cover - guarded API
        raise InvalidArgumentError(
            "ShardedPredictor is built by shard_predictor(predictor, "
            "rules=..., mesh=...), not constructed directly")

    # -- staging -------------------------------------------------------------

    def _stage(self, arr):
        """Commit one feed onto the mesh: batch-sharded over
        ``data_axis`` when the leading dim divides the axis size,
        replicated otherwise. Committed placement is what makes jax.jit
        compile (and cache) the partitioned program."""
        arr = np.asarray(arr)
        axis = self.data_axis
        n = self.num_shards
        if arr.ndim >= 1 and n > 1 and arr.shape[0] % n == 0:
            spec = P(axis, *([None] * (arr.ndim - 1)))
        else:
            spec = P()
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def run(self, inputs=None):
        """Same contract as :meth:`Predictor.run`, with feeds staged as
        mesh-committed jax arrays (the executor passes jax.Array feeds
        through untouched, so the jit signature sees the shardings)."""
        if inputs is not None:
            for n, arr in zip(self._feed_names, inputs):
                self._inputs[n]._data = self._stage(arr)
        feed = {}
        for n in self._feed_names:
            v = self._inputs[n]._data
            if v is None:
                raise RuntimeError(f"input {n!r} not set")
            if not isinstance(v, jax.Array):
                v = self._stage(v)  # handle staged via copy_from_cpu
            feed[n] = v
        outs = self._exe.run(
            self._program, feed=feed, fetch_list=self._fetch_names
        )
        for n, o in zip(self._fetch_names, outs):
            self._outputs[n]._data = o
        return outs

    def clone(self):
        """Replica twin: shared Executor/program/scope (the weights are
        already mesh-committed — one placement serves every clone), plus
        the mesh/axis staging config; per-clone IO handles as in the
        base class."""
        new = Predictor.clone(self)
        new.__class__ = ShardedPredictor
        new.mesh = self.mesh
        new.data_axis = self.data_axis
        new.num_shards = self.num_shards
        new.rules = self.rules
        new.sharded_params = self.sharded_params
        return new


def shard_predictor(predictor, rules=None, mesh=None, data_axis="dp"):
    """Thread PartitionSpecs into a predictor's compiled program.

    Commits every scope-resident parameter of ``predictor``'s inference
    program onto ``mesh`` per ``rules`` (:class:`parallel.ShardingRules`;
    default replicates everything) and returns the predictor rewrapped
    as a :class:`ShardedPredictor` staging its feeds onto the same mesh.

    Wrap BEFORE the first ``run()``: the executor's jit cache keys on
    shapes, not placement, so programs compiled after the wrap are
    partitioned from their first compile, while an entry compiled
    pre-wrap would be demoted to the jit fallback on its first sharded
    call (correct, but it forfeits that entry's AOT cost record).

    ``mesh`` defaults to the active ``parallel.mesh_scope`` mesh;
    ``data_axis`` names the mesh axis the batch dimension splits over.
    """
    mesh = mesh or get_mesh()
    if mesh is None:
        raise PreconditionNotMetError(
            "shard_predictor needs a mesh: pass mesh=... or enter "
            "parallel.mesh_scope(create_mesh(dp=...))")
    if data_axis not in mesh.shape:
        raise InvalidArgumentError(
            f"data_axis {data_axis!r} is not a mesh axis; mesh has "
            f"{dict(mesh.shape)}")
    rules = rules or DEFAULT_RULES
    from ..static.executor import global_scope

    scope = global_scope()
    sharded = {}
    for name in _persistable_names(predictor._program):
        if not scope.has(name):
            continue
        arr = scope.get(name)
        np_arr = np.asarray(arr)
        spec = rules.clamped_spec_for(name, np_arr.ndim)
        # a spec that does not divide the array degrades to replication
        # rather than erroring mid-boot: serving a new checkpoint must
        # not die because one bias picked up a stale rule
        for dim, part in zip(np_arr.shape, tuple(spec)):
            if part is None:
                continue
            axes = part if isinstance(part, tuple) else (part,)
            k = 1
            for ax in axes:
                k *= int(mesh.shape[ax])
            if dim % k:
                spec = P()
                break
        scope.set(name, jax.device_put(
            np_arr, named_sharding(spec, mesh)))
        sharded[name] = spec
    predictor.__class__ = ShardedPredictor
    predictor.mesh = mesh
    predictor.data_axis = data_axis
    predictor.num_shards = int(mesh.shape[data_axis])
    predictor.rules = rules
    predictor.sharded_params = sharded
    _flight.record_event(
        "serving_shard_predictor",
        mesh={ax: int(n) for ax, n in mesh.shape.items()},
        data_axis=data_axis,
        params=len(sharded),
        partitioned=sum(1 for s in sharded.values() if tuple(s)))
    return predictor
