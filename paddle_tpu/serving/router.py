"""Load-aware router tier: one front door over N backend processes.

A single ``InferenceServer``/``GenerationServer`` is one process on one
host; "heavy traffic" needs a fleet. The router spreads ``/predict`` and
``/generate`` traffic across independent backend processes using the
machine-oriented signals they already publish:

- **power-of-two-choices dispatch**: each request samples two in-rotation
  backends and takes the less loaded one (router-side in-flight count
  plus the last-probed ``/loadz`` queue depth). P2C gets most of the
  benefit of full load-awareness while staying O(1) and herd-immune —
  stale load signals cannot stampede every request onto one backend.
- **health/readiness probes**: a daemon prober hits every backend's
  ``/healthz`` + ``/loadz`` each ``FLAGS_serving_router_probe_interval_s``.
  A backend that stops answering, flips draining, or loses readiness is
  **evicted** from rotation; re-admission happens ONLY when a later
  probe sees ``/healthz`` readiness again — a drained backend cannot
  leak back in through a lucky dispatch.
- **retry-on-next-backend** for failures that provably precede dispatch:
  connection failures (refused/reset/EOF before a response line — the
  backend never answered; predict/generate are stateless, so replaying
  on a survivor is the availability contract) and admission rejections
  (503: draining or not ready — refused at the door). Work a backend
  actually ANSWERED is never replayed: any received HTTP status other
  than 503 (429 backpressure, 400 client errors, 504 deadline, 500
  dispatch failures) passes through to the client untouched.
- **fleet introspection**: the router serves its own ``/statz`` — fleet
  p50/p99 merged from the backends' ``/histz`` bucket counts (exact:
  summed buckets ≡ one pooled histogram), per-backend load/weights, and
  eviction/retry/readmission counters — plus ``/healthz``, ``/loadz``,
  ``/metrics``, all reporting into the flight recorder and registered
  with ``serving.shutdown_all``.

Backends enter the fleet via ``add_backend(url)`` (the autoscaler's
launcher calls this after booting a process) and leave via
``remove_backend``/eviction; the router never owns backend processes —
``serving/scaler.py`` does lifecycle.

The router is also runnable as its own process —
``python -m paddle_tpu.serving.router --backend URL [--backend URL ...]``
— which is how a production fleet (and the ``router_throughput`` bench)
deploys it: proxying is pure-Python byte shuffling, so co-hosting the
router inside a busy client or backend process would serialize the whole
fleet behind that process's GIL. (The in-process object form stays the
right one for tests and for the autoscaler, which drives
``add_backend``/``remove_backend`` directly.)
"""
from __future__ import annotations

import json
import random
import socket
import threading
import time
from http.client import (
    BadStatusLine,
    HTTPConnection,
    IncompleteRead,
    LineTooLong,
)
from urllib.parse import urlsplit

from ..errors import InvalidArgumentError, UnavailableError
from ..flags import flag
from ..monitor import counter, gauge, histogram
from ..monitor import flight_recorder as _flight
from ..monitor import histogram_quantile, merge_histogram_snapshots
from ..monitor import tracing as _tracing
from .server import _BaseHandler

__all__ = ["Router", "BackendState", "NoBackendError",
           "BackendUnavailableError", "BackendTimeoutError"]

_POST_KINDS = {"/predict": "predict", "/generate": "generate"}

# a backend dying while its response body is being read: ConnectionError
# covers resets, IncompleteRead a mid-body EOF, socket.timeout a stall,
# OSError the rest of the socket-level failure family
_BACKEND_READ_ERRORS = (ConnectionError, IncompleteRead, socket.timeout,
                        OSError)


def _quantile_row(h) -> dict | None:
    """p50/p99/count for one merged histogram, or None when it holds no
    observations (quantiles of nothing are not 0ms)."""
    p50 = histogram_quantile(h, 0.5)
    if p50 is None:
        return None
    return {"p50_ms": round(p50, 3),
            "p99_ms": round(histogram_quantile(h, 0.99), 3),
            "count": h.count}


class NoBackendError(UnavailableError):
    """No backend admitted the request within the retry budget (503)."""


class BackendUnavailableError(UnavailableError):
    """A backend could not be reached / died before answering. The
    request was never answered, so the router may retry it elsewhere."""

    def __init__(self, reason, detail):
        super().__init__(f"backend unavailable ({reason}): {detail}")
        self.reason = reason


class BackendTimeoutError(UnavailableError):
    """The backend took the request but no response arrived within the
    budget. The work IS dispatched (and may still be running), so the
    router must NOT retry — the client gets 504."""


class BackendState:
    """Router-side view of one backend: rotation membership, the last
    probed ``/loadz`` signals, and per-backend dispatch accounting.
    Mutated only under the router lock."""

    __slots__ = (
        "url", "kind", "in_rotation", "draining", "inflight",
        "queue_depth", "queue_capacity", "load", "mean_fill",
        "slot_occupancy", "compiles", "consecutive_failures",
        "admitted", "completed", "evictions", "last_probe_t",
        "last_error", "metrics", "metrics_t",
    )

    def __init__(self, url):
        self.url = url.rstrip("/")
        self.kind = None           # "predict" | "generate", from /loadz
        self.in_rotation = False   # eligible for dispatch
        self.draining = False
        self.inflight = 0          # router-side outstanding requests
        self.queue_depth = 0
        self.queue_capacity = 0
        self.load = 0.0
        self.mean_fill = None
        self.slot_occupancy = None
        self.compiles = {}
        self.consecutive_failures = 0
        self.admitted = 0
        self.completed = 0
        self.evictions = 0
        self.last_probe_t = 0.0
        self.last_error = None
        # last /metricz?format=snapshot scrape (registry snapshot dict),
        # the /fleetz merge feed; stale-tolerant for one probe period
        self.metrics = {}
        self.metrics_t = 0.0

    def score(self) -> float:
        """P2C comparison key: fresher router-side in-flight count plus
        the last-probed backend queue depth."""
        return self.inflight + self.queue_depth

    def view(self) -> dict:
        return {
            "url": self.url, "kind": self.kind,
            "in_rotation": self.in_rotation, "draining": self.draining,
            "inflight": self.inflight, "queue_depth": self.queue_depth,
            "load": self.load, "mean_fill": self.mean_fill,
            "slot_occupancy": self.slot_occupancy,
            "compiles": self.compiles,
            "admitted": self.admitted, "completed": self.completed,
            "evictions": self.evictions,
            "last_error": self.last_error,
        }


class _RouterHandler(_BaseHandler):
    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if self._get_common(path):
            return
        if path == "/fleetz":
            self._reply(200, self._srv.fleetz())
        elif path == "/":
            self._reply(200, {
                "service": "paddle_tpu serving router",
                "routes": ["/predict (POST)", "/generate (POST)",
                           "/healthz", "/statz", "/loadz", "/fleetz",
                           "/histz", "/tracez", "/metrics", "/metricz",
                           "/sloz"]})
        else:
            self._reply(404, {"error": f"unknown path {path!r}"})

    def do_POST(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        body = self._read_body()
        if body is None:
            return
        kind = _POST_KINDS.get(path)
        if kind is None:
            self._reply(404, {"error": f"unknown path {path!r}"})
            return
        # the router is where a fleet trace is BORN (or continued, when
        # the client itself propagates a traceparent): every dispatch
        # attempt becomes a child span, and the chosen backend's whole
        # span tree hangs under the winning attempt
        with self._trace_request("serving::router"):
            _tracing.annotate(kind=kind)
            self._proxy(path, kind, body)

    def _proxy(self, path, kind, body):
        srv = self._srv
        if srv.draining:
            self._reply(503, {"error": "router draining"})
            return
        if kind == "generate" and srv.has_kind("prefill") \
                and srv.has_kind("decode"):
            # disaggregated fleet: /generate becomes prefill -> slab ->
            # decode, orchestrated here (the tiers never talk directly,
            # so each leg keeps the full retry/eviction policy). BOTH
            # tiers must be live — with only a prefill tier up (decode
            # still booting/evicted) requests keep flowing to any
            # unified generate backends instead of 503ing
            self._proxy_disagg(body)
            return
        t0 = time.monotonic()
        try:
            backend, conn, resp = srv.dispatch(kind, path, body)
        except NoBackendError as e:
            self._reply(503, {"error": str(e)})
            return
        except BackendTimeoutError as e:
            self._reply(504, {"error": str(e)})
            return
        _tracing.annotate(backend=backend.url)
        self._relay(srv, backend, conn, resp, t0)

    def _relay(self, srv, backend, conn, resp, t0):
        """Forward one dispatched backend response to the client —
        streamed re-chunking or a buffered read — with the
        died-mid-response handling and the finish bookkeeping."""
        status = resp.status
        try:
            if (resp.getheader("Transfer-Encoding") or "").lower() \
                    == "chunked":
                self._proxy_stream(resp, srv, backend)
            else:
                try:
                    data = resp.read()
                except _BACKEND_READ_ERRORS as e:
                    # the backend answered its status line then died
                    # mid-body: the work WAS dispatched (no retry), but
                    # the client must get a real response, not a
                    # dropped socket
                    status = 502
                    srv.note_backend_died(backend, "died_mid_response")
                    self._reply(502, {
                        "error": "backend connection lost mid-response "
                                 f"({type(e).__name__})"})
                else:
                    self._reply_raw(status, data,
                                    resp.getheader("Content-Type"))
        finally:
            srv.finish(backend, t0, status, conn=conn, resp=resp)

    def _proxy_disagg(self, body):
        """Two-leg /generate: POST the request to a prefill backend
        (bounded forward on the handoff budget), then hand its KV slab
        to a decode backend whose response — streamed or not — relays
        to the client exactly like a unified /generate.

        Leg semantics: the prefill leg is stateless and keeps the full
        retry policy; a non-200 prefill answer (400 bad prompt, 429
        backpressure) passes through untouched. The slab then rides the
        normal dispatch to the decode tier, where the usual "answered
        means no replay" contract takes over."""
        from ..generation.handoff import HANDOFF_CONTENT_TYPE

        srv = self._srv
        t0 = time.monotonic()
        try:
            b1, conn1, resp1 = srv.dispatch(
                "prefill", "/prefill", body,
                read_timeout=srv.handoff_timeout_s)
        except NoBackendError as e:
            self._reply(503, {"error": str(e)})
            return
        except BackendTimeoutError as e:
            self._reply(504, {"error": f"prefill handoff: {e}"})
            return
        _tracing.annotate(prefill_backend=b1.url)
        status1 = resp1.status
        slab = None
        ctype1 = resp1.getheader("Content-Type")
        try:
            try:
                slab = resp1.read()
            except _BACKEND_READ_ERRORS as e:
                status1 = 502
                srv.note_backend_died(b1, "died_mid_response")
                self._reply(502, {
                    "error": "prefill backend connection lost "
                             f"mid-slab ({type(e).__name__})"})
                return
        finally:
            srv.finish(b1, t0, status1, conn=conn1, resp=resp1)
        if status1 != 200:
            self._reply_raw(status1, slab, ctype1)
            return
        t1 = time.monotonic()
        try:
            b2, conn2, resp2 = srv.dispatch(
                "decode", "/generate_kv", slab,
                content_type=HANDOFF_CONTENT_TYPE)
        except NoBackendError as e:
            self._reply(503, {"error": str(e)})
            return
        except BackendTimeoutError as e:
            self._reply(504, {"error": str(e)})
            return
        _tracing.annotate(backend=b2.url, handoff=True)
        self._relay(srv, b2, conn2, resp2, t1)

    def _proxy_stream(self, resp, srv, backend):
        """Re-chunk a streaming backend response to the client as the
        bytes arrive (one ``read1`` per backend chunk — per-token
        streaming survives the hop)."""
        # the chunked path bypasses _reply/_reply_raw, so the trace
        # must learn its status here
        _tracing.note_status(resp.status)
        self.send_response(resp.status)
        self.send_header("Content-Type",
                         resp.getheader("Content-Type")
                         or "application/x-ndjson; charset=utf-8")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk_out(data):
            self.wfile.write(f"{len(data):x}\r\n".encode()
                             + data + b"\r\n")

        try:
            while True:
                try:
                    chunk = resp.read1(65536)
                except _BACKEND_READ_ERRORS as e:
                    # backend died mid-stream: the status line is long
                    # gone, so terminate the chunked stream PROPERLY
                    # with an error line — a bare connection drop would
                    # leave the client hanging on a dechunk. The trace
                    # is exactly the one the incident post-mortem needs:
                    # mark it errored so the tail sampler keeps it.
                    _tracing.note_status(502)
                    srv.note_backend_died(backend, "died_mid_stream")
                    chunk_out(json.dumps({
                        "error": "backend connection lost mid-stream "
                                 f"({type(e).__name__})"
                    }).encode() + b"\n")
                    break
                if not chunk:
                    break
                chunk_out(chunk)
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; backend read drains on conn.close


class Router:
    """HTTP front door spreading traffic over registered backends.

    ``backends`` seeds the fleet (each is probed and admitted when
    ready). ``port=0`` binds an ephemeral port. ``start()`` boots the
    listener and the prober; ``stop(drain=True)`` refuses new work,
    waits for in-flight proxied requests, and closes both.
    """

    def __init__(self, backends=(), port=0, host="127.0.0.1",
                 probe_interval_s=None, retries=None,
                 connect_timeout_ms=None, request_timeout_s=None):
        self.probe_interval_s = float(
            probe_interval_s if probe_interval_s is not None
            else flag("serving_router_probe_interval_s"))
        self.retries = int(retries if retries is not None
                           else flag("serving_router_retries"))
        if self.retries <= 0:
            raise InvalidArgumentError(
                f"router retry budget must be positive, got {self.retries}")
        self.connect_timeout_s = float(
            connect_timeout_ms if connect_timeout_ms is not None
            else flag("serving_router_connect_timeout_ms")) / 1e3
        self.request_timeout_s = float(
            request_timeout_s if request_timeout_s is not None
            else flag("serving_router_request_timeout_s"))
        # budget for the prefill->slab leg of a disaggregated /generate
        # (one bounded forward; the decode leg keeps the full budget)
        self.handoff_timeout_s = float(flag("serving_handoff_timeout_s"))
        self._lock = threading.Lock()
        self._backends: dict[str, BackendState] = {}
        # keep-alive pools: idle router->backend connections per backend
        # url. Connection-per-request would pay a TCP handshake + a
        # backend handler-thread spawn per dispatch — at fleet request
        # rates that churn IS the bottleneck.
        self._pools: dict[str, list] = {}
        self._pool_max_idle = 32
        self._rng = random.Random(0xB0DE)
        # fleet metrics (router process registry -> /metrics)
        self._m_requests = counter("serving/router_requests_total")
        self._m_retries = counter("serving/router_retries_total")
        self._m_evictions = counter("serving/router_evictions_total")
        self._m_readmissions = counter(
            "serving/router_readmissions_total")
        self._m_no_backend = counter("serving/router_no_backend_total")
        self._m_healthy = gauge("serving/router_backends_healthy")
        self._h_e2e = histogram("serving/router_e2e_ms")
        from .server import ServingHTTPServer

        self._httpd = ServingHTTPServer((host, int(port)),
                                        _RouterHandler)
        self._httpd._inference_server = self
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None
        self._prober = None
        self._stop_probe = threading.Event()
        self._t0 = time.monotonic()
        self.draining = False
        self._stopped = False
        for url in backends:
            self.add_backend(url)
        from . import _register_live

        _register_live(self)

    # -- fleet membership ----------------------------------------------------

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def ready(self) -> bool:
        return not self.draining and self.healthy_count > 0

    @property
    def healthy_count(self) -> int:
        with self._lock:
            return sum(b.in_rotation for b in self._backends.values())

    def has_kind(self, kind) -> bool:
        """Any in-rotation backend confirmed as ``kind``? (The
        disaggregation switch: /generate orchestrates prefill->decode
        exactly when a prefill tier is live.)"""
        with self._lock:
            return any(b.in_rotation and b.kind == kind
                       for b in self._backends.values())

    def backend_states(self) -> list:
        with self._lock:
            return list(self._backends.values())

    def add_backend(self, url, probe=True) -> BackendState:
        """Register a backend. With ``probe`` (default) it is health-
        checked immediately and admitted if ready; otherwise it waits
        for the prober's next pass."""
        b = BackendState(url)
        with self._lock:
            existing = self._backends.get(b.url)
            if existing is not None:
                return existing
            self._backends[b.url] = b
        _flight.record_event("router_backend_added", url=b.url)
        if probe:
            self._probe_backend(b)
        return b

    def remove_backend(self, url) -> BackendState | None:
        """Drop a backend from the fleet entirely (scale-down path: the
        caller owns draining/terminating the process)."""
        with self._lock:
            b = self._backends.pop(url.rstrip("/"), None)
        self._pool_drop(url)
        if b is not None:
            _flight.record_event("router_backend_removed", url=b.url)
            self._update_healthy_gauge()
        return b

    def _update_healthy_gauge(self):
        self._m_healthy.set(self.healthy_count)

    def _evict(self, b: BackendState, reason: str):
        """Remove from rotation (dispatch stops immediately). The ONLY
        way back in is a later probe seeing /healthz readiness."""
        with self._lock:
            was = b.in_rotation
            b.in_rotation = False
            b.evictions += was
            b.last_error = reason
        if was:
            self._pool_drop(b.url)  # idle conns to a sick backend: out
            self._m_evictions.inc()
            _flight.record_event("router_backend_evicted", url=b.url,
                                 reason=reason)
            self._update_healthy_gauge()

    def note_backend_died(self, b: BackendState, reason: str):
        """A dispatched request's connection died mid-response: the
        client already owns that failure (502 / error chunk), but the
        backend is evidently gone — evict it so the NEXT requests go
        elsewhere instead of each paying the same discovery."""
        self._evict(b, reason=reason)

    def _admit(self, b: BackendState):
        with self._lock:
            was = b.in_rotation
            b.in_rotation = True
            # /healthz readiness implies not draining (ready == warmed
            # AND not draining); clear a stale dispatch-path flag even
            # when the /loadz refresh was skipped — in-rotation with
            # draining stuck True would be unpickable yet counted
            # healthy
            b.draining = False
            b.consecutive_failures = 0
            b.last_error = None
        if not was:
            if b.evictions:
                self._m_readmissions.inc()
                _flight.record_event("router_backend_readmitted",
                                     url=b.url)
            self._update_healthy_gauge()

    # -- backend HTTP --------------------------------------------------------

    def _connect(self, b: BackendState,
                 read_timeout=None) -> HTTPConnection:
        u = urlsplit(b.url)
        conn = HTTPConnection(u.hostname, u.port,
                              timeout=self.connect_timeout_s)
        try:
            conn.connect()
        except OSError as e:
            conn.close()
            raise BackendUnavailableError("connect", str(e)) from None
        conn.sock.settimeout(read_timeout or self.request_timeout_s)
        return conn

    def _send(self, b: BackendState, method, path, body=None,
              read_timeout=None):
        """One request to one backend. Returns ``(conn, resp)`` with the
        response UNREAD (the caller streams or reads it, then closes the
        conn). Raises :class:`BackendUnavailableError` only when no
        response line ever arrived — the definition of "not dispatched"
        the retry policy keys on."""
        conn = self._connect(b, read_timeout=read_timeout)
        try:
            return conn, self._request_on(conn, method, path, body,
                                          read_timeout=read_timeout)
        except BackendTimeoutError:
            conn.close()
            raise
        except (ConnectionError, BadStatusLine, LineTooLong,
                OSError) as e:
            conn.close()
            raise BackendUnavailableError(
                "no_response", f"{type(e).__name__}: {e}") from None

    def _pool_pop(self, b: BackendState):
        with self._lock:
            pool = self._pools.get(b.url)
            return pool.pop() if pool else None

    def _pool_push(self, b_url, conn):
        with self._lock:
            pool = self._pools.setdefault(b_url, [])
            if len(pool) < self._pool_max_idle:
                pool.append(conn)
                return
        conn.close()

    def _pool_drop(self, url):
        with self._lock:
            pool = self._pools.pop(url.rstrip("/"), [])
        for conn in pool:
            conn.close()

    def _dispatch_send(self, b: BackendState, path, body, headers=None,
                       content_type=None, read_timeout=None):
        """POST over a pooled keep-alive connection. A failure on a
        REUSED connection is retried once on a fresh one — the backend
        may simply have timed the idle socket out, which is not evidence
        of death. Only a fresh-connection failure raises the retriable
        :class:`BackendUnavailableError`. ``content_type`` overrides
        the JSON default (KV-slab handoffs are octet bodies);
        ``read_timeout`` overrides the request budget (the prefill leg
        of a handoff runs on the shorter handoff timeout)."""
        conn = self._pool_pop(b)
        if conn is not None:
            try:
                return conn, self._request_on(conn, "POST", path, body,
                                              extra_headers=headers,
                                              content_type=content_type,
                                              read_timeout=read_timeout)
            except BackendTimeoutError:
                conn.close()
                raise
            except (ConnectionError, BadStatusLine, LineTooLong,
                    OSError):
                conn.close()  # stale keep-alive: fall through to fresh
        conn = self._connect(b)
        try:
            return conn, self._request_on(conn, "POST", path, body,
                                          extra_headers=headers,
                                          content_type=content_type,
                                          read_timeout=read_timeout)
        except BackendTimeoutError:
            conn.close()
            raise
        except (ConnectionError, BadStatusLine, LineTooLong,
                OSError) as e:
            conn.close()
            raise BackendUnavailableError(
                "no_response", f"{type(e).__name__}: {e}") from None

    def _request_on(self, conn, method, path, body, extra_headers=None,
                    content_type=None, read_timeout=None):
        timeout = (self.request_timeout_s if read_timeout is None
                   else float(read_timeout))
        if conn.sock is not None:
            # pooled connections keep their previous budget otherwise
            conn.sock.settimeout(timeout)
        try:
            headers = ({"Content-Type": content_type or
                        "application/json"} if body else {})
            if extra_headers:
                headers.update(extra_headers)
            conn.request(method, path, body=body, headers=headers)
            return conn.getresponse()
        except socket.timeout:
            # the request went out but nothing came back in time: the
            # backend may still be computing it — dispatched work, so
            # no retry (504), unlike the connection-failure cases
            raise BackendTimeoutError(
                f"backend gave no response within {timeout}s") from None

    def _get_json(self, b: BackendState, path):
        """Probe GET: ``(status, parsed-json-or-{})``. Probes read on a
        short budget of their own — a hung backend must cost the prober
        seconds, not the full request timeout."""
        conn, resp = self._send(
            b, "GET", path,
            read_timeout=min(5.0, self.request_timeout_s))
        try:
            data = resp.read()
        finally:
            conn.close()
        try:
            payload = json.loads(data) if data else {}
        except ValueError:
            payload = {}
        return resp.status, payload

    # -- dispatch ------------------------------------------------------------

    def _pick(self, kind, exclude) -> BackendState | None:
        """Power-of-two-choices among in-rotation backends serving
        ``kind``: sample two, take the lower load score.

        Kind-CONFIRMED backends always win over kind-unknown ones: a
        not-yet-probed backend (``kind is None``) is only eligible when
        NO confirmed backend serves the kind — with several kinds in
        one fleet, an unprobed decode backend must not siphon
        ``/predict`` traffic it will 404. A mis-guessed unknown is
        re-picked, not failed (see :meth:`dispatch`)."""
        with self._lock:
            pool = [
                b for b in self._backends.values()
                if b.in_rotation and not b.draining
                and b.url not in exclude
            ]
            cands = [b for b in pool if b.kind == kind]
            if not cands:
                cands = [b for b in pool if b.kind is None]
            if not cands:
                return None
            if len(cands) == 1:
                return cands[0]
            a, c = self._rng.sample(cands, 2)
            return min((a, c), key=lambda b: (b.score(), b.url))

    def dispatch(self, kind, path, body, content_type=None,
                 read_timeout=None):
        """Pick-and-forward with the retry policy. Returns ``(backend,
        conn, resp)`` — response unread so the handler can stream it;
        the handler MUST call :meth:`finish` when done. Raises
        :class:`NoBackendError` after the retry budget.

        Every attempt is its own child span under the request's trace
        (the trace_id survives retries; each attempt is distinct), and
        the attempt's ``traceparent`` rides the proxied request so the
        backend's span tree hangs under it."""
        tried: set = set()
        while len(tried) < self.retries:
            b = self._pick(kind, tried)
            if b is None:
                break
            tried.add(b.url)
            kind_known = b.kind is not None
            with self._lock:
                b.inflight += 1
                b.admitted += 1
            # per-attempt span: bound under the handler's router root
            # (NULL outside a trace — direct dispatch() callers pay one
            # flag read). The span is recorded on scope exit whatever
            # the outcome, so even a timed-out attempt leaves a record.
            with _tracing.start_span(
                    "serving::attempt", backend=b.url,
                    attempt=len(tried)) as asp:
                headers = None
                if asp:
                    headers = {
                        _tracing.TRACEPARENT_HEADER:
                            _tracing.format_traceparent(asp.context)}
                try:
                    conn, resp = self._dispatch_send(
                        b, path, body, headers=headers,
                        content_type=content_type,
                        read_timeout=read_timeout)
                except BackendTimeoutError as e:
                    with self._lock:
                        b.inflight -= 1
                    # the work may still be running over there: no
                    # retry, but the orphaned attempt span (with the
                    # backend identity) is recorded and the trace is
                    # retained — an operator inspecting the 504 can see
                    # WHICH backend swallowed the request
                    asp.set_error(f"read timeout: {e}")
                    _tracing.flag_current_trace("timeout")
                    raise  # dispatched: surfaces as 504, never retried
                except BackendUnavailableError as e:
                    with self._lock:
                        b.inflight -= 1
                    # never answered -> the work never ran to completion
                    # anywhere; evict the silent backend and retry the
                    # request on the next one
                    asp.set_error(f"unavailable ({e.reason})")
                    _tracing.flag_current_trace("retry")
                    self._evict(b, reason=e.reason)
                    self._m_retries.inc()
                    _flight.record_event("router_retry", url=b.url,
                                         reason=e.reason, path=path)
                    continue
                if resp.status == 503:
                    # refused at admission (draining / not ready): the
                    # backend did NOT take the work — evict immediately
                    # (readiness re-admits it later) and retry elsewhere
                    try:
                        resp.read()
                    finally:
                        conn.close()
                    with self._lock:
                        b.inflight -= 1
                        b.draining = True
                    asp.set_attributes(status=503, refused=True)
                    _tracing.flag_current_trace("retry")
                    self._evict(b, reason="admission_503")
                    self._m_retries.inc()
                    _flight.record_event("router_retry", url=b.url,
                                         reason="admission_503",
                                         path=path)
                    continue
                if resp.status == 404 and not kind_known:
                    # a kind-unknown backend won the fallback pick for
                    # a route it does not serve: learn its kind from a
                    # probe and RE-PICK — the request never ran, so
                    # failing it would punish the client for the
                    # router's incomplete map
                    try:
                        resp.read()
                    finally:
                        conn.close()
                    with self._lock:
                        b.inflight -= 1
                    asp.set_attributes(status=404, kind_mismatch=True)
                    _tracing.flag_current_trace("retry")
                    self._probe_backend(b)
                    self._m_retries.inc()
                    _flight.record_event("router_retry", url=b.url,
                                         reason="kind_mismatch",
                                         path=path)
                    continue
                asp.set_attributes(status=resp.status)
                return b, conn, resp
        self._m_no_backend.inc()
        _flight.record_event("router_no_backend", path=path,
                             tried=sorted(tried))
        raise NoBackendError(
            f"no backend admitted the request (tried {len(tried)}, "
            f"retry budget {self.retries})")

    def finish(self, b: BackendState, t0, status, conn=None, resp=None):
        with self._lock:
            b.inflight -= 1
            b.completed += 1
        self._m_requests.inc()
        self._h_e2e.observe((time.monotonic() - t0) * 1e3)
        if conn is None:
            return
        # keep-alive recycling: only a FULLY-read response on a
        # connection the backend will keep open may re-enter the pool —
        # a half-read body (client vanished mid-stream) would corrupt
        # the next request on that socket
        if (resp is not None and resp.isclosed()
                and not resp.will_close and b.in_rotation):
            self._pool_push(b.url, conn)
        else:
            conn.close()

    # -- probing -------------------------------------------------------------

    def _probe_backend(self, b: BackendState):
        """One health/load probe: readiness on ``/healthz`` gates
        rotation membership; ``/loadz`` refreshes the dispatch signals
        (and the backend's kind)."""
        try:
            status, _ = self._get_json(b, "/healthz")
            if status != 200:
                raise BackendUnavailableError("not_ready",
                                              f"healthz {status}")
        except (BackendUnavailableError, BackendTimeoutError) as e:
            with self._lock:
                b.consecutive_failures += 1
            self._evict(b, reason=getattr(e, "reason", "probe_timeout"))
            b.last_probe_t = time.monotonic()
            return
        try:
            lz_status, lz = self._get_json(b, "/loadz")
            if lz_status == 200 and lz:
                with self._lock:
                    b.kind = lz.get("kind", b.kind)
                    b.queue_depth = int(lz.get("queue_depth", 0))
                    b.queue_capacity = int(lz.get("queue_capacity", 0))
                    b.load = float(lz.get("load", 0.0))
                    b.mean_fill = lz.get("mean_fill")
                    b.slot_occupancy = lz.get("slot_occupancy")
                    b.compiles = lz.get("compiles", {})
                    b.draining = bool(lz.get("draining", False))
                if b.draining:
                    self._evict(b, reason="draining")
                    return
            self._admit(b)
        except (BackendUnavailableError, BackendTimeoutError) as e:
            with self._lock:
                b.consecutive_failures += 1
            self._evict(b, reason=getattr(e, "reason", "probe_timeout"))
        finally:
            b.last_probe_t = time.monotonic()
        # fleet-metrics scrape rides the same probe pass: the latest
        # registry snapshot (labeled series included) lands on the
        # state, so /fleetz is a dict merge, never a fan-out of
        # on-demand backend GETs. Failure keeps the previous snapshot —
        # load/health already decided rotation, and metrics one probe
        # period stale merge fine.
        try:
            mz_status, mz = self._get_json(b, "/metricz?format=snapshot")
            if mz_status == 200 and isinstance(mz, dict):
                with self._lock:
                    b.metrics = mz.get("metrics") or {}
                    b.metrics_t = time.monotonic()
        except (BackendUnavailableError, BackendTimeoutError):
            pass

    def probe_once(self):
        for b in self.backend_states():
            self._probe_backend(b)

    def _probe_loop(self):
        while not self._stop_probe.wait(self.probe_interval_s):
            try:
                self.probe_once()
            except Exception:  # the prober must never die
                pass

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"ptpu-router:{self.port}", daemon=True)
            self._thread.start()
        if self._prober is None or not self._prober.is_alive():
            self._stop_probe.clear()
            self._prober = threading.Thread(
                target=self._probe_loop, name="ptpu-router-prober",
                daemon=True)
            self._prober.start()
        _flight.record_event(
            "router_start", port=self.port,
            backends=[b.url for b in self.backend_states()])
        return self

    def stop(self, drain=True, timeout=10.0):
        """Refuse new work, optionally wait out in-flight proxied
        requests, then close prober + listener. Backends are NOT
        stopped — the router does not own their processes."""
        if self._stopped:
            return
        self._stopped = True
        self.draining = True
        if drain:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._lock:
                    busy = sum(b.inflight
                               for b in self._backends.values())
                if not busy:
                    break
                time.sleep(0.01)
        self._stop_probe.set()
        p = self._prober
        if p is not None:
            p.join(timeout=self.probe_interval_s + 1.0)
        self._prober = None
        t = self._thread
        if t is not None and t.is_alive():
            # shutdown() blocks on an event only serve_forever() sets —
            # never call it on a listener that never started
            self._httpd.shutdown()
        self._httpd.server_close()
        if t is not None:
            t.join(timeout=5)
        self._thread = None
        for url in list(self._pools):
            self._pool_drop(url)
        _flight.record_event("router_stop", port=self.port, drain=drain)

    # -- introspection -------------------------------------------------------

    def merged_backend_quantiles(self, names=None, timeout_s=2.0) -> dict:
        """Fleet-wide latency quantiles: fetch every in-rotation
        backend's ``/histz`` bucket counts and merge per histogram name
        (exact — summed buckets are the pooled histogram). Returns
        ``{name: {p50_ms, p99_ms, count, backends}}``."""
        per_name: dict[str, list] = {}
        for b in self.backend_states():
            if not b.in_rotation:
                continue
            try:
                status, payload = self._get_json(b, "/histz")
            except (BackendUnavailableError, BackendTimeoutError):
                continue
            if status != 200:
                continue
            for name, snap in payload.get("histograms", {}).items():
                if names is not None and name not in names:
                    continue
                per_name.setdefault(name, []).append(snap)
        out = {}
        for name, snaps in per_name.items():
            merged = merge_histogram_snapshots(snaps, name=name)
            row = _quantile_row(merged)
            if row is None:
                continue
            row["backends"] = len(snaps)
            out[name] = row
        return out

    def fleetz(self) -> dict:
        """``GET /fleetz``: fleet-merged labeled quantiles. Per backend
        kind, per ``serving/*`` histogram, the elementwise bucket sum of
        every in-rotation backend's last prober-scraped snapshot —
        exact, identical to one pooled histogram — with quantiles per
        labeled series riding along. Empty series are omitted entirely
        (a fake 0ms p99 is worse than no row). No backend I/O happens
        here: the prober already paid for the snapshots."""
        groups: dict = {}
        states = self.backend_states()
        scraped = 0
        for b in states:
            if not b.in_rotation or not b.metrics:
                continue
            scraped += 1
            kind = b.kind or "unknown"
            for name, snap in b.metrics.items():
                if (not isinstance(snap, dict)
                        or snap.get("kind") != "histogram"
                        or not name.startswith("serving/")):
                    continue
                groups.setdefault(kind, {}).setdefault(
                    name, []).append(snap)
        fleet: dict = {}
        for kind, per_name in groups.items():
            for name, snaps in per_name.items():
                try:
                    merged = merge_histogram_snapshots(snaps, name=name)
                except ValueError:
                    continue  # mixed bucket ladders: skip, don't 500
                row = _quantile_row(merged)
                if row is None:
                    continue
                row["backends"] = len(snaps)
                series = {}
                for sel, child in sorted(merged.series().items()):
                    srow = _quantile_row(child)
                    if srow is not None:
                        series[sel] = srow
                if series:
                    row["series"] = series
                fleet.setdefault(kind, {})[name] = row
        return {"backends_scraped": scraped, "fleet": fleet}

    def healthz(self) -> dict:
        return {
            "ready": self.ready,
            "draining": self.draining,
            "backends_total": len(self._backends),
            "backends_healthy": self.healthy_count,
            "uptime_s": round(time.monotonic() - self._t0, 3),
        }

    def loadz(self) -> dict:
        """Routers speak the backend load schema too (fleets can stack:
        a region router over host routers). Queue depth aggregates the
        fleet's last-probed depths plus router-side in-flight."""
        states = self.backend_states()
        depth = sum(b.queue_depth + b.inflight for b in states
                    if b.in_rotation)
        cap = sum(b.queue_capacity for b in states if b.in_rotation)
        from .server import LOADZ_SCHEMA_VERSION

        return {
            "schema": LOADZ_SCHEMA_VERSION,
            "kind": "router",
            "ready": self.ready,
            "draining": self.draining,
            "queue_depth": depth,
            "queue_capacity": cap,
            "load": round(depth / cap, 4) if cap else 0.0,
            "mean_fill": None,
            "slot_occupancy": None,
            "compiles": {"expected": 0, "unexpected": 0,
                         "jit_misses": 0},
        }

    def statz(self) -> dict:
        states = self.backend_states()
        scores = {b.url: 1.0 / (1.0 + b.score()) for b in states
                  if b.in_rotation}
        total_w = sum(scores.values()) or 1.0
        backends = []
        for b in states:
            v = b.view()
            v["weight"] = round(scores.get(b.url, 0.0) / total_w, 4)
            backends.append(v)
        from .server import _stats_readers

        _, quantiles = _stats_readers()
        return {
            **self.healthz(),
            "backends": backends,
            "fleet": {
                "requests": self._m_requests.value,
                "retries": self._m_retries.value,
                "evictions": self._m_evictions.value,
                "readmissions": self._m_readmissions.value,
                "no_backend_503": self._m_no_backend.value,
            },
            "latency": {
                "router_e2e": quantiles("serving/router_e2e_ms"),
                "backends_merged": self.merged_backend_quantiles(),
            },
        }


# ---------------------------------------------------------------------------
# process entrypoint
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    """``python -m paddle_tpu.serving.router``: run the router as its
    own process over a static backend list (port announced through
    ``--port-file``, SIGTERM drains — the ``serving/backend.py``
    lifecycle, applied to the front door)."""
    import argparse
    import signal as _sig
    import threading as _threading

    p = argparse.ArgumentParser(
        prog="paddle_tpu.serving.router",
        description="serving-fleet router process")
    p.add_argument("--backend", action="append", default=[],
                   help="backend base URL (repeatable)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--port-file", default="")
    p.add_argument("--probe-interval-s", type=float, default=None)
    p.add_argument("--retries", type=int, default=None)
    args = p.parse_args(argv)

    router = Router(backends=args.backend, host=args.host,
                    port=args.port,
                    probe_interval_s=args.probe_interval_s,
                    retries=args.retries).start()
    # router-local SLOs (e.g. over serving/router_e2e_ms) from
    # FLAGS_slo_objectives; no-op when the flag is empty
    from ..monitor import slo as _slo

    _slo.install_from_flags()
    if args.port_file:
        from .backend import _announce_port

        _announce_port(args.port_file, router.port)
    import os as _os

    print(f"serving router ready on {router.url} "
          f"({len(args.backend)} backends, pid={_os.getpid()})",
          flush=True)
    stop = _threading.Event()
    _sig.signal(_sig.SIGTERM, lambda s, f: stop.set())
    _sig.signal(_sig.SIGINT, lambda s, f: stop.set())
    stop.wait()
    router.stop(drain=True)
    return 0


if __name__ == "__main__":
    import sys as _sys

    _sys.exit(main())
