"""Continuous batching: slot-turnover scheduling over a GenerationEngine.

The dynamic batcher (``batcher.py``) assembles a batch, dispatches it,
and TEARS IT DOWN — right for single-call predictors, ruinous for
autoregressive decoding where co-batched sequences finish at different
times: the batch would run at the pace of its longest member while
finished slots burn compute on garbage.

Here the batch never tears down. The compiled decode step always runs
all ``engine.slots`` rows; a sequence that hits EOS or its token budget
VACATES its slot mid-batch, and the next queued request is admitted into
the vacant slot at the very next step (a prefill + one functional
indexed cache write — no recompile, the decode program's shapes are slot
-count-static). Under mixed-length traffic the slots stay full, which is
where the throughput comes from (bench.py ``decode_throughput`` measures
continuous vs static on exactly that sweep).

Admission reuses the serving queue contracts: bounded queue with
:class:`QueueFullError` backpressure (HTTP 429), deadlines that expire
queued requests WITHOUT dispatch, :class:`ServingClosedError` after
close, and graceful drain. Compile accounting reuses
:class:`replica.CompileWatch` over the ``generation::compile`` counter —
steady state is exactly 1 decode + len(prefill ladder) programs, any
growth bumps ``serving/gen_unexpected_compiles`` + a flight event.

Per-token streaming: pass ``on_token`` to :meth:`submit` and every
sampled token is delivered as it is decoded (the HTTP ``/generate``
endpoint's streaming mode rides this).
"""
from __future__ import annotations

import threading
import time
from collections import deque

from ..errors import InvalidArgumentError
from ..flags import flag
from ..generation.handoff import PageSlab
from ..monitor import counter, gauge, histogram
from ..monitor import flight_recorder as _flight
from ..monitor import tracing as _tracing
from .batcher import (
    DeadlineExceededError,
    QueueFullError,
    ServingClosedError,
)

__all__ = ["ContinuousBatcher", "GenerationRequest"]


class GenerationRequest:
    """One submitted generation: a token prompt (or a handed-off KV
    slab standing in for one), its budget and sampling override, the
    tokens produced so far, and a completion event."""

    __slots__ = ("prompt", "prompt_len", "max_new_tokens", "temperature",
                 "deadline", "t_submit", "t_first_token", "tokens",
                 "finish_reason", "on_token", "error", "trace",
                 "handoff", "tenant", "_done")

    def __init__(self, prompt, max_new_tokens, temperature, deadline,
                 t_submit, on_token=None, handoff=None, prompt_len=None,
                 tenant=None):
        self.prompt = prompt
        # a disaggregated admission knows the prompt LENGTH (slab
        # metadata) even when the tokens themselves did not ride along
        self.prompt_len = (len(prompt) if prompt_len is None
                           else int(prompt_len))
        # (planes, length, first_token) from generation.handoff — the
        # admission path becomes insert_slot_kv instead of a prefill
        self.handoff = handoff
        # the submitter's trace context (the HTTP handler's server
        # span): queue-wait / slot-admission / decode spans recorded by
        # the decode-loop thread hang under it
        self.trace = _tracing.current_context()
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.t_submit = t_submit
        # label dimension on the per-request latency histograms
        self.tenant = "default" if tenant is None else str(tenant)
        self.t_first_token = None
        self.tokens = []
        self.finish_reason = None  # "eos" | "length" | None
        self.on_token = on_token
        self.error = None
        self._done = threading.Event()

    def expired(self, now) -> bool:
        return self.deadline is not None and now > self.deadline

    def done(self, error=None):
        self.error = error
        self._done.set()

    @property
    def finished(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout=None):
        """Block until generation completes; returns the token list or
        raises the stored error."""
        if not self._done.wait(timeout):
            from ..errors import ExecutionTimeoutError

            raise ExecutionTimeoutError(
                f"generation not completed within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.tokens


class ContinuousBatcher:
    """Slot scheduler + decode-loop worker over one GenerationEngine."""

    def __init__(self, engine, queue_capacity=None, clock=time.monotonic,
                 kind="generate"):
        self.engine = engine
        # the backend's fleet role ("generate" | "decode" | ...): label
        # dimension on every latency series this scheduler observes
        self.kind = str(kind)
        self.queue_capacity = int(
            queue_capacity if queue_capacity is not None
            else flag("generation_queue_capacity"))
        if self.queue_capacity <= 0:
            raise InvalidArgumentError(
                f"generation queue capacity must be positive, got "
                f"{self.queue_capacity}")
        self._clock = clock
        self._q = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closed = False
        self._drain = True
        self._thread = None
        s = engine.slots
        self._slots = [None] * s           # slot -> GenerationRequest
        import numpy as np

        self._last = np.zeros(s, np.int32)
        self._temps = np.zeros(s, np.float32)
        # the engine owns the warmup-snapshot watch (armed by warmup());
        # the loop notes growth through it after every step
        self._watch = engine.watch
        # metrics (get-or-create; shared across scheduler rebuilds)
        self._m_requests = counter("serving/gen_requests_total")
        self._m_responses = counter("serving/gen_responses_total")
        self._m_rejected = counter("serving/gen_rejected_total")
        self._m_expired = counter("serving/gen_expired_total")
        self._m_errors = counter("serving/gen_errors_total")
        self._m_tokens = counter("serving/gen_tokens_total")
        self._m_midbatch = counter("serving/gen_midbatch_admissions_total")
        self._m_depth = gauge("serving/gen_queue_depth")
        self._m_busy = gauge("serving/gen_slots_busy")
        self._h_token = histogram("serving/gen_token_ms")
        self._h_ttft = histogram("serving/gen_ttft_ms")
        self._h_e2e = histogram("serving/gen_e2e_ms")
        from . import _register_live

        _register_live(self)

    # -- client side ---------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._closed

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def live_slots(self) -> int:
        return sum(r is not None for r in self._slots)

    def occupancy(self) -> float:
        return self.live_slots / self.engine.slots

    def extra_compiles(self) -> int:
        return self.engine.extra_compiles()

    def submit(self, prompt, max_new_tokens=None, temperature=None,
               deadline_ms=None, on_token=None,
               tenant=None) -> GenerationRequest:
        """Enqueue one generation request. Validation happens at
        ADMISSION TIME here (a malformed prompt must be rejected before
        it can occupy a decode slot); a full queue raises
        :class:`QueueFullError` (HTTP 429)."""
        prompt = [int(t) for t in prompt]
        max_new = (self.engine.default_max_new_tokens
                   if max_new_tokens is None else int(max_new_tokens))
        self.engine.validate(prompt, max_new)
        now = self._clock()
        deadline = (now + float(deadline_ms) / 1e3
                    if deadline_ms is not None and float(deadline_ms) > 0
                    else None)
        req = GenerationRequest(prompt, max_new, temperature, deadline,
                                now, on_token=on_token, tenant=tenant)
        return self._enqueue(req)

    def _enqueue(self, req) -> GenerationRequest:
        """The one admission gate both submit paths share: closed
        check, bounded-queue backpressure (429), enqueue + notify."""
        with self._lock:
            if self._closed:
                raise ServingClosedError(
                    "generation scheduler is shut down; no new requests")
            if len(self._q) >= self.queue_capacity:
                self._m_rejected.inc()
                _flight.record_event(
                    "generation_reject", reason="queue_full",
                    depth=len(self._q), capacity=self.queue_capacity)
                raise QueueFullError(
                    f"generation queue full ({self.queue_capacity} "
                    "requests queued); backpressure — retry with backoff")
            self._q.append(req)
            self._m_depth.set(len(self._q))
            self._not_empty.notify()
        self._m_requests.inc()
        return req

    def submit_prefilled(self, planes, length, first_token,
                         max_new_tokens=None, temperature=None,
                         deadline_ms=None, on_token=None,
                         prompt=None, tenant=None) -> GenerationRequest:
        """Enqueue a handed-off generation: the prompt was prefilled on
        a PREFILL-tier backend and arrives as a KV slab (window-width
        per-slot planes + true length + the first sampled token).
        Admission becomes a single functional cache insert instead of a
        prefill forward; everything downstream (queue contracts,
        deadlines, streaming, completion) is the normal request path.
        ``prompt`` (the token ids) is required by speculative engines —
        the draft ring must be prefilled at admission."""
        length = int(length)
        if not 1 <= length <= self.engine.cache_len:
            raise InvalidArgumentError(
                f"handoff prompt length {length} outside "
                f"[1, {self.engine.cache_len}]")
        max_new = (self.engine.default_max_new_tokens
                   if max_new_tokens is None else int(max_new_tokens))
        if max_new < 1:
            raise InvalidArgumentError(
                f"max_new_tokens must be >= 1, got {max_new}")
        if length + max_new > self.engine.max_positions:
            raise InvalidArgumentError(
                f"prompt ({length}) + max_new_tokens ({max_new}) "
                f"exceeds max_position_embeddings "
                f"{self.engine.max_positions}")
        if self.engine.speculative and prompt is None:
            raise InvalidArgumentError(
                "a speculative decode tier needs the prompt tokens with "
                "the KV slab (draft ring prefill at admission)")
        now = self._clock()
        deadline = (now + float(deadline_ms) / 1e3
                    if deadline_ms is not None and float(deadline_ms) > 0
                    else None)
        req = GenerationRequest(
            prompt, max_new, temperature, deadline, now,
            on_token=on_token, prompt_len=length,
            handoff=(planes, length, int(first_token)), tenant=tenant)
        return self._enqueue(req)

    def submit_prefilled_pages(self, slab: PageSlab, max_new_tokens=None,
                               temperature=None, deadline_ms=None,
                               on_token=None, tenant=None,
                               prompt=None) -> GenerationRequest:
        """Enqueue a PAGE-GRANULAR handoff (``handoff.PageSlab``): the
        prefill tier shipped only the pages this decode tier's prefix
        index does not already hold; admission maps known pages
        copy-on-write and installs the shipped ones into freshly
        allocated pool pages. Requires ``kv_cache_layout=paged``."""
        if not getattr(self.engine, "paged", False):
            raise InvalidArgumentError(
                "page-granular handoff needs kv_cache_layout=paged on "
                "the decode tier")
        length = int(slab.length)
        if not 1 <= length <= self.engine.cache_len:
            raise InvalidArgumentError(
                f"handoff prompt length {length} outside "
                f"[1, {self.engine.cache_len}]")
        max_new = (self.engine.default_max_new_tokens
                   if max_new_tokens is None else int(max_new_tokens))
        if max_new < 1:
            raise InvalidArgumentError(
                f"max_new_tokens must be >= 1, got {max_new}")
        if length + max_new > self.engine.max_positions:
            raise InvalidArgumentError(
                f"prompt ({length}) + max_new_tokens ({max_new}) "
                f"exceeds max_position_embeddings "
                f"{self.engine.max_positions}")
        now = self._clock()
        deadline = (now + float(deadline_ms) / 1e3
                    if deadline_ms is not None and float(deadline_ms) > 0
                    else None)
        req = GenerationRequest(
            prompt, max_new, temperature, deadline, now,
            on_token=on_token, prompt_len=length, handoff=slab,
            tenant=tenant)
        return self._enqueue(req)

    def generate(self, prompt, max_new_tokens=None, temperature=None,
                 timeout=None) -> list:
        """Synchronous convenience: submit + wait."""
        return self.submit(prompt, max_new_tokens, temperature).wait(timeout)

    # -- decode loop ---------------------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._thread = threading.Thread(
            target=self._loop, name="ptpu-generation-decode", daemon=True)
        self._thread.start()
        return self

    def _pop_expired_locked(self, now):
        while self._q and self._q[0].expired(now):
            req = self._q.popleft()
            self._m_depth.set(len(self._q))
            self._m_expired.inc()
            _flight.record_event(
                "generation_deadline_expired",
                queued_ms=round((now - req.t_submit) * 1e3, 3))
            # queue-wait is this request's whole story: record it
            # errored and flag the trace — a deadline miss is never the
            # trace the tail sampler drops
            _tracing.record_interval(
                "serving::queue_wait", req.trace, req.t_submit, now,
                error="deadline exceeded in queue",
                prompt_tokens=req.prompt_len)
            _tracing.flag_trace(req.trace, "deadline")
            req.done(error=DeadlineExceededError(
                f"generation deadline passed after "
                f"{(now - req.t_submit) * 1e3:.1f}ms in queue; "
                "never admitted to a slot"))

    def _finished_reason(self, req):
        if (self.engine.eos_id is not None
                and req.tokens and req.tokens[-1] == self.engine.eos_id):
            return "eos"
        if len(req.tokens) >= req.max_new_tokens:
            return "length"
        return None

    def _deliver(self, req, tok):
        req.tokens.append(int(tok))
        self._m_tokens.inc()
        if req.on_token is not None:
            try:
                req.on_token(int(tok))
            except Exception:  # a slow/broken stream must not stall decode
                req.on_token = None

    def _complete(self, req, reason):
        req.finish_reason = reason
        now = self._clock()
        # one decode span per REQUEST (first token -> finish), not per
        # token: a long generation must not eat the trace's span budget
        _tracing.record_interval(
            "serving::decode", req.trace,
            req.t_first_token if req.t_first_token is not None
            else req.t_submit,
            now, tokens=len(req.tokens), finish_reason=reason)
        # labeled observe: the child propagates into the bare family,
        # so /histz merges keep exact totals while /metricz gains the
        # per-kind/per-tenant series
        self._h_e2e.labels(kind=self.kind, tenant=req.tenant).observe(
            (now - req.t_submit) * 1e3)
        self._m_responses.inc()
        _flight.record_event(
            "generation_complete", reason=reason,
            prompt_tokens=req.prompt_len, tokens=len(req.tokens))
        req.done()

    def _admit_ready(self):
        """Fill vacant slots from the queue (the continuous-batching
        move: admission happens between decode steps, never tearing the
        running batch down)."""
        engine = self.engine
        while True:
            with self._lock:
                now = self._clock()
                self._pop_expired_locked(now)
                if not self._q:
                    return
                free = next((s for s, r in enumerate(self._slots)
                             if r is None), None)
                if free is None:
                    return
                # paged layout: a vacant slot is NOT capacity — the
                # page pool is. Leave the head queued until enough
                # free or evictable pages exist (slots release pages
                # as sequences finish); ring layout always passes.
                head = self._q[0]
                if not engine.has_capacity(
                        head.prompt if head.handoff is None
                        and head.prompt is not None
                        else head.prompt_len):
                    return
                req = self._q.popleft()
                self._m_depth.set(len(self._q))
            midbatch = self.live_slots > 0
            t_admit = self._clock()
            # queue-wait is knowable only now: record it backwards into
            # the member trace, then time the prefill as a
            # slot-admission span carrying the bucket-padding waste the
            # p99 post-mortem needs (engine._dispatch annotates it with
            # the cache disposition + FLOPs while it is current)
            _tracing.record_interval(
                "serving::queue_wait", req.trace, req.t_submit, t_admit,
                prompt_tokens=req.prompt_len)
            if req.handoff is not None:
                # a prefill-tier forward already happened elsewhere;
                # admission is one functional cache insert
                asp = _tracing.begin_span(
                    "serving::slot_admission", slot=free,
                    midbatch=midbatch, handoff=True,
                    prompt_tokens=req.prompt_len)
            else:
                bucket = engine.bucket_for(len(req.prompt))
                asp = _tracing.begin_span(
                    "serving::slot_admission", slot=free,
                    midbatch=midbatch,
                    bucket=bucket, prompt_tokens=req.prompt_len,
                    padded_tokens=bucket - len(req.prompt),
                    fill=round(len(req.prompt) / bucket, 4))
            try:
                with _tracing.use_span(asp):
                    if isinstance(req.handoff, PageSlab):
                        slab = req.handoff
                        tok = engine.admit_prefilled_pages(
                            free, slab.pages, slab.length,
                            slab.first_token,
                            page_size=slab.page_size,
                            tenant=req.tenant)
                    elif req.handoff is not None:
                        planes, length, first = req.handoff
                        tok = engine.admit_prefilled(
                            free, planes, length, first,
                            prompt=req.prompt)
                    else:
                        tok = engine.admit(free, req.prompt,
                                           req.temperature,
                                           tenant=req.tenant)
            except Exception as e:  # noqa: BLE001 — the loop must survive
                asp.set_error(f"{type(e).__name__}: {e}")
                _tracing.record_fanin(asp, [req.trace])
                _tracing.flag_trace(req.trace, "error")
                self._m_errors.inc()
                req.done(error=e)
                continue
            _tracing.record_fanin(asp, [req.trace])
            with self._lock:
                if self._closed and not self._drain:
                    # stop(drain=False) landed while this request was in
                    # flight between the queue pop and slot install — it
                    # was promised a failure, not a quiet completion
                    self._m_errors.inc()
                    req.done(error=ServingClosedError(
                        "generation scheduler shut down before the "
                        "request reached a decode slot"))
                    continue
            req.t_first_token = self._clock()
            self._h_ttft.labels(kind=self.kind, tenant=req.tenant).observe(
                (req.t_first_token - req.t_submit) * 1e3)
            if midbatch:
                self._m_midbatch.inc()
            _flight.record_event(
                "generation_admit", slot=free, midbatch=midbatch,
                prompt_tokens=req.prompt_len,
                queued_ms=round(
                    (req.t_first_token - req.t_submit) * 1e3, 3))
            self._deliver(req, tok)
            reason = self._finished_reason(req)
            if reason is not None:
                engine.release_slot(free)
                self._complete(req, reason)
                continue
            self._slots[free] = req
            self._last[free] = tok
            self._temps[free] = (
                self.engine.default_temperature
                if req.temperature is None else float(req.temperature))
            self._m_busy.set(self.live_slots)

    def _loop(self):
        engine = self.engine
        while True:
            self._admit_ready()
            busy = [s for s, r in enumerate(self._slots) if r is not None]
            if not busy:
                with self._lock:
                    if self._closed and not self._q:
                        break
                    if not self._q:
                        self._not_empty.wait(0.05)
                continue
            t0 = self._clock()
            try:
                if engine.speculative:
                    # one draft+verify round: every busy slot emits
                    # 1..k+1 tokens (the scheduler truncates at its own
                    # EOS/budget, exactly like the one-token path)
                    ts, counts = engine.spec_step(
                        self._last, self._temps, busy=busy)
                else:
                    nxt = engine.step(self._last, self._temps)
            except Exception as e:  # noqa: BLE001 — fail THESE, keep serving
                for s in busy:
                    req, self._slots[s] = self._slots[s], None
                    engine.release_slot(s)
                    self._m_errors.inc()
                    _tracing.record_interval(
                        "serving::decode", req.trace,
                        req.t_first_token if req.t_first_token is not None
                        else req.t_submit,
                        error=f"{type(e).__name__}: {e}",
                        tokens=len(req.tokens))
                    _tracing.flag_trace(req.trace, "error")
                    req.done(error=e)
                self._m_busy.set(0)
                _flight.record_event(
                    "generation_step_error", slots=len(busy),
                    error=f"{type(e).__name__}: {e}"[:300])
                continue
            dt_ms = (self._clock() - t0) * 1e3
            if self._watch.armed:
                self._watch.note(slots=len(busy))
            emitted = 0
            for s in busy:
                req = self._slots[s]
                if req is None or req.finished:  # stop(drain=False) race
                    self._slots[s] = None
                    engine.release_slot(s)
                    continue
                reason = None
                if engine.speculative:
                    for i in range(int(counts[s])):
                        self._deliver(req, ts[s, i])
                        self._last[s] = ts[s, i]
                        emitted += 1
                        reason = self._finished_reason(req)
                        if reason is not None:
                            break
                else:
                    self._deliver(req, nxt[s])
                    self._last[s] = nxt[s]
                    emitted += 1
                    reason = self._finished_reason(req)
                if reason is not None:
                    self._slots[s] = None
                    engine.release_slot(s)
                    self._complete(req, reason)
            # per-token latency, per STREAM (what a client waits between
            # tokens): the plain path observes the step time unchanged;
            # a speculative round amortizes its two dispatches over the
            # mean tokens each busy stream emitted
            # kind-labeled only: one step serves slots of mixed tenants
            h_token = self._h_token.labels(kind=self.kind)
            if engine.speculative and emitted:
                h_token.observe(dt_ms * len(busy) / emitted)
            else:
                h_token.observe(dt_ms)
            self._m_busy.set(self.live_slots)
        # drained exit: nothing queued, nothing active
        self._m_busy.set(self.live_slots)

    # -- lifecycle -----------------------------------------------------------

    def close(self, drain=True):
        """Refuse new requests. ``drain=True`` lets the decode loop
        finish everything queued AND active; ``drain=False`` fails
        queued requests immediately (active ones still finish their
        current step and are failed by ``stop``)."""
        with self._lock:
            if self._closed and not self._q:
                return
            self._closed = True
            self._drain = drain
            dropped = []
            if not drain:
                dropped = list(self._q)
                self._q.clear()
            self._m_depth.set(len(self._q))
            self._not_empty.notify_all()
        for req in dropped:
            self._m_errors.inc()
            req.done(error=ServingClosedError(
                "generation scheduler shut down before admission"))
        _flight.record_event("generation_close", drain=drain,
                             dropped=len(dropped))

    def stop(self, drain=True, timeout=30.0):
        """Close and join the decode loop. With ``drain=False`` active
        sequences are failed instead of run to completion."""
        self.close(drain=drain)
        if not drain:
            self._fail_pending("generation scheduler shut down "
                               "mid-sequence")
        t = self._thread
        if t is not None:
            t.join(timeout)
        if t is None or not t.is_alive():
            # a drain-stop with no live loop (never started, or it died)
            # would otherwise strand queued/slot requests un-completed
            # forever — their waiters must get an error, not a hang
            self._thread = None
            self._fail_pending("generation scheduler stopped with no "
                               "decode loop to drain the request")

    def _fail_pending(self, why):
        with self._lock:
            dropped = list(self._q)
            self._q.clear()
            self._m_depth.set(len(self._q))
        for s, req in enumerate(self._slots):
            if req is not None:
                self._slots[s] = None
                self.engine.release_slot(s)
                if not req.finished:
                    dropped.append(req)
        for req in dropped:
            if not req.finished:
                self._m_errors.inc()
                req.done(error=ServingClosedError(why))
        self._m_busy.set(0)

    @property
    def alive(self) -> int:
        t = self._thread
        return int(t is not None and t.is_alive())
