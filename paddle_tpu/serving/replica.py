"""Replica pool: N worker threads serving one shared executable cache.

Throughput needs concurrent dispatch (one thread's device wait must not
idle the queue), but naive replication would pay N compiles of the same
program. ``Predictor.clone()`` shares the underlying Executor — and with
it the RunPlan + jit/AOT executable caches — so every replica serves
from the SAME compiled programs: N workers, zero extra compiles
(asserted: the pool snapshots the jit-miss counter after warmup and
counts any later miss as an ``unexpected_compile``).

Warmup compiles every bucket of the batcher's ladder ahead of traffic
(zero-filled synthetic batches through one replica — the shared cache
warms them all), so the first real request never pays an XLA compile and
readiness (`/healthz`) can gate on warmup-complete.
"""
from __future__ import annotations

import threading

import numpy as np

from ..errors import InvalidArgumentError
from ..flags import flag
from ..monitor import histogram
from ..monitor import flight_recorder as _flight
from ..monitor import tracing as _tracing
from ..profiler import RecordEvent, counters as _profiler_counters
# CompileWatch now lives in the shared compiled-callable runtime (it is
# the unexpected-compile half of the runtime's accounting); re-exported
# here for the historical import path
from ..runtime.compiled import CompileWatch  # noqa: F401

__all__ = ["ReplicaPool", "CompileWatch", "predictor_input_specs"]

_JIT_MISS = "executor::jit_cache_miss"


def predictor_input_specs(predictor) -> dict:
    """Per-feed (feature_shape, dtype) from the predictor's program vars:
    the leading (batch) axis is stripped; remaining dims must be static
    so warmup can synthesize bucket-shaped batches."""
    block = predictor._program.global_block()
    specs = {}
    for name in predictor.get_input_names():
        if not block.has_var(name):
            raise InvalidArgumentError(
                f"feed {name!r} has no var in the inference program")
        v = block.var(name)
        if v.shape is None or len(v.shape) < 1:
            raise InvalidArgumentError(
                f"feed {name!r} needs a ranked shape with a leading "
                f"batch axis, got {v.shape!r}")
        feat = tuple(int(d) for d in v.shape[1:])
        if any(d < 0 for d in feat):
            raise InvalidArgumentError(
                f"feed {name!r} has dynamic feature dims {v.shape!r}; "
                "only the leading batch axis may be dynamic for serving")
        specs[name] = (feat, v.dtype)
    return specs


class ReplicaPool:
    """Worker threads pulling assembled batches from a DynamicBatcher
    and dispatching them on Predictor clones."""

    def __init__(self, predictor, batcher, replicas=None):
        n = int(replicas if replicas is not None else flag("serving_replicas"))
        if n <= 0:
            raise InvalidArgumentError(
                f"serving replica count must be positive, got {n}")
        self.batcher = batcher
        self.replicas = n
        # replica 0 is the caller's predictor; the rest are clones that
        # share its Executor (and therefore every compiled program)
        self._preds = [predictor] + [predictor.clone() for _ in range(n - 1)]
        self._specs = predictor_input_specs(predictor)
        # arm admission-time feature-shape validation on a bare batcher:
        # a request that couldn't concatenate must be rejected at
        # submit(), never fail the batch it was co-assembled into
        if batcher.input_specs is None:
            batcher.input_specs = dict(self._specs)
        self._threads = []
        self._stop = threading.Event()
        # cleared = paused (workers park before pulling the next batch);
        # the 429/drain tests and maintenance windows use this
        self._live = threading.Event()
        self._live.set()
        self.warmed = False
        self._watch = CompileWatch(
            lambda: _profiler_counters().get(_JIT_MISS, 0))
        self._h_dispatch = histogram("serving/dispatch_ms")
        from . import _register_live

        _register_live(self)

    # -- warmup --------------------------------------------------------------

    def _synthetic_feed(self, bucket):
        return {
            name: np.zeros((bucket,) + feat, dtype=dtype)
            for name, (feat, dtype) in self._specs.items()
        }

    def warmup(self):
        """Compile every bucket ahead of traffic on one replica (the
        shared cache warms all of them), then snapshot the jit-miss
        counter: any later miss is an unexpected compile. Idempotent."""
        if self.warmed:
            return self
        # warm on a DEDICATED clone: workers may already be serving
        # direct batcher.submit() traffic on self._preds[0], and
        # Predictor.run stages inputs through per-predictor IO handles —
        # sharing one would let warmup's zero batches overwrite a live
        # request between staging and dispatch. The clone shares the
        # executable cache, which is all warmup needs.
        pred = self._preds[0].clone()
        names = pred.get_input_names()
        for bucket in self.batcher.buckets:
            feed = self._synthetic_feed(bucket)
            with RecordEvent("serving::warmup"):
                pred.run([feed[n] for n in names])
        self._watch.arm()
        self.warmed = True
        _flight.record_event(
            "serving_warmup", buckets=list(self.batcher.buckets),
            replicas=self.replicas)
        return self

    def extra_compiles(self) -> int:
        """Jit-cache misses since warmup — the bounded-compile assertion:
        steady-state serving must keep this at 0."""
        return self._watch.extra()

    # -- worker loop ---------------------------------------------------------

    def start(self):
        if self._threads:
            return self
        self._stop.clear()
        for i, pred in enumerate(self._preds):
            t = threading.Thread(
                target=self._worker, args=(i, pred),
                name=f"ptpu-serving-replica-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def _worker(self, idx, pred):
        names = pred.get_input_names()
        batcher = self.batcher
        while True:
            self._live.wait()
            if self._stop.is_set() and not (batcher.closed
                                            and batcher.queue_depth()):
                break
            batch = batcher.next_batch(timeout=0.05)
            if batch is None:
                if batcher.closed:
                    break  # closed AND drained
                continue
            # ONE dispatch span serves the whole co-batch: made current
            # while the executor runs (so it annotates the span with
            # its plan/jit cache disposition and CostRecord FLOPs),
            # then fanned into every member trace with links naming all
            # members — each trace shows both its own dispatch cost and
            # who it shared the program with
            dsp = _tracing.begin_span(
                "serving::dispatch", bucket=batch.bucket,
                rows=batch.rows, requests=len(batch.requests),
                replica=idx)
            fanned = False
            try:
                with RecordEvent("serving::dispatch"), \
                        _tracing.use_span(dsp):
                    outs = pred.run([batch.feed[n] for n in names])
                    # materialize before slicing (lazy fetch list)
                    outs = [np.asarray(o) for o in outs]
                dsp.end()
                self._h_dispatch.observe(
                    (batcher._clock() - batch.t_ready) * 1e3)
                if self.warmed:
                    self._note_unexpected_compiles(idx, batch.bucket)
                _tracing.record_fanin(
                    dsp, [r.trace for r in batch.requests])
                fanned = True
                batcher.complete(batch, outs)
            except Exception as e:  # noqa: BLE001 — worker must survive
                dsp.set_error(f"{type(e).__name__}: {e}")
                if not fanned:  # complete() failing must not double-fan
                    _tracing.record_fanin(
                        dsp, [r.trace for r in batch.requests])
                batcher.fail(batch, e)

    def _note_unexpected_compiles(self, replica_idx, bucket):
        """The ladder invariant broke (a feed escaped the buckets, or
        the program changed under us): count it loudly rather than
        silently re-growing the cache."""
        self._watch.note(replica=replica_idx, bucket=bucket)

    # -- lifecycle -----------------------------------------------------------

    def pause(self):
        """Freeze batch hand-out (in-flight dispatches finish). The gate
        lives in the batcher, so it holds even for workers already
        blocked inside ``next_batch`` — queued requests wait and the
        bounded queue exerts backpressure. The deterministic handle the
        429/deadline tests and maintenance windows need."""
        self._live.clear()
        self.batcher.pause()

    def resume(self):
        self.batcher.resume()
        self._live.set()

    @property
    def alive(self) -> int:
        return sum(t.is_alive() for t in self._threads)

    def stop(self, drain=True, timeout=10.0):
        """Stop the workers. ``drain=True`` closes the batcher but lets
        workers flush everything already queued before they exit."""
        self.batcher.close(drain=drain)
        self._stop.set()
        self._live.set()  # a paused pool must still be able to exit/drain
        for t in self._threads:
            t.join(timeout)
        self._threads = []
