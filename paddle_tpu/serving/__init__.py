"""Online serving subsystem: dynamic batcher, replica pool, HTTP frontend.

Layers (each usable alone) on top of ``paddle_tpu.inference.Predictor``:

- :mod:`serving.batcher` — shape-bucketed dynamic batching with a
  bounded admission queue, per-request deadlines, and zero-padding up to
  a configured bucket ladder (``FLAGS_serving_batch_buckets``) so the
  steady-state XLA compile count is bounded by the ladder length.
- :mod:`serving.replica` — a replica pool of ``Predictor.clone()``
  workers sharing ONE jit/AOT executable cache (N threads, zero extra
  compiles), warmed up bucket-by-bucket before readiness.
- :mod:`serving.server` — stdlib ThreadingHTTPServer frontend
  (``/predict``, ``/healthz`` readiness, ``/statz``, ``/metrics``) with
  429 backpressure on a full queue and graceful drain on shutdown.
- :mod:`serving.continuous` — CONTINUOUS BATCHING for autoregressive
  generation: a slot scheduler over ``generation.GenerationEngine``
  where finished sequences vacate their decode slot mid-batch and queued
  requests are admitted at the next step; served by
  :class:`GenerationServer` (``/generate``, streaming-friendly, with
  tokens/sec + slot occupancy + per-token latency on ``/statz``).
- :mod:`serving.sharded` — GSPMD-SHARDED backends: commit the loaded
  weights and feeds onto a device mesh per ``parallel.ShardingRules``
  PartitionSpecs and the predictor's compiled program becomes a
  partitioned program — one logical backend spanning a multi-device
  world, executor caches/donation untouched.
- :mod:`serving.router` — the FLEET tier: a front door spreading
  ``/predict``/``/generate`` over N independent backend processes with
  power-of-two-choices dispatch on their ``/loadz`` signals, health
  probes with eviction/readmission, retry-on-next-backend for
  connection failures (never for answered work), and fleet p50/p99
  merged exactly from backend ``/histz`` bucket counts.
- :mod:`serving.scaler` — metrics-driven AUTOSCALING: hysteresis +
  cooldown decisions over router aggregates and ``monitor/cluster``
  snapshots, acting through a pluggable backend launcher
  (:class:`SubprocessLauncher` boots ``python -m
  paddle_tpu.serving.backend`` processes with port-file discovery).

Quickstart::

    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.serving import InferenceServer

    srv = InferenceServer(create_predictor(Config(model_dir)),
                          port=8500, replicas=4).start()
    # POST {"inputs": {"x": [[...]]}} to http://127.0.0.1:8500/predict
    srv.stop(drain=True)

or, from a trained high-level model: ``model.serve(input_spec=[...])``.
"""
from __future__ import annotations

import atexit
import weakref

from .batcher import (  # noqa: F401
    DeadlineExceededError,
    DynamicBatcher,
    QueueFullError,
    ServingClosedError,
    parse_buckets,
)
from .replica import CompileWatch, ReplicaPool, predictor_input_specs  # noqa: F401
from .continuous import ContinuousBatcher, GenerationRequest  # noqa: F401
from .server import GenerationServer, InferenceServer  # noqa: F401
from .sharded import ShardedPredictor, shard_predictor  # noqa: F401
from .router import (  # noqa: F401
    BackendState,
    BackendTimeoutError,
    BackendUnavailableError,
    NoBackendError,
    Router,
)
from .scaler import (  # noqa: F401
    AutoScaler,
    FleetSignals,
    LaunchedBackend,
    SubprocessLauncher,
)

__all__ = [
    "DynamicBatcher", "ReplicaPool", "InferenceServer",
    "ContinuousBatcher", "GenerationRequest", "GenerationServer",
    "CompileWatch",
    "ShardedPredictor", "shard_predictor",
    "Router", "BackendState", "NoBackendError",
    "BackendUnavailableError", "BackendTimeoutError",
    "AutoScaler", "FleetSignals", "SubprocessLauncher",
    "LaunchedBackend",
    "QueueFullError", "DeadlineExceededError", "ServingClosedError",
    "parse_buckets", "predictor_input_specs", "shutdown_all",
]

# every live batcher/pool/server registers itself here so one call can
# tear the whole subsystem down (tests must not leak serving threads
# across the suite — see tests/conftest.py)
_live = weakref.WeakSet()


def _register_live(obj):
    _live.add(obj)


def shutdown_all():
    """Stop every live server, pool, and batcher (idempotent; exceptions
    swallowed — this is the test-teardown / atexit path, where a
    half-constructed object must not mask the real failure)."""
    # fleet tier first (the scaler owns backend PROCESSES, the router
    # fronts the servers), then servers (they drain their own
    # pool/scheduler+batcher), then bare pools/schedulers, then bare
    # batchers — reverse dependency order
    objs = list(_live)
    for cls in (AutoScaler, Router, InferenceServer, GenerationServer,
                ReplicaPool, ContinuousBatcher, DynamicBatcher):
        for obj in objs:
            if type(obj) is not cls:
                continue
            try:
                if cls is DynamicBatcher:
                    obj.close(drain=False)
                else:
                    obj.stop(drain=False, timeout=2.0)
            except Exception:
                pass


# a replica worker parked inside XLA while the interpreter tears down
# aborts the process ("terminate called without an active exception");
# stop the whole subsystem before Python starts dying
atexit.register(shutdown_all)
