"""Online serving subsystem: dynamic batcher, replica pool, HTTP frontend.

Layers (each usable alone) on top of ``paddle_tpu.inference.Predictor``:

- :mod:`serving.batcher` — shape-bucketed dynamic batching with a
  bounded admission queue, per-request deadlines, and zero-padding up to
  a configured bucket ladder (``FLAGS_serving_batch_buckets``) so the
  steady-state XLA compile count is bounded by the ladder length.
- :mod:`serving.replica` — a replica pool of ``Predictor.clone()``
  workers sharing ONE jit/AOT executable cache (N threads, zero extra
  compiles), warmed up bucket-by-bucket before readiness.
- :mod:`serving.server` — stdlib ThreadingHTTPServer frontend
  (``/predict``, ``/healthz`` readiness, ``/statz``, ``/metrics``) with
  429 backpressure on a full queue and graceful drain on shutdown.
- :mod:`serving.continuous` — CONTINUOUS BATCHING for autoregressive
  generation: a slot scheduler over ``generation.GenerationEngine``
  where finished sequences vacate their decode slot mid-batch and queued
  requests are admitted at the next step; served by
  :class:`GenerationServer` (``/generate``, streaming-friendly, with
  tokens/sec + slot occupancy + per-token latency on ``/statz``).

Quickstart::

    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.serving import InferenceServer

    srv = InferenceServer(create_predictor(Config(model_dir)),
                          port=8500, replicas=4).start()
    # POST {"inputs": {"x": [[...]]}} to http://127.0.0.1:8500/predict
    srv.stop(drain=True)

or, from a trained high-level model: ``model.serve(input_spec=[...])``.
"""
from __future__ import annotations

import atexit
import weakref

from .batcher import (  # noqa: F401
    DeadlineExceededError,
    DynamicBatcher,
    QueueFullError,
    ServingClosedError,
    parse_buckets,
)
from .replica import CompileWatch, ReplicaPool, predictor_input_specs  # noqa: F401
from .continuous import ContinuousBatcher, GenerationRequest  # noqa: F401
from .server import GenerationServer, InferenceServer  # noqa: F401

__all__ = [
    "DynamicBatcher", "ReplicaPool", "InferenceServer",
    "ContinuousBatcher", "GenerationRequest", "GenerationServer",
    "CompileWatch",
    "QueueFullError", "DeadlineExceededError", "ServingClosedError",
    "parse_buckets", "predictor_input_specs", "shutdown_all",
]

# every live batcher/pool/server registers itself here so one call can
# tear the whole subsystem down (tests must not leak serving threads
# across the suite — see tests/conftest.py)
_live = weakref.WeakSet()


def _register_live(obj):
    _live.add(obj)


def shutdown_all():
    """Stop every live server, pool, and batcher (idempotent; exceptions
    swallowed — this is the test-teardown / atexit path, where a
    half-constructed object must not mask the real failure)."""
    # servers first (they drain their own pool/scheduler+batcher), then
    # bare pools/schedulers, then bare batchers — reverse dependency order
    objs = list(_live)
    for cls in (InferenceServer, GenerationServer, ReplicaPool,
                ContinuousBatcher, DynamicBatcher):
        for obj in objs:
            if type(obj) is not cls:
                continue
            try:
                if cls is DynamicBatcher:
                    obj.close(drain=False)
                else:
                    obj.stop(drain=False, timeout=2.0)
            except Exception:
                pass


# a replica worker parked inside XLA while the interpreter tears down
# aborts the process ("terminate called without an active exception");
# stop the whole subsystem before Python starts dying
atexit.register(shutdown_all)
