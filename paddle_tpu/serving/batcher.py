"""Dynamic batching engine for online inference.

Production TPU serving gets its throughput from batch parallelism, but a
naive "batch whatever arrived" policy compiles a new XLA program for
every distinct request count — the compile cache grows with traffic, not
with the model. This batcher pads every assembled batch up to a small
ladder of bucketed batch sizes (``FLAGS_serving_batch_buckets``, powers
of two by default), so the steady-state compile count is bounded by the
ladder length no matter what the traffic mix looks like — the
bounded-compile-cache discipline, applied to the batch axis.

Mechanics:

- ``submit()`` validates the request and appends it to a BOUNDED queue;
  a full queue rejects with :class:`QueueFullError` (the HTTP frontend
  maps it to 429) instead of queueing unboundedly — under overload the
  caller learns to back off while memory stays flat.
- Replica workers call ``next_batch()``: it blocks for the first live
  request, gathers more until the largest bucket fills or the assembly
  window (``FLAGS_serving_batch_timeout_ms``) closes, drops requests
  whose deadline already passed (they complete with
  :class:`DeadlineExceededError` WITHOUT dispatching), concatenates the
  survivors along the batch axis, and zero-pads up to the smallest
  covering bucket.
- ``complete()`` slices the padded outputs back per request; padding
  rows are computed and discarded (numerically inert: they ride along in
  the same fused program, results for real rows are identical to an
  unbatched run — asserted by golden tests).

Everything reports into the monitor stack: queue-depth / batch-fill
gauges, per-stage latency histograms (queue / assemble / dispatch /
end-to-end), request counters in the Prometheus dump, and batcher
events in the flight recorder.
"""
from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from ..errors import (
    ExecutionTimeoutError,
    InvalidArgumentError,
    ResourceExhaustedError,
    UnavailableError,
)
from ..flags import flag
from ..monitor import counter, gauge, histogram
from ..monitor import flight_recorder as _flight
from ..monitor import tracing as _tracing
from ..profiler import RecordEvent

__all__ = [
    "DynamicBatcher", "QueueFullError", "DeadlineExceededError",
    "ServingClosedError", "parse_buckets",
]


class QueueFullError(ResourceExhaustedError):
    """The bounded admission queue is full: back off and retry (429)."""


class DeadlineExceededError(ExecutionTimeoutError):
    """The request's deadline passed while it waited; never dispatched."""


class ServingClosedError(UnavailableError):
    """The batcher is shut down (or draining) and accepts no new work."""


def parse_buckets(spec) -> tuple:
    """Parse a bucket ladder ("1,2,4,8" or an int sequence) into a
    strictly ascending tuple of positive batch sizes."""
    if isinstance(spec, str):
        parts = [p.strip() for p in spec.split(",") if p.strip()]
        try:
            vals = tuple(int(p) for p in parts)
        except ValueError:
            raise InvalidArgumentError(
                f"serving_batch_buckets {spec!r} is not a comma-separated "
                "int list") from None
    else:
        vals = tuple(int(v) for v in spec)
    if not vals or any(v <= 0 for v in vals) or list(vals) != sorted(set(vals)):
        raise InvalidArgumentError(
            f"serving batch buckets must be strictly ascending positive "
            f"ints, got {vals!r}")
    return vals


class _Request:
    """One submitted prediction: inputs with a leading batch axis, an
    optional absolute deadline, and a completion event the submitter
    waits on."""

    __slots__ = ("inputs", "rows", "deadline", "t_submit", "tenant",
                 "result", "error", "trace", "_done")

    def __init__(self, inputs, rows, deadline, t_submit, tenant="default"):
        self.inputs = inputs
        self.rows = rows
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.t_submit = t_submit
        self.tenant = tenant  # label dimension on the stage histograms
        self.result = None
        self.error = None
        # the submitter's trace context (the HTTP handler's server
        # span): queue-wait/assemble/dispatch spans recorded by worker
        # threads hang under it — the identity crosses the thread hop
        self.trace = _tracing.current_context()
        self._done = threading.Event()

    def expired(self, now) -> bool:
        return self.deadline is not None and now > self.deadline

    def done(self, result=None, error=None):
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout=None):
        """Block until completion; returns the per-fetch output list
        (batch axis = this request's rows) or raises the stored error."""
        if not self._done.wait(timeout):
            raise ExecutionTimeoutError(
                f"serving request not completed within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.result


class _Batch:
    """An assembled, padded batch ready for one replica dispatch."""

    __slots__ = ("requests", "bucket", "rows", "feed", "t_ready")

    def __init__(self, requests, bucket, rows, feed, t_ready):
        self.requests = requests
        self.bucket = bucket  # padded batch-axis size (a ladder entry)
        self.rows = rows      # real rows (sum over requests)
        self.feed = feed      # name -> padded (bucket, *feature) array
        self.t_ready = t_ready


class DynamicBatcher:
    """Bounded-queue dynamic batcher over a fixed feed-name set.

    ``feed_names`` fixes the request schema (every request must supply
    exactly these inputs, each with the same leading row count).
    Workers drive it via ``next_batch()`` / ``complete()`` / ``fail()``;
    clients via ``submit()`` (async) or ``predict()`` (sync).
    """

    def __init__(self, feed_names, buckets=None, queue_capacity=None,
                 batch_timeout_ms=None, clock=time.monotonic,
                 input_specs=None):
        self.feed_names = list(feed_names)
        # optional {feed: (feature_shape, dtype)}: when set (the replica
        # pool wires it from the predictor's program), submit() rejects
        # feature-shape mismatches at ADMISSION — co-batching a bad
        # request must never poison the innocent requests in its batch
        self.input_specs = dict(input_specs) if input_specs else None
        self.buckets = parse_buckets(
            buckets if buckets is not None
            else flag("serving_batch_buckets"))
        self.queue_capacity = int(
            queue_capacity if queue_capacity is not None
            else flag("serving_queue_capacity"))
        if self.queue_capacity <= 0:
            raise InvalidArgumentError(
                f"serving queue capacity must be positive, got "
                f"{self.queue_capacity}")
        self._batch_timeout_ms = batch_timeout_ms
        self._clock = clock
        self._q = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closed = False
        self._paused = False
        # metrics (get-or-create: shared across batcher rebuilds)
        self._m_depth = gauge("serving/queue_depth")
        self._m_fill = gauge("serving/batch_fill")
        self._m_requests = counter("serving/requests_total")
        self._m_rejected = counter("serving/rejected_total")
        self._m_expired = counter("serving/deadline_expired_total")
        self._m_responses = counter("serving/responses_total")
        self._m_errors = counter("serving/errors_total")
        self._m_batches = counter("serving/batches_total")
        self._m_rows = counter("serving/batched_rows_total")
        self._m_slots = counter("serving/batch_slots_total")
        self._m_pad = counter("serving/padded_rows_total")
        self._h_queue = histogram("serving/queue_ms")
        self._h_assemble = histogram("serving/assemble_ms")
        self._h_e2e = histogram("serving/e2e_ms")
        from . import _register_live  # registration for shutdown_all

        _register_live(self)

    # -- client side ---------------------------------------------------------

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    @property
    def closed(self) -> bool:
        return self._closed

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._q)

    def _batch_window_s(self) -> float:
        ms = self._batch_timeout_ms
        if ms is None:
            ms = flag("serving_batch_timeout_ms")
        return max(0.0, float(ms)) / 1e3

    def _validate(self, inputs) -> int:
        if set(inputs) != set(self.feed_names):
            raise InvalidArgumentError(
                f"serving request inputs {sorted(inputs)} != model feeds "
                f"{sorted(self.feed_names)}")
        rows = None
        for n in self.feed_names:
            a = inputs[n]
            if a.ndim < 1:
                raise InvalidArgumentError(
                    f"serving input {n!r} needs a leading batch axis, "
                    f"got a scalar")
            spec = self.input_specs.get(n) if self.input_specs else None
            if spec is not None and tuple(a.shape[1:]) != tuple(spec[0]):
                raise InvalidArgumentError(
                    f"serving input {n!r} has feature shape "
                    f"{tuple(a.shape[1:])}, model expects {tuple(spec[0])}")
            if rows is None:
                rows = int(a.shape[0])
            elif int(a.shape[0]) != rows:
                raise InvalidArgumentError(
                    f"serving input {n!r} has {a.shape[0]} rows, other "
                    f"inputs have {rows}")
        if rows == 0:
            raise InvalidArgumentError("serving request has zero rows")
        if rows > self.max_batch:
            raise InvalidArgumentError(
                f"serving request has {rows} rows > largest batch bucket "
                f"{self.max_batch}; split the request or raise "
                "FLAGS_serving_batch_buckets")
        return rows

    def submit(self, inputs, deadline_ms=None, tenant=None) -> _Request:
        """Enqueue one request (dict feed_name -> array with leading
        batch axis). Returns the request handle; ``wait()`` it.
        Raises :class:`QueueFullError` on a full queue and
        :class:`ServingClosedError` after ``close()``. ``tenant``
        labels the request's series on the stage histograms (default
        tenant when unset; the registry's cardinality bound keeps a
        hostile value at one ``other`` series)."""
        inputs = {n: np.asarray(v) for n, v in inputs.items()}
        rows = self._validate(inputs)
        if deadline_ms is None:
            d = float(flag("serving_default_deadline_ms"))
            deadline_ms = d if d > 0 else None
        now = self._clock()
        deadline = (now + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        req = _Request(inputs, rows, deadline, now,
                       tenant="default" if tenant is None else str(tenant))
        with self._lock:
            if self._closed:
                raise ServingClosedError(
                    "serving batcher is shut down; no new requests")
            if len(self._q) >= self.queue_capacity:
                self._m_rejected.inc()
                _flight.record_event(
                    "serving_reject", reason="queue_full",
                    depth=len(self._q), capacity=self.queue_capacity)
                raise QueueFullError(
                    f"serving queue full ({self.queue_capacity} requests "
                    "queued); backpressure — retry with backoff")
            self._q.append(req)
            self._m_depth.set(len(self._q))
            self._not_empty.notify()
        self._m_requests.inc()
        return req

    def predict(self, inputs, deadline_ms=None, timeout=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(inputs, deadline_ms).wait(timeout)

    # -- worker side ---------------------------------------------------------

    def _pop_expired_locked(self, now):
        """Drop queue-front requests whose deadline passed (complete them
        with DeadlineExceededError, no dispatch). Lock held."""
        while self._q and self._q[0].expired(now):
            req = self._q.popleft()
            self._m_depth.set(len(self._q))
            self._m_expired.inc()
            _flight.record_event(
                "serving_deadline_expired", rows=req.rows,
                queued_ms=round((now - req.t_submit) * 1e3, 3))
            # the queue-wait span IS the whole story of this request:
            # record it errored and flag the trace so tail sampling
            # retains it unconditionally (the satellite/acceptance
            # contract: a deadline miss is never the trace you drop)
            _tracing.record_interval(
                "serving::queue_wait", req.trace, req.t_submit, now,
                error="deadline exceeded in queue", rows=req.rows)
            _tracing.flag_trace(req.trace, "deadline")
            req.done(error=DeadlineExceededError(
                f"request deadline passed after "
                f"{(now - req.t_submit) * 1e3:.1f}ms in queue; "
                "never dispatched"))

    def next_batch(self, timeout=None):
        """Assemble the next batch (replica workers call this).

        Blocks up to ``timeout`` seconds for a first live request
        (``None``: until one arrives or the batcher closes), then holds
        the batch open for the assembly window to gather more, up to the
        largest bucket. Returns an assembled :class:`_Batch`, or ``None``
        on timeout / when closed and drained.
        """
        with self._not_empty:
            first = None
            wait_until = (self._clock() + timeout
                          if timeout is not None else None)
            while first is None:
                now = self._clock()
                if not self._paused:
                    self._pop_expired_locked(now)
                    if self._q:
                        first = self._q.popleft()
                        self._m_depth.set(len(self._q))
                        break
                    if self._closed:
                        return None  # closed and fully drained
                elif self._closed and not self._q:
                    return None
                if wait_until is not None:
                    remaining = wait_until - now
                    if remaining <= 0:
                        return None
                    self._not_empty.wait(remaining)
                else:
                    self._not_empty.wait()

            t_first = self._clock()
            picked = [first]
            rows = first.rows
            window_end = t_first + self._batch_window_s()
            while rows < self.max_batch:
                now = self._clock()
                self._pop_expired_locked(now)
                if self._q:
                    nxt = self._q[0]
                    if rows + nxt.rows > self.max_batch:
                        break  # next request wouldn't fit: dispatch now
                    self._q.popleft()
                    self._m_depth.set(len(self._q))
                    picked.append(nxt)
                    rows += nxt.rows
                    continue
                if self._closed:
                    break  # draining: flush without waiting the window
                remaining = window_end - now
                if remaining <= 0:
                    break
                self._not_empty.wait(remaining)

        # heavy work (concat + pad) outside the lock; any failure here
        # must fail THESE requests and leave the worker alive — an
        # unvalidated batcher (no input_specs) can still see
        # incompatible feature shapes meet in one np.concatenate
        try:
            return self._assemble(picked, rows, t_first)
        except Exception as e:  # noqa: BLE001 — workers must survive
            for req in picked:
                _tracing.flag_trace(req.trace, "error")
                req.done(error=e)
                self._m_errors.inc()
            _flight.record_event(
                "serving_assemble_error", rows=rows,
                requests=len(picked),
                error=f"{type(e).__name__}: {e}"[:300])
            return None

    def _assemble(self, picked, rows, t_first):
        with RecordEvent("serving::assemble"):
            now = self._clock()
            bucket = next(b for b in self.buckets if b >= rows)
            for req in picked:
                # labeled observe: the child propagates into the bare
                # family, so /histz and the merge goldens keep exact
                # totals while /metricz gains per-dimension series
                self._h_queue.labels(
                    kind="predict", bucket=str(bucket),
                    tenant=req.tenant).observe((now - req.t_submit) * 1e3)
                # queue-wait is knowable only now: record it backwards
                # into each member's trace
                _tracing.record_interval(
                    "serving::queue_wait", req.trace, req.t_submit, now,
                    rows=req.rows)
            asp = _tracing.begin_span("serving::assemble")
            feed = {}
            for n in self.feed_names:
                arr = (picked[0].inputs[n] if len(picked) == 1
                       else np.concatenate([r.inputs[n] for r in picked]))
                if bucket > rows:
                    pad = np.zeros((bucket - rows,) + arr.shape[1:],
                                   arr.dtype)
                    arr = np.concatenate([arr, pad])
                feed[n] = arr
            t_ready = self._clock()
            # one assembly serves every member: the span lands in each
            # member trace, carrying the batch-fill / padding-waste
            # attribution the p99 post-mortem needs
            asp.set_attributes(
                bucket=bucket, rows=rows, requests=len(picked),
                fill=round(rows / bucket, 4), padded_rows=bucket - rows)
            _tracing.record_fanin(asp, [r.trace for r in picked])
            self._h_assemble.observe((t_ready - t_first) * 1e3)
            self._m_batches.inc()
            self._m_rows.inc(rows)
            self._m_slots.inc(bucket)
            self._m_pad.inc(bucket - rows)
            self._m_fill.set(rows / bucket)
            _flight.record_event(
                "serving_batch", bucket=bucket, rows=rows,
                requests=len(picked),
                fill=round(rows / bucket, 4))
            return _Batch(picked, bucket, rows, feed, t_ready)

    def complete(self, batch, outputs):
        """Slice the padded per-fetch ``outputs`` back per request and
        complete each one. Padding rows are discarded here."""
        now = self._clock()
        outs = [np.asarray(o) for o in outputs]
        offset = 0
        for req in batch.requests:
            req_out = [o[offset:offset + req.rows] for o in outs]
            offset += req.rows
            req.done(result=req_out)
            self._m_responses.inc()
            self._h_e2e.labels(
                kind="predict", bucket=str(batch.bucket),
                tenant=req.tenant).observe((now - req.t_submit) * 1e3)

    def fail(self, batch, error):
        """Complete every request of a failed dispatch with ``error``."""
        for req in batch.requests:
            _tracing.flag_trace(req.trace, "error")
            req.done(error=error)
            self._m_errors.inc()
        _flight.record_event(
            "serving_batch_error", bucket=batch.bucket, rows=batch.rows,
            error=f"{type(error).__name__}: {error}"[:300])

    # -- lifecycle -----------------------------------------------------------

    def pause(self):
        """Freeze batch hand-out: ``next_batch`` stops popping (requests
        keep queueing, so the bounded queue exerts backpressure). Takes
        effect even for workers already blocked inside ``next_batch`` —
        the deterministic handle the backpressure/deadline tests and
        maintenance windows need."""
        with self._lock:
            self._paused = True

    def resume(self):
        with self._lock:
            self._paused = False
            self._not_empty.notify_all()

    def close(self, drain=True):
        """Stop accepting new requests. ``drain=True`` leaves queued work
        for the workers to flush (``next_batch`` keeps returning batches
        until the queue empties, then ``None``); ``drain=False`` fails
        everything still queued with :class:`ServingClosedError`."""
        with self._lock:
            if self._closed and not self._q:
                return
            self._closed = True
            self._paused = False  # a paused batcher must still drain
            dropped = []
            if not drain:
                dropped = list(self._q)
                self._q.clear()
            self._m_depth.set(len(self._q))
            self._not_empty.notify_all()
        for req in dropped:
            req.done(error=ServingClosedError(
                "serving batcher shut down before dispatch"))
            self._m_errors.inc()
        _flight.record_event("serving_batcher_close", drain=drain,
                             dropped=len(dropped))
