"""Metrics-driven autoscaling: grow/shrink the backend fleet.

The router balances whatever fleet exists; this module decides how big
that fleet should BE. An :class:`AutoScaler` periodically gathers

- **router-side aggregates** — per-backend queue depth / in-flight from
  the router's probed :class:`~paddle_tpu.serving.router.BackendState`
  table (the same ``/loadz`` signals dispatch uses), and
- **host snapshots** — ``monitor/cluster.py``'s ``local_snapshot()``
  (MFU, HBM watermark, step rate), recorded as evidence with every
  decision so a post-mortem can see what the fleet looked like when the
  scaler acted,

and runs one decision per tick against a pluggable **launcher**:

- *scale up* when mean queue depth per healthy backend sustains at or
  above ``FLAGS_serving_scaler_up_queue_depth`` for
  ``FLAGS_serving_scaler_window`` consecutive evaluations (hysteresis —
  one spiky tick must not flap the fleet), bounded by
  ``FLAGS_serving_scaler_max_backends``;
- *scale down* when the fleet sustains idle (queue depth at or below
  ``FLAGS_serving_scaler_down_queue_depth`` with zero in-flight) for a
  full window, bounded by ``FLAGS_serving_scaler_min_backends`` — the
  victim is the least-loaded backend the scaler itself launched, which
  is first removed from rotation (no new traffic) and then terminated
  through the launcher (SIGTERM -> the backend's graceful drain);
- after ANY action, ``FLAGS_serving_scaler_cooldown_s`` suppresses
  further decisions so a booting backend's warmup cannot be misread as
  sustained pressure.

Decisions, hysteresis, and cooldowns are pure functions of the signal
stream and an injectable clock (``AutoScaler(clock=...)``) — unit tests
drive :meth:`AutoScaler.decide` tick by tick with synthetic
:class:`FleetSignals` and a fake launcher, no processes involved. The
provided :class:`SubprocessLauncher` boots real
``python -m paddle_tpu.serving.backend`` processes with port-file
discovery (ready means warmed: the port file is written after warmup).
"""
from __future__ import annotations

import os
import signal as _signal
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field

from ..errors import InvalidArgumentError, UnavailableError
from ..flags import flag
from ..monitor import cluster as _cluster
from ..monitor import counter, gauge
from ..monitor import flight_recorder as _flight

__all__ = ["AutoScaler", "FleetSignals", "SubprocessLauncher",
           "LaunchedBackend", "launch_process"]


@dataclass
class FleetSignals:
    """One evaluation tick's view of the fleet (inputs to ``decide``).

    ``kinds`` splits the pressure aggregates per backend kind
    (``predict`` / ``generate`` / ``prefill`` / ``decode``): fleet-wide
    means average a saturated decode tier against idle prefill
    backends, which is exactly how a starving tier hides — a
    kind-scoped scaler reads its own tier's split instead. When the
    scaler is constructed with ``kind=...``, the TOP-LEVEL aggregates
    are already that tier's (and ``kind`` names it); ``kinds`` always
    carries the full per-kind view for evidence/debugging."""

    time: float
    backends_total: int
    backends_healthy: int
    mean_queue_depth: float
    max_queue_depth: int
    total_inflight: int
    host: dict = field(default_factory=dict)  # cluster.local_snapshot()
    kind: str | None = None
    kinds: dict = field(default_factory=dict)
    # confirmed SLO burn rate (monitor.slo.current_burn(): max over
    # objectives of min(fast, slow) window burn) — queue depth says the
    # fleet is BUSY, burn says users are already losing error budget
    slo_burn: float = 0.0


def _kind_split(states) -> dict:
    """Per-kind pressure aggregates over in-rotation backends (a
    kind-unknown backend is booting/unprobed: its own bucket, so it
    cannot dilute a confirmed tier's mean)."""
    split: dict = {}
    for b in states:
        if not b.in_rotation:
            continue
        k = b.kind or "unknown"
        row = split.setdefault(
            k, {"healthy": 0, "queue_depths": [], "inflight": 0})
        row["healthy"] += 1
        row["queue_depths"].append(b.queue_depth)
        row["inflight"] += b.inflight
    out = {}
    for k, row in split.items():
        depths = row.pop("queue_depths")
        row["mean_queue_depth"] = (sum(depths) / len(depths)
                                   if depths else 0.0)
        row["max_queue_depth"] = max(depths) if depths else 0
        out[k] = row
    return out


@dataclass
class LaunchedBackend:
    """A backend process the scaler owns (and may terminate)."""

    url: str
    proc: object = None
    workdir: str = ""
    log_path: str = ""


def launch_process(module, args, host="127.0.0.1", python=None,
                   env=None, cpus=None, startup_timeout_s=120.0):
    """Boot ``python -m <module> <args> --port-file <f>`` and wait for
    the port announcement — the one process-discovery recipe every
    fleet process (backend OR router) uses: PYTHONPATH propagation so
    the child imports THIS paddle_tpu even uninstalled, stdout/stderr
    into a per-process log, optional ``taskset -c`` core pinning, and a
    startup deadline that distinguishes "died during boot" (with the
    log path) from "never became ready". The announced port is written
    by the child only once it is READY (the entrypoints write it after
    warmup/start), so the returned URL is immediately servable."""
    workdir = tempfile.mkdtemp(prefix="ptpu_proc_")
    port_file = os.path.join(workdir, "port")
    log_path = os.path.join(workdir, "proc.log")
    cmd = [python or sys.executable, "-m", module,
           *[str(a) for a in args], "--port-file", port_file]
    if cpus is not None:
        import shutil

        if shutil.which("taskset"):
            cmd = ["taskset", "-c", str(cpus)] + cmd
    child_env = dict(os.environ)
    import paddle_tpu

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(paddle_tpu.__file__)))
    child_env["PYTHONPATH"] = os.pathsep.join(
        [pkg_root] + ([child_env["PYTHONPATH"]]
                      if child_env.get("PYTHONPATH") else []))
    if env:
        child_env.update(env)
    with open(log_path, "wb") as log:
        proc = subprocess.Popen(cmd, stdout=log,
                                stderr=subprocess.STDOUT, env=child_env)
    deadline = time.monotonic() + float(startup_timeout_s)
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise UnavailableError(
                f"{module} process died during startup "
                f"(rc={proc.returncode}); log: {log_path}")
        if os.path.exists(port_file):
            with open(port_file) as f:
                port = int(f.read().strip())
            return LaunchedBackend(url=f"http://{host}:{port}",
                                   proc=proc, workdir=workdir,
                                   log_path=log_path)
        time.sleep(0.05)
    proc.kill()
    raise UnavailableError(
        f"{module} did not become ready within {startup_timeout_s}s; "
        f"log: {log_path}")


class SubprocessLauncher:
    """Launch/terminate real backend processes on this host.

    ``launch()`` blocks until the backend announces its port (which the
    entrypoint does only after warmup, so a returned URL is READY) and
    returns a :class:`LaunchedBackend`; ``terminate()`` SIGTERMs it
    (graceful drain) and escalates to SIGKILL past the timeout.
    """

    def __init__(self, model_dir, host="127.0.0.1", replicas=None,
                 buckets=None, queue_capacity=None, batch_timeout_ms=None,
                 mesh_dp=0, python=None, env=None,
                 startup_timeout_s=120.0, cpu_sets=None,
                 kind="predict", extra_args=()):
        self.model_dir = model_dir
        self.host = host
        self.replicas = replicas
        self.buckets = buckets
        self.queue_capacity = queue_capacity
        self.batch_timeout_ms = batch_timeout_ms
        self.mesh_dp = mesh_dp
        # generation kinds boot from a save_gpt_model dir (--gpt-dir);
        # extra_args passes kind-specific knobs straight through
        # (--slots, --draft-dir, ... — a tier-bound scaler's launcher
        # bakes its tier's configuration here)
        self.kind = str(kind)
        self.extra_args = [str(a) for a in extra_args]
        self.python = python or sys.executable
        self.env = dict(env) if env else {}
        self.startup_timeout_s = float(startup_timeout_s)
        # optional taskset core pinning, cycled per launch ("0-5",
        # "6-11", ...): on a single box, XLA:CPU spreads one backend's
        # intra-op threads across EVERY core, so co-hosted backends
        # fight for the same silicon — disjoint core sets make each
        # process behave like its own host (what the router_throughput
        # scaling bench emulates). Multi-host fleets don't need it.
        self.cpu_sets = list(cpu_sets) if cpu_sets else []
        self._launches = 0

    def _args(self):
        if self.kind != "predict":
            args = ["--kind", self.kind,
                    "--gpt-dir", str(self.model_dir),
                    "--host", self.host, "--port", "0"]
            if self.queue_capacity is not None:
                args += ["--queue-capacity", str(self.queue_capacity)]
            return args + self.extra_args
        args = ["--model-dir", str(self.model_dir),
                "--host", self.host, "--port", "0"]
        if self.replicas is not None:
            args += ["--replicas", str(self.replicas)]
        if self.buckets is not None:
            b = self.buckets
            args += ["--buckets",
                     b if isinstance(b, str)
                     else ",".join(str(int(v)) for v in b)]
        if self.queue_capacity is not None:
            args += ["--queue-capacity", str(self.queue_capacity)]
        if self.batch_timeout_ms is not None:
            args += ["--batch-timeout-ms", str(self.batch_timeout_ms)]
        if self.mesh_dp:
            args += ["--mesh-dp", str(self.mesh_dp)]
        return args + self.extra_args

    def launch(self) -> LaunchedBackend:
        cpus = (self.cpu_sets[self._launches % len(self.cpu_sets)]
                if self.cpu_sets else None)
        handle = launch_process(
            "paddle_tpu.serving.backend", self._args(), host=self.host,
            python=self.python, env=self.env, cpus=cpus,
            startup_timeout_s=self.startup_timeout_s)
        self._launches += 1
        _flight.record_event("scaler_backend_launched",
                             url=handle.url, pid=handle.proc.pid)
        return handle

    def terminate(self, handle: LaunchedBackend, drain=True,
                  timeout_s=15.0):
        proc = handle.proc
        if proc is None or proc.poll() is not None:
            return
        proc.send_signal(_signal.SIGTERM if drain else _signal.SIGKILL)
        try:
            proc.wait(timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(5.0)
        _flight.record_event("scaler_backend_terminated",
                             url=handle.url, drain=drain,
                             rc=proc.returncode)


class AutoScaler:
    """Scale decisions over router signals, acting through a launcher.

    ``router`` needs ``backend_states()`` / ``add_backend`` /
    ``remove_backend`` (duck-typed; tests pass a stub). ``launcher``
    needs ``launch() -> LaunchedBackend`` and ``terminate(handle,
    drain=)``. All thresholds default to their ``serving_scaler_*``
    flags; ``clock`` is injectable for deterministic hysteresis/cooldown
    tests.
    """

    def __init__(self, router, launcher, min_backends=None,
                 max_backends=None, up_queue_depth=None,
                 down_queue_depth=None, window=None, cooldown_s=None,
                 interval_s=None, kind=None, clock=time.monotonic):
        self.router = router
        self.launcher = launcher
        # tier scoping: a kind-bound scaler sees ONLY its tier's
        # pressure and owns only its tier's backends — one scaler per
        # kind sizes a disaggregated fleet's tiers independently (the
        # launcher must boot backends of the matching --kind)
        self.kind = kind
        self.min_backends = int(
            min_backends if min_backends is not None
            else flag("serving_scaler_min_backends"))
        self.max_backends = int(
            max_backends if max_backends is not None
            else flag("serving_scaler_max_backends"))
        if not 0 < self.min_backends <= self.max_backends:
            raise InvalidArgumentError(
                f"scaler bounds must satisfy 0 < min <= max, got "
                f"min={self.min_backends} max={self.max_backends}")
        self.up_queue_depth = float(
            up_queue_depth if up_queue_depth is not None
            else flag("serving_scaler_up_queue_depth"))
        self.down_queue_depth = float(
            down_queue_depth if down_queue_depth is not None
            else flag("serving_scaler_down_queue_depth"))
        self.window = int(window if window is not None
                          else flag("serving_scaler_window"))
        if self.window <= 0:
            raise InvalidArgumentError(
                f"scaler hysteresis window must be positive, got "
                f"{self.window}")
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else flag("serving_scaler_cooldown_s"))
        self.interval_s = float(
            interval_s if interval_s is not None
            else flag("serving_scaler_interval_s"))
        # burn at/above this (both SLO windows confirming) is up-pressure
        # on its own: latency SLOs can burn while queues stay shallow
        # (e.g. a wedged-but-answering backend)
        self.burn_alert = float(flag("slo_burn_alert"))
        self.clock = clock
        self.owned: dict[str, LaunchedBackend] = {}
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._m_ups = counter("serving/scaler_scale_ups_total")
        self._m_downs = counter("serving/scaler_scale_downs_total")
        self._m_reaped = counter("serving/scaler_backends_reaped_total")
        self._m_owned = gauge("serving/scaler_backends_owned")
        from . import _register_live

        _register_live(self)

    # -- signal gathering ----------------------------------------------------

    def signals(self) -> FleetSignals:
        """One tick's fleet view: router backend table aggregates plus
        this host's cluster snapshot (decision evidence). A kind-bound
        scaler's top-level aggregates are its TIER's only (a saturated
        decode tier must never be masked by idle prefill backends);
        the full per-kind split rides along either way."""
        all_states = self.router.backend_states()
        states = all_states
        if self.kind is not None:
            # a just-launched owned backend may not have a probed kind
            # yet — it still belongs to this tier's totals
            states = [b for b in all_states
                      if b.kind == self.kind or (
                          b.kind is None
                          and b.url in self.owned)]
        healthy = [b for b in states if b.in_rotation]
        depths = [b.queue_depth for b in healthy]
        # the scaler runs in-process with the router, so the router-side
        # SLO engine's confirmed burn is a local read, not an RPC
        from ..monitor import slo as _slo

        return FleetSignals(
            time=self.clock(),
            backends_total=len(states),
            backends_healthy=len(healthy),
            mean_queue_depth=(sum(depths) / len(depths)
                              if depths else 0.0),
            max_queue_depth=max(depths) if depths else 0,
            total_inflight=sum(b.inflight for b in healthy),
            host=_cluster.local_snapshot(),
            kind=self.kind,
            kinds=_kind_split(all_states),
            slo_burn=_slo.current_burn(),
        )

    # -- decision ------------------------------------------------------------

    def in_cooldown(self, now=None) -> bool:
        if self._last_action_t is None:
            return False
        now = self.clock() if now is None else now
        return (now - self._last_action_t) < self.cooldown_s

    def decide(self, sig: FleetSignals) -> str | None:
        """Evaluate one tick: returns ``"up"``, ``"down"``, or ``None``.

        Hysteresis: an action fires only after ``window`` CONSECUTIVE
        same-direction ticks; a neutral tick resets both streaks. During
        cooldown streaks do not accumulate at all — pressure during a
        backend's boot must not pre-charge the next decision.
        """
        if self.in_cooldown(sig.time):
            self._up_streak = self._down_streak = 0
            return None
        # zero healthy backends IS up-pressure regardless of queue math:
        # the fleet is dark and the router is answering 503s; a
        # confirmed SLO burn past the alert threshold likewise — error
        # budget is being spent NOW even if queues look shallow
        up = (sig.backends_healthy == 0
              or sig.mean_queue_depth >= self.up_queue_depth
              or sig.slo_burn >= self.burn_alert)
        down = (not up
                and sig.mean_queue_depth <= self.down_queue_depth
                and sig.total_inflight == 0)
        self._up_streak = self._up_streak + 1 if up else 0
        self._down_streak = self._down_streak + 1 if down else 0
        if (self._up_streak >= self.window
                and sig.backends_total < self.max_backends):
            return "up"
        if (self._down_streak >= self.window
                and sig.backends_healthy > self.min_backends
                and self.owned):
            return "down"
        return None

    # -- actions -------------------------------------------------------------

    def _note_action(self, now):
        self._last_action_t = now
        self._up_streak = self._down_streak = 0
        self._m_owned.set(len(self.owned))

    def scale_up(self, sig: FleetSignals):
        handle = self.launcher.launch()
        self.owned[handle.url.rstrip("/")] = handle
        self.router.add_backend(handle.url)
        self._m_ups.inc()
        self._note_action(self.clock())
        _flight.record_event(
            "scaler_scale_up", url=handle.url,
            backends=sig.backends_total + 1,
            mean_queue_depth=round(sig.mean_queue_depth, 3),
            host_mfu=sig.host.get("mfu"),
            host_hbm_peak=sig.host.get("hbm_peak_bytes"))
        return handle

    def scale_down(self, sig: FleetSignals):
        """Drain the least-loaded OWNED backend: out of rotation first
        (no new traffic), then a graceful terminate (SIGTERM -> the
        backend drains queued work before its listener closes)."""
        victims = [b for b in self.router.backend_states()
                   if b.url in self.owned]
        if not victims:
            return None
        victim = min(victims, key=lambda b: (b.score(), b.url))
        self.router.remove_backend(victim.url)
        handle = self.owned.pop(victim.url)
        self._m_downs.inc()
        self._note_action(self.clock())
        _flight.record_event(
            "scaler_scale_down", url=victim.url,
            backends=sig.backends_total - 1,
            mean_queue_depth=round(sig.mean_queue_depth, 3),
            host_mfu=sig.host.get("mfu"),
            host_hbm_peak=sig.host.get("hbm_peak_bytes"))
        self.launcher.terminate(handle, drain=True)
        return handle

    def reap_dead(self) -> list:
        """Forget owned backends whose PROCESS died (crash, OOM-kill):
        drop them from the router and from ``owned``. Without this, a
        dead-but-registered backend holds a ``backends_total`` slot
        forever and blocks its own replacement at ``max_backends`` —
        the fleet would run degraded with no path back to capacity."""
        reaped = []
        for url, handle in list(self.owned.items()):
            proc = handle.proc
            if proc is None or proc.poll() is None:
                continue
            self.owned.pop(url, None)
            try:
                self.router.remove_backend(url)
            except Exception:
                pass
            self._m_reaped.inc()
            self._m_owned.set(len(self.owned))
            _flight.record_event("scaler_backend_reaped", url=url,
                                 rc=proc.returncode)
            reaped.append(url)
        return reaped

    def step(self) -> str | None:
        """One evaluate-decide-act tick (the loop body; also the unit
        tests' entry). Returns the action taken, if any."""
        self.reap_dead()
        sig = self.signals()
        action = self.decide(sig)
        if action == "up":
            self.scale_up(sig)
        elif action == "down":
            self.scale_down(sig)
        return action

    # -- lifecycle -----------------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self):
        if self.alive:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="ptpu-serving-scaler", daemon=True)
        self._thread.start()
        _flight.record_event("scaler_start",
                             interval_s=self.interval_s,
                             min=self.min_backends,
                             max=self.max_backends)
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:  # the scaler must never kill the fleet
                pass

    def stop(self, drain=True, timeout=10.0):
        """Stop the loop and terminate every backend the scaler owns
        (``drain=False`` SIGKILLs them — the test-teardown path must
        not leave orphan processes)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.interval_s + 1.0)
        self._thread = None
        for url, handle in list(self.owned.items()):
            try:
                self.router.remove_backend(url)
            except Exception:
                pass
            try:
                self.launcher.terminate(handle, drain=drain,
                                        timeout_s=timeout)
            except Exception:
                pass
            self.owned.pop(url, None)
        self._m_owned.set(0)
        _flight.record_event("scaler_stop", drain=drain)

    def view(self) -> dict:
        return {
            "alive": self.alive,
            "owned": sorted(self.owned),
            "min_backends": self.min_backends,
            "max_backends": self.max_backends,
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
            "in_cooldown": self.in_cooldown(),
            "scale_ups": self._m_ups.value,
            "scale_downs": self._m_downs.value,
        }
