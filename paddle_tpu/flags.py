"""Global FLAGS registry (env-driven runtime configuration).

Reference parity: gflags definitions in paddle/fluid/platform/flags.cc
(~50 flags, e.g. FLAGS_check_nan_inf :44), exported to Python through
global_value_getter_setter.cc as ``core.globals()`` and the
paddle.get_flags/set_flags API; ``init_gflags`` (pybind/pybind.cc:1652)
imports ``FLAGS_*`` environment variables.

TPU-native scope: only flags that change behavior on this runtime are
registered — memory-fraction/allocator/cudnn knobs have no XLA
equivalent and registering silent no-ops is worse than NotFound (the
same contract as DistributedStrategy consumption). Each flag documents
what consumes it.
"""
from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = ["define_flag", "get_flags", "set_flags", "flag", "globals_view",
           "watch_flag"]


@dataclass
class _Flag:
    name: str
    value: object
    default: object
    type: type
    help: str
    # writable=False mirrors the reference's non-public globals
    # (global_value_getter_setter.cc exposes some read-only)
    writable: bool = True


_REGISTRY: dict[str, _Flag] = {}
_WATCHERS: dict[str, list] = {}


def watch_flag(name: str, callback):
    """Invoke ``callback(new_value)`` whenever ``set_flags`` changes the
    flag — for flags whose consumers must react immediately (e.g. the
    executor re-syncs jax's persistent compile cache on change) rather
    than at their next natural read."""
    _WATCHERS.setdefault(name, []).append(callback)


def _coerce(value, typ):
    if typ is bool and isinstance(value, str):
        return value.strip().lower() in ("1", "true", "yes", "on")
    return typ(value)


def define_flag(name: str, default, help: str = "", writable: bool = True):
    """Register a flag (DEFINE_bool/int32/double/string equivalent,
    platform/flags.cc). ``FLAGS_<name>`` env overrides the default at
    definition time (init_gflags semantics)."""
    typ = type(default)
    value = default
    env = os.environ.get(f"FLAGS_{name}")
    if env is not None:
        value = _coerce(env, typ)
    _REGISTRY[name] = _Flag(name, value, default, typ, help, writable)
    return value


def flag(name: str):
    """Fast internal read used by the runtime hot paths."""
    try:
        return _REGISTRY[name].value
    except KeyError:
        from .errors import NotFoundError

        raise NotFoundError(
            f"unknown flag {name!r}; known: {sorted(_REGISTRY)}"
        ) from None


def get_flags(names):
    """paddle.get_flags: dict of current values for name or list of names."""
    if isinstance(names, str):
        names = [names]
    return {n: flag(n) for n in names}


def set_flags(flags_map: dict):
    """paddle.set_flags: update flag values with type checking."""
    from .errors import InvalidArgumentError, NotFoundError

    for name, value in flags_map.items():
        f = _REGISTRY.get(name)
        if f is None:
            raise NotFoundError(
                f"unknown flag {name!r}; known: {sorted(_REGISTRY)}"
            )
        if not f.writable:
            raise InvalidArgumentError(f"flag {name!r} is read-only")
        try:
            f.value = _coerce(value, f.type)
        except (TypeError, ValueError) as e:
            raise InvalidArgumentError(
                f"flag {name!r} expects {f.type.__name__}, got {value!r}"
            ) from e
        for cb in _WATCHERS.get(name, ()):
            cb(f.value)
        # flag flips are exactly the kind of breadcrumb a post-mortem
        # needs ("who turned donation off mid-run?") — record each one
        try:
            from .monitor import flight_recorder as _flight

            _flight.record_event("flag_change", flag=name,
                                 value=repr(f.value))
        except Exception:
            pass  # bootstrap import order / partially-initialized package


def globals_view() -> dict:
    """core.globals() equivalent: snapshot of every flag value."""
    return {n: f.value for n, f in _REGISTRY.items()}


# ---------------------------------------------------------------------------
# Registered flags (each consumed somewhere — grep the name to find where)
# ---------------------------------------------------------------------------

# platform/flags.cc:44 — wired into framework/jit.py TrainStepFn (checkify
# per-primitive NaN/Inf localization) and static/executor.py (post-run
# scan of fetches/written vars, naming the variable)
define_flag("check_nan_inf", False,
            "scan step outputs for NaN/Inf and name the producing op")

# static/executor.py + static/program.py Program.verify + analysis/ —
# run the program-IR verifier (def-before-use, write conflicts, kernel
# dtype consistency, control-flow block well-formedness; analysis/passes)
# before each program is planned/lowered, raising a structured
# VerifyError naming the offending op index/type/var instead of an
# opaque XLA trace error. Values: off | on | strict ("strict" promotes
# dead-code findings to errors). The verdict is cached per program
# version, so steady-state dispatch pays a dict lookup (<1%, bench.py
# executor_dispatch.program_verify sub-row).
define_flag("program_verify", "on",
            "verify program IR before lowering: off | on | strict "
            "(strict also fails on dead ops/vars)")

# static/executor.py + analysis/memory.py — static peak-HBM admission:
# before any lower/compile, plan the program's liveness footprint
# (analysis.plan_memory) and compare the predicted peak against the
# device HBM capacity from the cost-model peaks table (hbm_bytes,
# overridable via FLAGS_device_peaks). "strict" rejects over-budget
# programs (MemoryBudgetError naming the high-water op + top tensors)
# and liveness-unsafe donations (DonationError) BEFORE compiling;
# "warn" records the same verdicts as memory_budget flight events and
# a Python warning but admits. Verdicts cache per program version —
# steady-state dispatch pays a dict lookup (<1%, bench.py
# executor_dispatch.memplan). The generation engine applies the same
# budget to its slots x cache-len x dtype geometry at construction.
define_flag("memory_budget_check", "warn",
            "static peak-HBM admission before compile: off | warn | "
            "strict (strict rejects over-budget programs and unsafe "
            "donations with the high-water op named)")

# static/executor.py Executor.run + inference/predictor.py Predictor +
# analysis/optimizer.py — the program-IR optimizer gate, run ahead of the
# verify/memplan gates and lowering (the switch_ir_optim role of
# inference/api/paddle_pass_builder.cc, generalized to every executed
# program). 0: off (programs run exactly as built). 1: fusion rewrites
# onto the fused registry kernels (conv2d->batch_norm->relu,
# residual-add->layer_norm, dequantized-int8 matmul/mul chains) plus
# side-effect-safe dead-op elimination — a training program with no
# fusible chain comes back byte-identical. 2: level 1 plus liveness-
# driven rematerialization when the memory planner says the program is
# over the device HBM budget (recompute cheap activations at their late
# uses instead of holding them). The optimized clone caches per program
# version (the verifier-cache discipline), so steady-state dispatch pays
# one dict lookup; per-pass stats land on profiler counters and /statz.
define_flag("ir_opt_level", 1,
            "program-IR optimizer level: 0 off, 1 fusion+DCE, "
            "2 +rematerialization under memory pressure")

# platform/flags.cc benchmark — wired into framework/jit.py: synchronous
# dispatch (block until ready each step) so wall-clock timings are exact
define_flag("benchmark", False,
            "synchronous step dispatch for exact per-step timing")

# platform/enforce.h FLAGS_call_stack_level — wired into errors.py
# formatting (0: message only, 1: + op context, 2: + python stack)
define_flag("call_stack_level", 1,
            "error verbosity: 0 message, 1 +op context, 2 +python stack")

# TPU pallas fused max-pool backward (ops/pallas/pool_backward.py) — the
# role of the reference's hand-written MaxPool2dGradFunctor CUDA kernel
# (operators/math/pooling.cu). OFF by default: the kernel is numerically
# exact (first-max parity with select_and_scatter, tested), but ordered
# A/B at the ResNet-50 stem shape measured XLA's select_and_scatter at
# 4.7 ms vs 24 ms for the kernel — per-program pallas dispatch overhead
# dominates at the block sizes the kernel's VMEM footprint allows (lane-
# dim stride work must run as one-hot MXU matmuls, tripling the working
# set). Kept behind the flag for future backends/shapes.
define_flag("use_pallas_pool_bwd", False,
            "fused pallas kernel for max-pool backward on TPU")

# static/executor.py — buffer donation for persistables on the compiled
# whole-block step: parameters/optimizer state update in place (XLA input/
# output aliasing) instead of doubling HBM traffic each step, matching the
# dygraph path's donate_argnums (parallel/train.py). The Scope transfers
# ownership: after a run, donated scope entries point at the NEW arrays and
# the old buffers are dead. Opt out for debugging workflows that hold
# references to pre-step parameter arrays.
define_flag("executor_buffer_donation", True,
            "donate written persistables to the compiled step (in-place "
            "parameter updates); disable to keep pre-step arrays alive")

# monitor/training_monitor.py — steps between TrainingMonitor periodic
# log lines (step wall time, examples/sec, input-wait ratio, cache hit
# rates, HBM watermark). 0 disables the line; aggregation always runs
# (it is a handful of float adds per step).
define_flag("monitor_interval", 100,
            "steps between TrainingMonitor log lines (0: silent)")

# monitor/flight_recorder.py — the structured-event ring buffer every
# subsystem reports into (executor runs, collectives with per-group seq
# numbers, PS RPCs, dataloader lifecycle, flag changes, XLA compiles);
# dumped on unhandled exception / SIGUSR1 / watchdog trip. Recording is
# lock-cheap (<2% on the dispatch micro-bench, bench.py
# flight_recorder_overhead); disable only to rule instrumentation out.
define_flag("flight_recorder", True,
            "record structured runtime events into the in-memory ring "
            "buffer for crash/hang post-mortems")

# monitor/flight_recorder.py — ring capacity, read once at recorder
# construction (import time); resizing a live ring would tear its seq
# accounting
define_flag("flight_recorder_capacity", 4096,
            "flight-recorder ring buffer capacity (events)")

# monitor/flight_recorder.py — where dump files land
# (paddle_tpu_flight_rank<r>_pid<pid>.json); empty: the system temp dir
define_flag("flight_recorder_dump_dir", "",
            "directory for flight-recorder dump files (empty: temp dir)")

# monitor/flight_recorder.py HangWatchdog — trips when no executor step,
# eager collective, or PS reply completes within the deadline; the trip
# dumps the recorder + all thread stacks and runs the cross-rank desync
# exchange. 0 disables. Consumed by install_from_flags (init_parallel_env)
# and start_watchdog().
define_flag("watchdog_timeout_s", 0.0,
            "hang watchdog deadline in seconds (0: disabled); on trip, "
            "dump the flight recorder + thread stacks + desync report")

# monitor/debug_server.py — /healthz /metrics /flightrecorder /threadz
# /flagz on 127.0.0.1:<port + rank> (rank-offset so every process of a
# multi-process host serves). 0 disables.
define_flag("debug_port", 0,
            "base port for the loopback HTTP debug endpoint "
            "(bound at port+rank; 0: disabled)")

# monitor/tracing.py — distributed request tracing: contextvar trace
# context, traceparent propagation router->backend, spans through the
# serving/executor path, step-scoped training traces. Span creation is
# cheap (bench.py tracing_overhead < 2%); disable only to rule the
# instrumentation out of a measurement.
define_flag("trace_enabled", True,
            "record per-request trace spans (traceparent propagation, "
            "/tracez, /statz slowest table)")

# monitor/tracing.py TraceStore — TAIL sampling: the retention decision
# happens at trace completion, when the outcome is known. Error /
# deadline / retried / timed-out traces are ALWAYS kept; of the boring
# rest, only the slowest K per window survive.
define_flag("trace_sample_slowest_k", 5,
            "retain the K slowest traces per sampling window in "
            "addition to every errored/flagged trace (0: flagged only)")

# monitor/tracing.py TraceStore — the slowest-K competition window; a
# new window forgets the old champions so a quiet hour cannot pin the
# store to stale outliers
define_flag("trace_sample_window_s", 30.0,
            "tail-sampling window in seconds for the slowest-K "
            "retention race")

# monitor/tracing.py TraceStore — bound on RETAINED traces (FIFO
# eviction past it); active (in-flight) traces are bounded at 4x this
define_flag("trace_store_capacity", 256,
            "maximum retained traces in the in-process trace store")

# static/executor.py _scan_nan_inf + framework/jit.py checkify path —
# what detection does: 'raise' (FatalError, the historical behavior),
# 'warn' (bump debug/nan_events, log the first offending variable, keep
# running), 'dump' (write the flight-recorder snapshot, then raise)
define_flag("check_nan_inf_action", "raise",
            "on NaN/Inf detection: raise | warn (count+log, continue) | "
            "dump (flight-recorder snapshot, then raise)")

# monitor/cost_model.py — override the detected device peak-throughput
# table (the MFU / HBM-bandwidth / roofline denominators) for new
# silicon, derated SKUs, or meaningful CPU numbers. Comma-separated
# k=v floats over {flops, hbm_bw, ici_bw} in FLOP/s and B/s, e.g.
# "flops=2.75e14,hbm_bw=1.228e12,ici_bw=3e11"; any subset overrides.
define_flag("device_peaks", "",
            "override device peak throughputs for utilization accounting:"
            " 'flops=<FLOP/s>,hbm_bw=<B/s>,ici_bw=<B/s>' (any subset)")

# monitor/cluster.py — a rank is flagged as a straggler on /clusterz when
# its step time exceeds this multiple of the cluster-median step time;
# the verdict is also recorded into the flight recorder
define_flag("straggler_threshold", 1.5,
            "flag a rank as straggler when its step time exceeds this "
            "multiple of the cluster median (/clusterz)")

# monitor/cluster.py ClusterPublisher — seconds between per-rank metric-
# snapshot publishes over the jax.distributed KV side channel (feeds
# rank-0's /clusterz). 0 disables; single-process worlds never publish.
# Consumed by install_from_flags (init_parallel_env).
define_flag("cluster_metrics_interval_s", 15.0,
            "period for publishing per-rank metric snapshots to the "
            "cluster aggregator (0: disabled)")

# serving/batcher.py — the shape-bucket ladder for the online batcher's
# batch axis. Every assembled batch is padded up to the smallest bucket
# that covers its rows, so the steady-state compile count is bounded by
# the ladder length (asserted after warmup). Powers of two by default:
# each recompile doubles capacity, log2(max) compiles total.
define_flag("serving_batch_buckets", "1,2,4,8",
            "comma-separated ascending batch-axis bucket sizes for the "
            "online serving batcher; each bucket is one compiled shape")

# serving/batcher.py — bounded admission queue. A full queue REJECTS the
# request (QueueFullError -> HTTP 429) instead of queueing unboundedly:
# under sustained overload an unbounded queue converts every request
# into a deadline miss while memory grows without limit.
define_flag("serving_queue_capacity", 256,
            "max requests the serving batcher holds before rejecting "
            "(backpressure: HTTP 429)")

# serving/batcher.py — how long the batch-assembly loop holds an open
# batch waiting for more requests after the first one arrives. The
# latency/throughput knob: 0 dispatches every request immediately.
define_flag("serving_batch_timeout_ms", 2.0,
            "max ms the serving batcher waits to fill a batch beyond "
            "its first request (0: dispatch immediately)")

# serving/replica.py — worker threads in the replica pool; every replica
# is a Predictor.clone() sharing ONE jit/AOT executable cache, so N
# replicas serve with zero extra compiles.
define_flag("serving_replicas", 1,
            "replica worker threads serving the online batcher")

# serving/batcher.py — default per-request deadline; a request that sits
# queued past its deadline completes with ExecutionTimeoutError without
# ever dispatching. 0 disables (requests wait indefinitely).
define_flag("serving_default_deadline_ms", 0.0,
            "default per-request serving deadline in ms (0: none); "
            "expired requests error without dispatch")

# generation/engine.py — capacity (tokens) of the static-shape ring KV
# cache per decode slot. Shapes never change across decode steps, so one
# compiled step serves every sequence length; past the window the ring
# overwrites the oldest token (sliding-window attention of this width —
# the model computes the same function, golden-tested).
define_flag("generation_kv_cache_len", 256,
            "per-slot ring KV cache capacity (tokens) for autoregressive "
            "decoding; also the sliding attention window width")

# generation/engine.py + nn/transformer.py QuantizedStaticCache — storage
# dtype of the ring KV cache. "int8" stores K/V as int8 with per-head
# dynamic scales (quantize on ring write, dequantize inside the
# attention read): ~3.8x fewer KV bytes per token at head_dim 64, so the
# same HBM holds ~1.9x the decode slots — a direct capacity multiplier
# for the continuous batcher, certified against the full-forward parity
# goldens at the envelope documented in README "Quantization".
define_flag("generation_kv_cache_dtype", "float32",
            "KV cache storage dtype for decoding: float32 | int8 "
            "(int8: per-head dynamic scales, ~4x fewer cache bytes)")

# generation/paging.py + nn/transformer.py PagedStaticCache — physical
# layout of the decode KV store. "ring" is the historical per-slot
# contiguous ring; "paged" decomposes the same logical ring into
# fixed-size pages drawn from a shared pool through per-slot page
# tables, enabling copy-on-write prefix sharing across requests and
# capacity as a function of ACTUAL tokens instead of worst-case window.
# Greedy output is token-identical between the two layouts.
define_flag("kv_cache_layout", "ring",
            "decode KV cache layout: ring (per-slot contiguous) | paged "
            "(shared page pool + per-slot page tables with copy-on-write "
            "prefix reuse)")

# generation/paging.py — tokens per KV page under the paged layout.
# Smaller pages share more aggressively (a prefix must fill a whole
# page to be reusable) but widen the page tables; must divide
# generation_kv_cache_len.
define_flag("generation_kv_page_size", 16,
            "tokens per KV page under kv_cache_layout=paged; must "
            "divide generation_kv_cache_len evenly")

# generation/paging.py — physical pages in the shared pool. 0 sizes the
# pool at slots x pages_per_slot (ring-equivalent worst case); smaller
# values bank on prefix sharing / short sequences to overcommit slots
# against HBM (the slots-vs-pages capacity recipe in README).
define_flag("generation_kv_pool_pages", 0,
            "physical KV pages in the paged pool (0: slots x "
            "pages_per_slot, the no-overcommit default)")

# generation/engine.py — the sequence-length bucket ladder for prefill.
# Prompts pad up to the smallest covering bucket, so prefill costs at
# most len(ladder) compiles ever — the serving batch-bucket discipline,
# applied to the sequence axis.
define_flag("generation_prefill_buckets", "16,32,64,128",
            "comma-separated ascending prompt-length buckets for "
            "generation prefill; each bucket is one compiled shape")

# generation/engine.py + serving/continuous.py — concurrent decode slots
# in the continuous-batching step. A finished sequence vacates its slot
# mid-batch and the next queued request is admitted at the next step;
# the decode program's batch axis is always exactly this many rows.
define_flag("generation_decode_slots", 4,
            "decode slots co-batched in the compiled generation step "
            "(continuous batching admits into vacant slots mid-batch)")

# generation/engine.py — default generation budget when the request does
# not set one.
define_flag("generation_max_new_tokens", 64,
            "default max tokens generated per request (requests may "
            "override below the model's position limit)")

# generation/engine.py — default sampling temperature; 0 = greedy
# (argmax). Per-request temperatures are traced values: any mix of
# greedy and sampled requests co-batches in the one compiled step.
define_flag("generation_temperature", 0.0,
            "default sampling temperature (0: greedy argmax); "
            "per-request override is compile-free")

# generation/engine.py — top-k filter width; 0 disables. STATIC: a
# different k is a different compiled program, so it is an engine-level
# knob, not a per-request one (the compile-once guarantee).
define_flag("generation_top_k", 0,
            "top-k sampling filter for generation (0: full distribution); "
            "engine-level — changing it recompiles the decode step")

# serving/continuous.py — bounded admission queue for generation
# requests, same backpressure contract as serving_queue_capacity (full
# queue -> QueueFullError -> HTTP 429).
define_flag("generation_queue_capacity", 128,
            "max generation requests queued for decode slots before "
            "rejecting (backpressure: HTTP 429)")

# generation/engine.py — speculative decoding. When enabled (and a
# draft model is available, e.g. serving/backend.py --draft-dir), every
# decode round runs the draft chain + ONE batched target verify over
# draft_k+1 positions instead of one full-model dispatch per token:
# greedy output stays token-identical to the plain engine, and each
# round emits 1..draft_k+1 tokens for two dispatches.
define_flag("speculative_enabled", False,
            "enable speculative decoding in serving backends that have "
            "a draft model configured (greedy output is token-identical "
            "to the plain engine)")

# generation/engine.py — proposals per speculative round. STATIC: k
# shapes the draft/verify programs (and widens the ring store by k
# scratch entries), so it is an engine-level knob, not per-request.
define_flag("speculative_draft_k", 4,
            "draft tokens proposed per speculative decoding round; "
            "engine-level — changing it recompiles draft+verify")

# serving/backend.py + serving/server.py — role of a generation backend
# in a disaggregated fleet. "generate" serves /generate end to end;
# "prefill" runs only the bucket-ladder forward and ships the KV slab
# (POST /prefill); "decode" admits handed-off slabs into decode slots
# (POST /generate_kv). The router composes prefill -> decode for
# /generate when both tiers are in rotation.
define_flag("backend_kind", "generate",
            "generation backend role: generate | prefill | decode "
            "(disaggregated fleets run distinct prefill/decode tiers)")

# serving/router.py — budget for the prefill leg of a disaggregated
# /generate (prompt -> KV slab). The decode leg keeps the normal
# request timeout: prefill is one bounded forward, decode is an open-
# ended generation.
define_flag("serving_handoff_timeout_s", 30.0,
            "router timeout for the prefill->slab leg of a "
            "disaggregated /generate handoff")

# serving/router.py — period of the router's backend prober (GET
# /healthz + /loadz per backend): drives load-signal freshness AND the
# only re-admission path for an evicted backend (readiness must flip
# back on /healthz before it rejoins rotation).
define_flag("serving_router_probe_interval_s", 1.0,
            "seconds between router health/load probes of each backend; "
            "also the re-admission latency for a recovered backend")

# serving/router.py — how many DISTINCT backends one request may be
# offered before the router gives up with 503. Retries happen only for
# connection-level failures and admission rejections (503) — work a
# backend actually answered is never replayed.
define_flag("serving_router_retries", 3,
            "max distinct backends tried per routed request before 503 "
            "(connection failures / admission rejects only)")

# serving/router.py — TCP connect budget per dispatch attempt. Short on
# purpose: a dead backend must cost the request milliseconds (then the
# next backend is tried), not a full request timeout.
define_flag("serving_router_connect_timeout_ms", 1000.0,
            "router->backend TCP connect timeout per attempt in ms")

# serving/router.py — end-to-end budget for one proxied request once it
# is on a backend (covers queueing + dispatch there).
define_flag("serving_router_request_timeout_s", 120.0,
            "router->backend response timeout once a request is "
            "dispatched (seconds)")

# serving/scaler.py — period of the autoscaler's evaluate loop; each
# tick gathers router + cluster signals and runs one decision.
define_flag("serving_scaler_interval_s", 5.0,
            "seconds between autoscaler evaluations of the fleet signals")

# serving/scaler.py — fleet size bounds the scaler may move between.
define_flag("serving_scaler_min_backends", 1,
            "autoscaler floor: never drain below this many backends")
define_flag("serving_scaler_max_backends", 4,
            "autoscaler ceiling: never launch above this many backends")

# serving/scaler.py — scale-up pressure: mean queue depth per healthy
# backend at/above this for `serving_scaler_window` consecutive
# evaluations triggers a launch.
define_flag("serving_scaler_up_queue_depth", 4.0,
            "scale up when mean backend queue depth sustains at or "
            "above this for a full hysteresis window")

# serving/scaler.py — scale-down idleness: mean queue depth per backend
# at/below this (and no inflight pressure) for a full window triggers a
# drain of the least-loaded backend.
define_flag("serving_scaler_down_queue_depth", 0.25,
            "scale down when mean backend queue depth sustains at or "
            "below this for a full hysteresis window")

# serving/scaler.py — hysteresis: consecutive same-direction evaluations
# required before acting (one spiky tick must not flap the fleet).
define_flag("serving_scaler_window", 3,
            "consecutive over/under-threshold evaluations required "
            "before the autoscaler acts")

# serving/scaler.py — cooldown after any scale action; decisions are
# suppressed until it elapses so a fresh backend's warmup window cannot
# be misread as sustained pressure.
define_flag("serving_scaler_cooldown_s", 30.0,
            "seconds after a scale action during which the autoscaler "
            "makes no further decisions")

# incubate/auto_checkpoint.py + distributed/checkpoint.py — serialize and
# fsync snapshots in a background thread instead of on the step/epoch
# critical path. The capture itself is a device-side copy (donation-safe)
# dispatched asynchronously; publication stays atomic (tmp -> rename with
# a checksummed manifest) either way, so a crash mid-save can never be
# loaded — only detected and skipped.
define_flag("checkpoint_async", True,
            "serialize + fsync checkpoints in a background thread "
            "(off the training step critical path)")

# incubate/auto_checkpoint.py — minimum seconds between periodic
# snapshots. Negative: defer to the PADDLE_EDL_SAVE_CHECKPOINT_INTER env
# (the reference's knob); >= 0 overrides it at runtime without touching
# the environment.
define_flag("checkpoint_save_inter_s", -1.0,
            "min seconds between auto-checkpoint snapshots "
            "(< 0: use PADDLE_EDL_SAVE_CHECKPOINT_INTER env)")

# incubate/auto_checkpoint.py + distributed/checkpoint.py — rotation
# depth: newest N intact snapshots are kept, older ones deleted after a
# successful publish. 2 = checkpoint_saver.py max_num_checkpoints.
define_flag("checkpoint_keep", 2,
            "intact snapshots kept by checkpoint rotation")

# distributed/elastic.py StragglerTracker — consecutive /clusterz
# straggler verdicts against the same rank before it is marked for
# eviction (checkpointed around + world renegotiated). One slow tick
# must not evict a healthy rank; a persistently slow one must not drag
# the whole job to its pace.
define_flag("eviction_threshold", 3,
            "consecutive straggler verdicts before a rank is evicted "
            "from the training world")

# distributed/chaos.py — fault-injection directives for chaos testing,
# ';'-separated `action:key=val,key=val` (actions kill|exit|delay|raise;
# points step|mid_save). E.g. 'kill:point=step,step=3,rank=1;'
# 'delay:point=step,step=2,ms=250;kill:point=mid_save,n=2'. Empty (the
# default) disables — the hooks are a flag-read when idle. Consumed at
# the train-step boundary (hapi.Model.fit, fixtures) and inside the
# checkpoint writer (between data files and manifest publish).
define_flag("fault_injection", "",
            "chaos directives: 'action:k=v,...;...' with actions "
            "kill|exit|delay|raise at points step|mid_save (empty: off)")

# static/executor.py — JAX persistent compilation cache directory: repeated
# process starts skip XLA recompilation of unchanged programs (the role of
# TVM's ahead-of-time compiled module artifact). Empty string disables.
# Applied lazily at the first executor compile after the flag is set.
define_flag("persistent_compile_cache_dir", "",
            "directory for the XLA persistent compilation cache "
            "(empty: disabled)")

# runtime/compiled.py CompiledStore — ONE bound for every compiled-
# executable LRU cache (executor jit entries, TrainStepFn per-batch-
# signature executables, generation prefill/decode programs). Before the
# shared runtime each site hardcoded its own (executor 128 vs TrainStepFn
# 16 — many batch signatures silently evicted/recompiled under the small
# one). Evictions bump `<label>::cache_evict` so an undersized cache
# shows in the counters instead of as mystery recompiles. Read at insert
# time, so set_flags applies to live stores.
define_flag("compiled_cache_capacity", 128,
            "LRU bound shared by every compiled-executable cache "
            "(executor / train step / generation); evictions counted "
            "per store as <label>::cache_evict")

# optimizer/__init__.py Momentum + ops/pallas/optimizer_update.py — fuse
# the momentum + L2 weight-decay parameter update into one pallas kernel
# on TPU (one HBM read/write pass over param+velocity instead of the
# op-by-op chain). The jnp fallback used elsewhere computes the identical
# expression, so the flag is numerically free to leave on.
define_flag("use_fused_optimizer", True,
            "fused pallas momentum/weight-decay parameter update on TPU "
            "(jnp fallback elsewhere; identical math)")

# nn/transformer.py + ops/pallas/layernorm_residual.py — fuse the
# residual-add + LayerNorm pair (the post-norm transformer's hottest
# pointwise chain) into one pallas kernel on TPU: one VMEM pass computes
# x+residual, the f32 statistics, and the affine output. The jnp
# fallback is the same math XLA fuses today.
define_flag("use_fused_layernorm", True,
            "fused pallas residual-add + LayerNorm on TPU "
            "(jnp fallback elsewhere; identical math)")

# ops/quantize_kernels.py matmul_int8/mul_int8 + ops/pallas/
# int8_matmul.py — run the int8×int8→int32 contraction of deployed int8
# inference programs as a pallas MXU kernel on TPU. The jnp fallback is
# the identical dot_general (integer math: bit-equal), so the flag never
# changes numerics — same discipline as the other pallas gates.
define_flag("use_int8_matmul", True,
            "pallas int8 matmul kernel for deployed int8 programs on TPU "
            "(jnp int8 dot_general fallback elsewhere; bit-equal)")

# framework/jit.py TrainStepFn/ShardedTrainStep + distributed/
# quantized.py — EQuARX-style quantized DP gradient all-reduce: gradients
# cross the wire as int8 with per-block f32 scales (alltoall the
# quantized shards, dequant-accumulate, requantize, all-gather), cutting
# gradient-sync wire bytes ~4x (certified by the collective/<prim>/
# traced_algo_bytes ledger and ici_bus_util gauges). Read at train-step
# CONSTRUCTION (like donate): set it before building the step.
define_flag("quantized_allreduce", False,
            "int8-with-per-block-scales DP gradient all-reduce "
            "(~4x fewer gradient-sync wire bytes; read at step build)")

# distributed/quantized.py — elements per quantization block (one f32
# scale each). Larger blocks amortize scale wire bytes; smaller blocks
# track outliers tighter. 2048 keeps scale overhead at 0.2% of payload.
define_flag("quantized_allreduce_block", 2048,
            "elements per int8 quantization block in the quantized "
            "all-reduce (one f32 scale per block)")

# io/dataloader.py _DevicePrefetcher — issue the NEXT batches' host
# fetch + jax.device_put from a background thread while the consumer's
# step runs (double-buffered h2d/compute overlap). Off: the legacy
# synchronous refill (the consumer's __next__ pays the upstream parse
# and the device_put enqueue inline).
define_flag("io_prefetch_overlap", True,
            "overlap dataloader H2D transfers with compute via a "
            "background prefetch thread (double-buffered)")

# tuning/ + ops/pallas/* — the kernel autotuner's dispatch policy.
# Every gated pallas kernel resolves its schedule (block rows/cols,
# tile geometry) through tuning.resolve():
#   off    — defaults only, zero tuner work (no cache load, no counters)
#   cached — tuned params on a cache hit, defaults on a miss; NO search
#   search — like cached, plus misses enqueue a background per-
#            device_kind search whose winner applies at the next
#            CompiledStore compile of the signature (never inline)
# Winners persist next to FLAGS_persistent_compile_cache_dir
# (tuning/cache.py); runtime/compiled.py folds the schedule token into
# every compile identity so a swap is a clean recompile.
define_flag("kernel_autotune", "cached",
            "pallas kernel schedule policy: off | cached | search "
            "(search tunes misses in the background, offline-style)")

# monitor/registry.py — hard per-family cardinality bound for labeled
# metric children (``metric.labels(**dims)``). Once a family holds this
# many distinct label sets, every NEW set collapses into one shared
# series whose label values are all "other" (plus a single
# metric_series_overflow flight event), so an unbounded dimension (a
# hostile tenant header) can never grow registry memory without limit.
# Read at labels() time, so set_flags applies to live families.
define_flag("metrics_max_series", 64,
            "max distinct label sets per metric family before new sets "
            "collapse into the shared 'other' overflow series")

# monitor/slo.py — declarative serving objectives installed by every
# fleet entrypoint (serving/backend.py, serving/router.py) via
# install_from_flags(). ';'-separated entries, '|'-separated fields:
#   name|selector|threshold_ms=250|target=0.99|window_s=3600
#   name|bad_selector|error_ratio=<total_selector>|target=0.999
# selector grammar: metric or metric{k=v,k2=v2} (labels subset-match
# the family's labeled series). Empty (default): no objectives.
define_flag("slo_objectives", "",
            "SLO definitions 'name|selector|k=v|...' joined by ';' "
            "(fields: threshold_ms | error_ratio, target, window_s, "
            "alert_burn); empty disables")

# monitor/slo.py SLOEngine — period of the background good/total
# sampler the burn-rate windows are computed over. Shorter intervals
# sharpen the fast (5m-style) window at the cost of more registry
# snapshots; the engine keeps at most one slow window of samples.
define_flag("slo_sample_interval_s", 10.0,
            "seconds between SLO engine good/total samples of the "
            "metric registry")

# monitor/slo.py + serving/scaler.py — burn-rate alert threshold (the
# Google-SRE multi-window convention: 14.4x burn consumes a 30-day
# budget in ~2 days). An SLO alerts when BOTH its fast and slow
# windows burn at/above this; the autoscaler treats the same
# double-window-confirmed burn as scale-up pressure.
define_flag("slo_burn_alert", 14.4,
            "error-budget burn-rate multiple at which an SLO alerts "
            "(both windows) and the autoscaler sees up-pressure")

# monitor/goodput.py — lifetime training goodput/badput ledger. The
# directory holds the GOODPUT.json sidecar (atomic tmp->rename + CRC,
# the checkpoint publication discipline), so a kill -9 restart CONTINUES
# the same lifetime accounting instead of starting a fresh wall clock.
# Empty (default): ledger off — zero step-path cost.
define_flag("goodput_dir", "",
            "directory for the training goodput ledger's GOODPUT.json "
            "sidecar; empty disables the ledger")

# How often the ledger re-publishes its sidecar, piggybacked on step
# commits (0 = every committed step — what the goodput smoke uses so the
# kill -9 window is one step wide). The ledger also publishes after
# every checkpoint publication, so the sidecar is never staler than the
# newest snapshot a resume could land on.
define_flag("goodput_publish_interval_s", 30.0,
            "seconds between goodput sidecar publications (piggybacked "
            "on step commits; 0 publishes every step)")

# Optional goodput-ratio SLO driven through monitor/slo.py's burn-rate
# engine: error mode over goodput/badput_seconds_total (bad) vs
# goodput/wall_seconds_total (total), i.e. the objective is
# "goodput >= target". 0 (default): no objective installed.
define_flag("goodput_slo_target", 0.0,
            "goodput-ratio SLO target (e.g. 0.9) installed through the "
            "burn-rate engine; 0 disables")

# models/resnet.py + nn/layers.py fused_conv_bn_relu + ops/pallas/
# conv_bn_relu.py — fuse the vision path's conv -> batch_norm -> relu
# triple into pallas kernels on TPU: the conv contraction runs as a
# tiled MXU matmul whose epilogue applies the BN affine + relu in VMEM
# (eval: one pass; training: matmul+stats pass, then normalize+relu
# pass), so the pre-activation never round-trips HBM. The jnp fallback
# calls the IDENTICAL conv2d/batch_norm/relu op kernels in the same
# order, so the flag never changes numerics off-TPU — the same
# discipline as the PR-10 fused kernels.
define_flag("use_fused_conv_bn", True,
            "fused pallas conv+batch_norm+relu on TPU for the vision "
            "path (jnp fallback elsewhere; identical op sequence)")

# monitor/opprof.py profile_program — per-op replay measurement
# discipline: each op's jitted kernel is warmed `opprof_warmup` times,
# then timed best-of-`opprof_repeats` behind block_until_ready. Raise
# repeats for tighter numbers on a noisy host; the smoke/CI defaults
# keep a full BERT-smoke replay under a second on the CPU runner.
define_flag("opprof_warmup", 1,
            "per-op replay profiler: warmup runs before timing each op")
define_flag("opprof_repeats", 3,
            "per-op replay profiler: timed runs per op (best-of-N)")

# monitor/opprof.py top_ops / profilez_payload — how many ops the
# /statz top-K table and the default /profilez view keep (the full
# per-op table stays in the stored profile; /profilez?topk=N overrides
# per request).
define_flag("opprof_topk", 10,
            "top-K ops by device time shown on /statz and /profilez")
