"""Samplers (fluid/dataloader/batch_sampler.py + 2.0 paddle.io samplers)."""
from __future__ import annotations

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(
            len(self.weights), self.num_samples, replace=self.replacement, p=p
        )
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """fluid/dataloader/batch_sampler.py:BatchSampler."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """distributed/fleet version: shard sample indices over dp ranks.

    On the single-controller TPU runtime the global batch is sharded over
    the mesh inside the step, so rank-sharding is only needed for
    multi-host input pipelines (nranks = process count).
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size

        self.nranks = num_replicas or get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        super().__init__(dataset, None, shuffle, batch_size, drop_last)
        self.epoch = 0

    def __iter__(self):
        n = len(self.data_source)
        indices = np.arange(n)
        if isinstance(self.sampler, RandomSampler):
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n)
        # pad to a multiple of nranks then take this rank's slice
        total = ((n + self.nranks - 1) // self.nranks) * self.nranks
        indices = np.concatenate([indices, indices[: total - n]])
        local = indices[self.local_rank :: self.nranks]
        batch = []
        for idx in local.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = (len(self.data_source) + self.nranks - 1) // self.nranks
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
