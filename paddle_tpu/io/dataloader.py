"""DataLoader with multiprocess workers and device prefetch.

Reference parity: fluid/reader.py DataLoader :123 / DygraphGeneratorLoader
:697 (worker subprocess loop :870), operators/reader/buffered_reader.cc
(double-buffered H2D prefetch), memory/allocation/mmap_allocator.cc
(shared-memory tensor transport between workers and the trainer).

TPU-native: worker processes serialize numpy batches over the native
shared-memory ring (paddle_tpu._native.shm_ring, C++) — falling back to
multiprocessing.Queue pickling — and the main process keeps
``prefetch_factor`` batches in flight with async jax.device_put, so the
accelerator never stalls on input (buffered_reader.cc's role).
"""
from __future__ import annotations

import atexit
import itertools
import multiprocessing as mp
import os
import queue as queue_mod
import threading
import time
import weakref

import numpy as np

from ..monitor import record_input_wait_ms, registry as _mon
from ..monitor import flight_recorder as _flight
from ..profiler import RecordEvent
from .dataset import IterableDataset
from .sampler import BatchSampler

# stop sentinel must survive pickling across the process boundary (an
# object() loses identity in the worker), so use None
_MP_STOP = None


def default_collate_fn(batch):
    """Stack samples into batch arrays (fluid/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(
            default_collate_fn([s[i] for s in batch])
            for i in range(len(sample))
        )
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    from ..framework.tensor import Tensor

    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch])
    return np.asarray(batch)


def _worker_loop(dataset, index_queue, data_queue, collate_fn, ring_name,
                 ring_capacity):
    """Worker process body (reader.py:870 _reader_process_loop).

    Results travel over the native shared-memory ring when available
    (mmap_allocator.cc transport equivalent); the mp.Queue is the fallback
    and the error channel.
    """
    ring = None
    if ring_name:
        try:
            from .._native import ShmRing

            ring = ShmRing(ring_name, capacity=ring_capacity, owner=False)
        except Exception:
            ring = None
    try:
        while True:
            task = index_queue.get()
            if task is None:
                break
            seq, indices = task
            try:
                batch = collate_fn([dataset[i] for i in indices])
                if ring is not None:
                    try:
                        ring.put((seq, batch))
                        data_queue.put((seq, ring_name, None))  # ready signal
                        continue
                    except ValueError:  # batch larger than the ring
                        pass
                data_queue.put((seq, batch, None))
            except Exception as e:  # propagate to main process
                data_queue.put((seq, None, e))
    except KeyboardInterrupt:
        pass
    finally:
        if ring is not None:
            ring.close(unlink=False)


class _MultiprocessIter:
    def __init__(self, loader):
        self.loader = loader
        ds = loader.dataset
        self.batches = list(iter(loader.batch_sampler))
        ctx = mp.get_context("fork")
        self.index_queue = ctx.Queue()
        self.data_queue = ctx.Queue(maxsize=loader.num_workers * loader.prefetch_factor)
        # one shared-memory ring per worker (SPSC); None disables
        self.rings = {}
        ring_names = [None] * loader.num_workers
        ring_cap = 64 << 20
        if loader.use_shared_memory:
            try:
                from .._native import ShmRing, available

                if available():
                    for i in range(loader.num_workers):
                        name = f"/ptpu_dl_{os.getpid()}_{id(self) & 0xFFFF}_{i}"
                        self.rings[name] = ShmRing(
                            name, capacity=ring_cap, owner=True
                        )
                    ring_names = list(self.rings.keys())
            except Exception:
                self.rings = {}
        self.workers = [
            ctx.Process(
                target=_worker_loop,
                args=(ds, self.index_queue, self.data_queue,
                      loader.collate_fn, ring_names[i], ring_cap),
                daemon=True,
            )
            for i in range(loader.num_workers)
        ]
        for w in self.workers:
            w.start()
        # worker-lifecycle breadcrumb: a dump taken while the main thread
        # is parked in worker_wait shows exactly which worker pids were
        # supposed to be feeding it (and whether shm rings were in play)
        _flight.record_event(
            "dataloader_workers_start", workers=len(self.workers),
            pids=[w.pid for w in self.workers],
            batches=len(self.batches), shm_rings=len(self.rings))
        atexit.register(self.shutdown)
        self._send = 0
        self._recv = 0
        self._reorder = {}
        # pre-dispatch
        for _ in range(loader.num_workers * loader.prefetch_factor):
            self._dispatch()

    def _dispatch(self):
        if self._send < len(self.batches):
            self.index_queue.put((self._send, self.batches[self._send]))
            self._send += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self._recv >= len(self.batches):
            self.shutdown()
            raise StopIteration
        if self._recv not in self._reorder:
            # the main process is BLOCKED on workers here — the span/stat
            # that tells an input-bound run from a compute-bound one
            with RecordEvent("dataloader::worker_wait"):
                t0 = time.perf_counter()
                while self._recv not in self._reorder:
                    seq, batch, err = self.data_queue.get()
                    if err is not None:
                        self.shutdown()
                        raise err
                    if isinstance(batch, str) and batch in self.rings:
                        # ready-signal: payload sits in that worker's ring
                        rseq, batch = self.rings[batch].get()
                        seq = rseq
                    self._reorder[seq] = batch
                _mon.histogram("io/worker_wait_ms").observe(
                    (time.perf_counter() - t0) * 1e3)
        batch = self._reorder.pop(self._recv)
        self._recv += 1
        self._dispatch()
        return batch

    def shutdown(self):
        if self.workers:
            _flight.record_event(
                "dataloader_workers_stop", workers=len(self.workers),
                delivered=getattr(self, "_recv", 0),
                dispatched=getattr(self, "_send", 0))
        for _ in self.workers:
            try:
                self.index_queue.put(_MP_STOP)
            except Exception:
                pass
        for w in self.workers:
            w.join(timeout=1)
            if w.is_alive():
                w.terminate()
        self.workers = []
        for ring in getattr(self, "rings", {}).values():
            try:
                ring.close(unlink=True)
            except Exception:
                pass
        self.rings = {}


class _DevicePrefetcher:
    """buffered_reader.cc equivalent: keep N batches already on device,
    with the host fetch + H2D enqueue OVERLAPPING the consumer's step.

    Under ``FLAGS_io_prefetch_overlap`` (default) a background thread
    owns the upstream ``next()`` (parse/collate wait) and the
    ``jax.device_put`` enqueue, double-buffered through a bounded queue
    of ``depth`` device-resident batches — the consumer's ``__next__``
    is a queue pop, so batch N+1's transfer is in flight while step N
    computes and the only consumer-visible input wait is a genuine
    underrun (visible as the monitor's ``input_wait_ratio``). With the
    flag off, the legacy synchronous refill runs inline in ``__next__``
    (the consumer pays parse + enqueue on the step path) — the A/B the
    bench's ``input_overlap`` sub-metric measures. Shared by the
    DataLoader's buffer reader and Executor.train_from_dataset (via
    DatasetBase._iter_device_batches)."""

    _DONE = object()

    def __init__(self, it, depth=2, to_device=None):
        from ..flags import flag

        self.it = it
        self.depth = max(1, int(depth))
        self.to_device = to_device
        self._overlap = bool(flag("io_prefetch_overlap"))
        if self._overlap:
            self._q = queue_mod.Queue(maxsize=self.depth)
            self._stop = threading.Event()
            self._done = False
            # the fill thread closes ONLY over (it, q, stop) — never
            # self: a thread frame referencing the prefetcher would keep
            # it reachable forever, so an abandoned iterator could never
            # be collected and the finalizer below could never fire
            self._thread = threading.Thread(
                target=_prefetch_fill_loop,
                args=(self.it, self.to_device, self._q, self._stop,
                      self._DONE),
                name="ptpu-h2d-prefetch", daemon=True)
            self._thread.start()
            # abandonment shutdown: when the consumer drops the iterator
            # mid-epoch, GC runs this and the fill thread exits at its
            # next 0.1s stop-check instead of spinning forever
            self._finalizer = weakref.finalize(self, self._stop.set)
        else:
            self.buf = []
            self._fill()

    def close(self):
        """Stop the background fill (idempotent)."""
        if self._overlap:
            self._stop.set()

    # -- legacy synchronous path --------------------------------------------

    def _fill(self):
        while len(self.buf) < self.depth:
            try:
                self.buf.append(
                    _prefetch_prepare(self.it, self.to_device))
            except StopIteration:
                return

    def __iter__(self):
        return self

    def __next__(self):
        # consumer-side wall time in here is input wait: with overlap on
        # it is the queue-pop wait (a true underrun); with it off, the
        # inline refill's upstream parse/collate + enqueue
        t0 = time.perf_counter()
        if self._overlap:
            if self._done:
                raise StopIteration  # terminal: never block again
            while True:
                try:
                    item = self._q.get(timeout=0.1)
                    break
                except queue_mod.Empty:
                    # after close() the fill thread refuses further puts
                    # (even its DONE tail), so an empty queue is
                    # terminal — without this check a consumer would
                    # block forever waiting for a sentinel that can
                    # never arrive
                    if self._stop.is_set():
                        self._done = True
                        raise StopIteration
            if item is self._DONE:
                self._done = True
                self.close()
                raise StopIteration
            if isinstance(item, BaseException):
                self._done = True
                self.close()
                raise item
            batch = item
        else:
            if not self.buf:
                raise StopIteration
            batch = self.buf.pop(0)
            self._fill()
        _mon.counter("io/batches").inc()
        # feeds io/input_wait_ms_total (counter), the monitor's window
        # input-wait ratio, and the goodput ledger's input_wait phase
        record_input_wait_ms((time.perf_counter() - t0) * 1e3)
        return batch


def _prefetch_prepare(it, to_device):
    """One upstream fetch + device enqueue (both prefetcher paths)."""
    with RecordEvent("dataloader::prefetch_fill"):
        batch = next(it)
    if to_device:
        import jax

        # async enqueue of the H2D copy (the actual transfer overlaps
        # the consumer's step; the span shows enqueue stalls when the
        # transfer queue backs up)
        with RecordEvent("dataloader::h2d"):
            batch = jax.tree_util.tree_map(jax.device_put, batch)
    return batch


def _prefetch_fill_loop(it, to_device, q, stop, done_sentinel):
    """_DevicePrefetcher's background fill (module-level on purpose —
    see the constructor: the thread must not keep the prefetcher
    alive). Exceptions travel to the consumer through the queue."""

    def put(item) -> bool:
        # bounded put that stays responsive to shutdown: an abandoned
        # consumer must not leave the thread parked on a full queue
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue_mod.Full:
                continue
        return False

    tail = done_sentinel
    try:
        while not stop.is_set():
            try:
                item = _prefetch_prepare(it, to_device)
            except StopIteration:
                break
            except BaseException as e:  # surface on the consumer side
                tail = e
                break
            if not put(item):
                return  # consumer abandoned the iterator
    finally:
        put(tail)


class _AccountedIter:
    """Input-wait accounting for the unbuffered path (the buffered path
    accounts inside _DevicePrefetcher.__next__). Attribute access
    proxies to the wrapped iterator so callers still reach the
    multiprocess machinery (rings, shutdown) underneath."""

    def __init__(self, it):
        self._it = it

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        batch = next(self._it)
        _mon.counter("io/batches").inc()
        record_input_wait_ms((time.perf_counter() - t0) * 1e3)
        return batch

    def __getattr__(self, name):
        return getattr(self._it, name)


class DataLoader:
    """paddle.io.DataLoader surface (fluid/reader.py:123)."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.return_list = return_list
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last,
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _single_iter(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        # one event per epoch: correlates "which epoch / which mode" with
        # whatever the rest of the ring shows hanging
        _flight.record_event(
            "dataloader_epoch",
            workers=self.num_workers if not self._iterable_mode else 0,
            iterable=self._iterable_mode,
            buffered=self.use_buffer_reader)
        if self.num_workers > 0 and not self._iterable_mode:
            it = iter(_MultiprocessIter(self))
        else:
            it = self._single_iter()
        if self.use_buffer_reader:
            return iter(
                _DevicePrefetcher(it, depth=self.prefetch_factor,
                                  to_device=True)
            )
        return _AccountedIter(it)
