"""Dataset/DataFeed ingestion — the out-of-Python file-list pipeline.

Reference parity: python/paddle/fluid/dataset.py (DatasetFactory :37,
InMemoryDataset :328 with load_into_memory/local_shuffle/global_shuffle,
QueueDataset :632 streaming) over the C++ runtime
paddle/fluid/framework/data_set.cc + data_feed.cc (MultiSlotDataFeed text
format: per line, per slot: count then values).

TPU-native redesign: the parse hot loop is native C++
(_native/datafeed.cpp, two-pass tokenizer over raw file bytes) fanned out
over multiprocess workers with the shared-memory ring transport the
DataLoader already uses (_native/shm_ring.cpp); batches come out as
STATIC-SHAPE numpy arrays (sparse slots padded/truncated to the declared
slot width) so the compiled step never re-specializes — where the
reference emits variable-length LoDTensors, XLA wants fixed shapes, and
the padded-id convention (pad=0) is the standard TPU embedding recipe.
Executor.train_from_dataset drives the compiled whole-block step over the
batch stream (fluid/executor.py:1597).
"""
from __future__ import annotations

import glob as _glob
import multiprocessing as mp
import os
import subprocess

import numpy as np

__all__ = ["DatasetFactory", "DatasetBase", "InMemoryDataset",
           "QueueDataset"]


class DatasetFactory:
    """fluid.DatasetFactory parity: create_dataset by class name."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")


def _parse_bytes(buf, slot_is_float):
    """Native parser with a pure-python fallback."""
    from .. import _native

    if _native.datafeed_available():
        return _native.multislot_parse(buf, slot_is_float)
    # fallback: python tokenizer (same format, ~20x slower)
    counts, ints, floats = [], [], []
    for line in buf.decode().splitlines():
        toks = line.split()
        if not toks:
            continue
        i = 0
        for s, is_f in enumerate(slot_is_float):
            cnt = int(toks[i]); i += 1
            counts.append(cnt)
            for _ in range(cnt):
                (floats if is_f else ints).append(
                    float(toks[i]) if is_f else int(toks[i]))
                i += 1
        if i != len(toks):
            raise ValueError(f"malformed MultiSlot line: {line!r}")
    n_slots = len(slot_is_float)
    return (np.asarray(counts, np.int64).reshape(-1, n_slots),
            np.asarray(ints, np.int64), np.asarray(floats, np.float32))


def _read_file(path, pipe_command=None):
    if pipe_command and pipe_command not in ("cat", "cat ", ""):
        with open(path, "rb") as f:
            out = subprocess.run(
                pipe_command, shell=True, stdin=f,
                capture_output=True, check=True,
            )
        return out.stdout
    with open(path, "rb") as f:
        return f.read()


def _parse_worker(files, slot_is_float, pipe_command, ring_name):
    """Worker process: parse assigned files, push per-file pools onto its
    OWN ring (the ShmRing is single-producer single-consumer — one ring
    per worker, exactly like the DataLoader's transport)."""
    ring = None
    try:
        from .. import _native

        ring = _native.ShmRing(ring_name, owner=False)
        for path in files:
            buf = _read_file(path, pipe_command)
            pools = _parse_bytes(buf, slot_is_float)
            ring.put(("data", pools))
        ring.put(("done", None))
        ring.close(unlink=False)
    except Exception as e:  # propagate the failure to the consumer
        if ring is not None:
            try:
                ring.put(("error", f"{type(e).__name__}: {e}"))
                ring.close(unlink=False)
            except Exception:
                pass


class DatasetBase:
    """Shared Dataset surface (fluid/dataset.py DatasetBase :64)."""

    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._filelist = []
        self._use_vars = []
        self._pipe_command = None
        self._fleet = None
        self._seed = None

    # -- configuration (reference method names) -----------------------------
    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self._thread_num = max(1, int(thread_num))

    def set_filelist(self, filelist):
        out = []
        for f in filelist:
            hits = sorted(_glob.glob(f)) if any(c in f for c in "*?[") else [f]
            out.extend(hits or [f])
        self._filelist = out

    def set_use_var(self, var_list):
        """Declare the slot order/dtypes/widths from program data vars
        (dataset.py set_use_var — builds the data_feed.proto slot list)."""
        self._use_vars = list(var_list)

    def set_pipe_command(self, pipe_command):
        self._pipe_command = pipe_command

    def set_hdfs_config(self, fs_name, fs_ugi):  # accepted for parity
        self._hdfs = (fs_name, fs_ugi)

    def desc(self):
        slots = ", ".join(
            f"{getattr(v, 'name', v)}:{self._slot_kind(v)}"
            for v in self._use_vars
        )
        return (f"{type(self).__name__}(batch={self._batch_size}, "
                f"threads={self._thread_num}, files={len(self._filelist)}, "
                f"slots=[{slots}])")

    # -- slot plumbing -------------------------------------------------------
    @staticmethod
    def _slot_kind(v):
        d = str(getattr(v, "dtype", "int64"))
        return "float" if ("float" in d or "double" in d) else "int"

    def _slot_spec(self):
        if not self._use_vars:
            raise ValueError("call set_use_var(...) before reading data")
        is_float = [self._slot_kind(v) == "float" for v in self._use_vars]
        widths = []
        for v in self._use_vars:
            shape = list(getattr(v, "shape", None) or [1])
            w = 1
            for d in shape[1:] if len(shape) > 1 else shape[-1:]:
                if d is not None and int(d) > 0:
                    w *= int(d)
            widths.append(max(1, w))
        return is_float, widths

    def _pools_iter(self):
        """Yield (file_idx, (counts, ints, floats)) per file, parsed by
        worker processes over the shm ring (DataLoader's transport).

        file_idx is the file's position in the filelist. With thread>1 the
        rings drain in timing-dependent order, but worker w emits exactly
        one pool per assigned file, in order — so the consumer recovers
        the deterministic index as w + seq_w * n_workers. InMemoryDataset
        reassembles in file order; without this, every trainer would hold
        a differently-ordered memory and a positional global_shuffle
        partition would silently drop/duplicate instances."""
        is_float, _ = self._slot_spec()
        if not self._filelist:
            return
        from .. import _native

        n_workers = min(self._thread_num, len(self._filelist))
        if n_workers <= 1 or not _native.available():
            for idx, path in enumerate(self._filelist):
                yield idx, _parse_bytes(
                    _read_file(path, self._pipe_command), is_float)
            return

        # one SPSC ring per worker (shm_ring.cpp's contract); the consumer
        # round-robins over them
        rings = [
            _native.ShmRing(capacity=(256 << 20) // n_workers)
            for _ in range(n_workers)
        ]
        ctx = mp.get_context("fork")
        procs = []
        for w in range(n_workers):
            files = self._filelist[w::n_workers]
            p = ctx.Process(
                target=_parse_worker,
                args=(files, is_float, self._pipe_command, rings[w].name),
                daemon=True,
            )
            p.start()
            procs.append(p)
        live = set(range(n_workers))
        seq = [0] * n_workers  # per-worker pool count -> global file index
        try:
            while live:
                progressed = False
                for w in sorted(live):
                    if rings[w].empty():
                        if not procs[w].is_alive():
                            # died without a done/error record (segfault,
                            # kill): drain anything left, then fail fast
                            # instead of a 120s timeout
                            if rings[w].empty():
                                raise RuntimeError(
                                    f"dataset parse worker {w} exited "
                                    f"(code {procs[w].exitcode}) without "
                                    "completing"
                                )
                        continue
                    kind, payload = rings[w].get(timeout=30.0)
                    progressed = True
                    if kind == "done":
                        live.discard(w)
                    elif kind == "error":
                        raise RuntimeError(
                            f"dataset parse worker {w}: {payload}"
                        )
                    else:
                        file_idx = w + seq[w] * n_workers
                        seq[w] += 1
                        yield file_idx, payload
                if live and not progressed:
                    import time as _time

                    _time.sleep(0.002)
        finally:
            for p in procs:
                p.join(timeout=5.0)
                if p.is_alive():
                    p.terminate()
            for r in rings:
                r.close(unlink=True)

    def _split_instances(self, pools):
        """Pool arrays -> list of per-instance per-slot value arrays."""
        counts, ints, floats = pools
        is_float, _ = self._slot_spec()
        out = []
        ii = fi = 0
        for r in range(counts.shape[0]):
            inst = []
            for s, is_f in enumerate(is_float):
                c = int(counts[r, s])
                if is_f:
                    inst.append(floats[fi:fi + c])
                    fi += c
                else:
                    inst.append(ints[ii:ii + c])
                    ii += c
            out.append(inst)
        return out

    def _assemble_batch(self, instances):
        """Fixed-shape batch per slot: [B, width], pad 0 / truncate (the
        XLA static-shape stand-in for the reference's LoDTensor slots)."""
        is_float, widths = self._slot_spec()
        batch = []
        for s, (is_f, w) in enumerate(zip(is_float, widths)):
            dt = np.float32 if is_f else np.int64
            arr = np.zeros((len(instances), w), dt)
            for r, inst in enumerate(instances):
                vals = inst[s][:w]
                arr[r, :len(vals)] = vals
            batch.append(arr)
        return batch

    def _feed_names(self):
        return [getattr(v, "name", str(v)) for v in self._use_vars]

    def _iter_device_batches(self, depth=2):
        """Device-resident batch stream: keep ``depth`` batches' H2D
        transfers in flight (buffered_reader.cc's double buffering, via
        the DataLoader's _DevicePrefetcher) so the executor's dispatch of
        step N overlaps batch N+1's host->device copy."""
        from .dataloader import _DevicePrefetcher

        return iter(_DevicePrefetcher(iter(self._iter_batches()),
                                      depth=depth, to_device=True))

    # subclasses provide _iter_batches()


class InMemoryDataset(DatasetBase):
    """fluid.InMemoryDataset (dataset.py:328): parse everything into host
    memory once, then shuffle/iterate without touching the files again."""

    def __init__(self):
        super().__init__()
        self._memory = []
        self._shuffled = None

    def load_into_memory(self):
        # reassemble in file order so every trainer holding the same
        # filelist holds the same instance ordering, no matter how the
        # worker rings interleave — global_shuffle's positional partition
        # depends on this. Only out-of-order pools are buffered (the
        # drain-order backlog), not the whole dataset twice.
        self._memory = []
        pending = {}
        next_idx = 0
        for idx, pools in self._pools_iter():
            pending[idx] = pools
            while next_idx in pending:
                self._memory.extend(
                    self._split_instances(pending.pop(next_idx)))
                next_idx += 1
        for idx in sorted(pending):  # gaps only if a tail file was empty
            self._memory.extend(self._split_instances(pending.pop(idx)))
        self._shuffled = None

    def local_shuffle(self):
        rng = np.random.RandomState(self._seed)
        order = rng.permutation(len(self._memory))
        self._shuffled = [self._memory[i] for i in order]

    def global_shuffle(self, fleet=None, thread_num=12):
        """Cross-trainer shuffle — decentralized redesign of the
        reference's PS-mediated global shuffle (data_set.cc GlobalShuffle).

        PRECONDITION (differs from the reference!): with multiple
        trainers, every trainer must have loaded the SAME FULL filelist.
        All trainers then draw the same permutation seed and each keeps
        the 1/trainer_num partition hashed to its id — same global
        coverage as the reference's instance exchange, with the file reads
        replacing the PS network hop. Feeding per-trainer DISJOINT
        filelists here would silently drop (n-1)/n of the corpus, so that
        layout is rejected loudly: shard via global_shuffle, not via the
        filelist. With one trainer this degenerates to local_shuffle.
        """
        trainer_id, trainer_num = 0, 1
        if fleet is not None:
            trainer_id = getattr(fleet, "worker_index", lambda: 0)()
            trainer_num = getattr(fleet, "worker_num", lambda: 1)()
        seed = self._seed if self._seed is not None else 12345
        rng = np.random.RandomState(seed)
        order = rng.permutation(len(self._memory))
        if trainer_num > 1:
            sizes = None
            allgather = getattr(fleet, "_all_gather", None)
            if callable(allgather):
                try:
                    sizes = allgather(len(self._memory))
                except Exception:
                    sizes = None
            if sizes is not None and len(set(int(s) for s in sizes)) > 1:
                raise RuntimeError(
                    "global_shuffle requires every trainer to load the "
                    "same full filelist (got per-trainer sizes "
                    f"{sizes}); see InMemoryDataset.global_shuffle docs"
                )
            order = [i for i in order if i % trainer_num == trainer_id]
        self._shuffled = [self._memory[i] for i in order]

    def release_memory(self):
        self._memory = []
        self._shuffled = None

    def get_memory_data_size(self, fleet=None):
        return len(self._memory)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._shuffled if self._shuffled is not None
                   else self._memory)

    def set_shuffle_seed(self, seed):
        self._seed = int(seed)

    def _iter_batches(self):
        data = self._shuffled if self._shuffled is not None else self._memory
        b = self._batch_size
        for i in range(0, len(data) - b + 1, b):
            yield self._assemble_batch(data[i:i + b])


class QueueDataset(DatasetBase):
    """fluid.QueueDataset (dataset.py:632): single-pass streaming — files
    are parsed by the workers while training consumes batches; nothing is
    retained."""

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset is single-pass streaming; use InMemoryDataset "
            "for shuffles (fluid/dataset.py:664 raises the same way)"
        )

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            "QueueDataset does not support global shuffle "
            "(fluid/dataset.py:678)"
        )

    def _iter_batches(self):
        b = self._batch_size
        pending = []
        for _idx, pools in self._pools_iter():
            pending.extend(self._split_instances(pools))
            while len(pending) >= b:
                yield self._assemble_batch(pending[:b])
                pending = pending[b:]
