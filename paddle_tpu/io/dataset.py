"""Dataset abstractions (fluid/dataloader/dataset.py)."""
from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    """Map-style dataset: __getitem__ + __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    """Stream-style dataset: __iter__."""

    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        from ..framework.tensor import Tensor

        arrays = [
            t.numpy() if isinstance(t, Tensor) else np.asarray(t)
            for t in tensors
        ]
        n = arrays[0].shape[0]
        for a in arrays:
            assert a.shape[0] == n, "all tensors must share dim 0"
        self._arrays = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self._arrays)

    def __len__(self):
        return self._arrays[0].shape[0]


class ComposeDataset(Dataset):
    """Zip datasets: sample i is the concat of each dataset's sample i."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        for d in self.datasets:
            assert len(d) == n
        self._len = n

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            s = d[idx]
            out.extend(s if isinstance(s, (tuple, list)) else [s])
        return tuple(out)

    def __len__(self):
        return self._len


class ChainDataset(IterableDataset):
    """Concatenate iterable datasets."""

    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    if sum(lengths) != n:
        raise ValueError("sum of lengths must equal dataset size")
    rng = np.random.RandomState(
        generator if isinstance(generator, int) else None
    )
    perm = rng.permutation(n)
    out, start = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[start : start + ln].tolist()))
        start += ln
    return out


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        di = bisect.bisect_right(self.cum, idx)
        prev = 0 if di == 0 else self.cum[di - 1]
        return self.datasets[di][idx - prev]
