"""paddle.io equivalent — datasets, samplers, DataLoader.

Reference parity: python/paddle/fluid/dataloader/ (dataset.py,
batch_sampler.py, collate), fluid/reader.py DataLoader :123 (multiprocess
worker loop :870, shared-memory transport via memory/allocation/
mmap_allocator.cc + pybind/reader_py.cc), operators/reader/
buffered_reader.cc (double-buffer H2D prefetch).

TPU-native: workers feed a prefetch pipeline that lands batches in device
memory (jax.device_put ahead of use) so the step function never waits on
H2D; the shared-memory transport is the native ring buffer in
paddle_tpu/_native (C++), with a multiprocessing.shared_memory fallback.
"""
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    WeightedRandomSampler,
)
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .feed import (  # noqa: F401
    DatasetBase,
    DatasetFactory,
    InMemoryDataset,
    QueueDataset,
)
