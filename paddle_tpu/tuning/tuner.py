"""The kernel tuner: offline per-device schedule search.

TVM's discipline (PAPERS.md): search OFFLINE, pay nothing at steady
state. ``KernelTuner.tune`` measures every VALID candidate of a
kernel's schedule space for one concrete shape — invalid candidates
(VMEM overflow, unsupported tile) are pruned by the space's predicate
BEFORE any compile is paid — and records the winner in the persistent
tuning cache, where ``resolve()`` finds it and ``schedule_token()``
turns it into a clean recompile at the next CompiledStore build.

Measurement is best-of-N timed jitted calls with a value-fetch barrier
(``block_until_ready`` inside the timed run): one untimed warmup call
absorbs the compile, then N timed calls keep the minimum — the
standard dispersion-robust estimator for a shared box. The timer is
injectable (``timer=``) so tests drive the whole selection pipeline
with a deterministic fake timer and zero real compiles.

Background search (``FLAGS_kernel_autotune=search``): ``resolve()``
misses enqueue here; one daemon worker drains the queue, tunes, and
swaps winners into the cache. Hot paths never block on it — the next
compile of the signature picks the winner up. Tuning failures are
counted + flight-recorded, never raised into the training loop (the
"inconclusive never blocks" discipline).
"""
from __future__ import annotations

import queue
import threading
import time

from ..profiler import bump_counter
from .cache import tuning_cache
from .schedule import schedule_space

__all__ = ["TuneResult", "KernelTuner", "tune", "enqueue_search",
           "drain_background", "pending_searches"]


def _flight():
    from ..monitor import flight_recorder

    return flight_recorder


class TuneResult:
    """Outcome of one ``tune()``: the winning params plus the evidence
    (tuned-vs-default microseconds, candidate accounting)."""

    __slots__ = ("kernel", "params", "default", "best_us", "default_us",
                 "measured", "pruned", "cached")

    def __init__(self, kernel, params, default, best_us, default_us,
                 measured, pruned, cached):
        self.kernel = kernel
        self.params = params          # winning schedule point
        self.default = default        # the byte-identical untuned point
        self.best_us = best_us
        self.default_us = default_us
        self.measured = measured      # candidates actually timed
        self.pruned = pruned          # candidates rejected pre-compile
        self.cached = cached          # landed in the tuning cache

    @property
    def speedup(self) -> float:
        return (self.default_us / self.best_us
                if self.best_us and self.default_us else 1.0)

    def __repr__(self):
        # default_us is None when the default point itself was pruned
        # (the space's predicate rejects it for this exact shape)
        default = (f"{self.default_us:.1f}us"
                   if self.default_us is not None else "pruned")
        return (f"TuneResult({self.kernel!r}, {self.params}, "
                f"best={self.best_us:.1f}us, default={default}, "
                f"x{self.speedup:.3f}, measured={self.measured}, "
                f"pruned={self.pruned})")


class KernelTuner:
    """Measure-and-select over a kernel's schedule space.

    ``timer(run) -> seconds`` times ONE call of the zero-arg ``run``
    (which already blocks on its outputs); the default is a wall-clock
    ``perf_counter`` pair. ``measure_n`` best-of repetitions after one
    untimed warmup (the warmup pays the XLA compile, so timings are
    steady-state numbers)."""

    def __init__(self, *, measure_n=5, timer=None):
        self.measure_n = max(1, int(measure_n))
        self._timer = timer

    def _time_once(self, run) -> float:
        if self._timer is not None:
            return float(self._timer(run))
        t0 = time.perf_counter()
        run()
        return time.perf_counter() - t0

    def measure(self, run) -> float:
        """Best-of-N microseconds for one candidate's ``run``."""
        run()  # warmup: compile + first dispatch, never timed
        best = float("inf")
        for _ in range(self.measure_n):
            best = min(best, self._time_once(run))
        return best * 1e6

    def tune(self, kernel, *, candidates=None, cache=None,
             device_kind=None, save=True, **info) -> TuneResult:
        """Search one (kernel, shape) and record the winner.

        ``candidates`` overrides the space's full cartesian product
        (the CPU smoke restricts it); the default point is always
        included and measured — the claimed speedup is against the real
        baseline, not a guess. ``save=False`` measures without touching
        the cache (A/B reporting)."""
        space = schedule_space(kernel)
        default = space.default_params(info)
        points = list(candidates) if candidates is not None else None
        if points is None:
            points = space.candidates(info)
        else:
            points = [{**default, **p} for p in points]
            if default not in points:
                points.insert(0, default)
        # prune BEFORE compile: the predicate is the only code that runs
        # for an invalid candidate
        valid, pruned = [], 0
        for cand in points:
            if space.is_supported(info, cand):
                valid.append(cand)
            else:
                pruned += 1
        bump_counter("autotune::pruned", pruned)
        builder = space.bench(info)
        best_params, best_us, default_us = None, float("inf"), None
        for cand in valid:
            us = self.measure(builder(cand))
            bump_counter("autotune::measured")
            if cand == default:
                default_us = us
            if us < best_us:
                best_params, best_us = cand, us
        if best_params is None:
            from ..errors import PreconditionNotMetError

            raise PreconditionNotMetError(
                f"tune({kernel!r}): no valid candidate for {info} "
                f"({pruned} pruned)")
        bump_counter("autotune::search")
        store = cache if cache is not None else tuning_cache()
        cached = False
        if save:
            store.put(space, info, best_params, device_kind=device_kind,
                      best_us=round(best_us, 3),
                      default_us=round(default_us, 3)
                      if default_us is not None else None)
            cached = True
        result = TuneResult(kernel, best_params, default, best_us,
                            default_us, len(valid), pruned, cached)
        _flight().record_event(
            "autotune_search", kernel=kernel,
            params=dict(best_params),
            best_us=round(best_us, 3),
            default_us=(round(default_us, 3)
                        if default_us is not None else None),
            speedup=round(result.speedup, 3),
            measured=len(valid), pruned=pruned)
        return result


_default_tuner = [None]


def _tuner() -> KernelTuner:
    if _default_tuner[0] is None:
        _default_tuner[0] = KernelTuner()
    return _default_tuner[0]


def tune(kernel, **kw) -> TuneResult:
    """Module-level convenience over the default tuner."""
    return _tuner().tune(kernel, **kw)


# ---------------------------------------------------------------------------
# Background search (FLAGS_kernel_autotune=search)
# ---------------------------------------------------------------------------

_bg_lock = threading.Lock()
_bg_queue: "queue.Queue" = queue.Queue()
_bg_pending: set = set()
_bg_thread = [None]


def pending_searches() -> int:
    with _bg_lock:
        return len(_bg_pending)


def _bg_key(kernel, info) -> tuple:
    space = schedule_space(kernel)
    return (kernel, space.bucket(info))


def _bg_worker():
    while True:
        kernel, info, key = _bg_queue.get()
        try:
            _tuner().tune(kernel, **info)
        except Exception as e:
            # a failed background search must never surface into the
            # training loop — count it, record it, move on
            bump_counter("autotune::search_error")
            try:
                _flight().record_event("autotune_search_error",
                                       kernel=kernel,
                                       error=f"{type(e).__name__}: {e}")
            except Exception:
                pass
        finally:
            with _bg_lock:
                _bg_pending.discard(key)
            _bg_queue.task_done()


def enqueue_search(kernel, info: dict):
    """Queue one (kernel, shape-bucket) for background tuning — deduped
    so a hot loop missing the cache every step enqueues ONE search, not
    thousands. Called by ``resolve()`` under mode=search only."""
    try:
        key = _bg_key(kernel, info)
    except Exception:
        return
    with _bg_lock:
        if key in _bg_pending:
            return
        _bg_pending.add(key)
        if _bg_thread[0] is None or not _bg_thread[0].is_alive():
            _bg_thread[0] = threading.Thread(
                target=_bg_worker, name="ptpu-autotune", daemon=True)
            _bg_thread[0].start()
    bump_counter("autotune::enqueued")
    _bg_queue.put((kernel, dict(info), key))


def drain_background(timeout=60.0) -> bool:
    """Wait for every queued background search to finish (tools/tests;
    production never blocks on this). True when drained in time."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with _bg_lock:
            if not _bg_pending:
                return True
        time.sleep(0.01)
    return False
