"""Declarative kernel schedule spaces + the ``resolve()`` choke point.

Every gated pallas kernel (``ops/pallas/*``) registers ONE
:class:`ScheduleSpace` here: its tunable parameters (block rows/cols,
tile geometry, unroll factor), today's hardcoded geometry as the
DEFAULT point, and a validity predicate that prunes candidates (VMEM
overflow, tile misalignment) BEFORE any compile — the same role the
kernels' ``_supported`` gates play for shape admission, applied to
schedules.

``resolve(kernel, **shape_info)`` is the only way a kernel call site
asks for its schedule:

- cache hit  -> the tuned params for this (kernel, device_kind,
  shape-bucket, dtype, space-version) — re-validated against the EXACT
  shape (buckets are coarser than shapes, so a tuned point may not
  admit every shape in its bucket; an inadmissible hit degrades to the
  default, counted as ``autotune::cache_reject``).
- miss -> the default params, byte-identical to the pre-tuning
  hardcoded geometry. "Untuned" means "default schedule", not a
  separate code path.

``resolve`` NEVER searches inline: on a miss under
``FLAGS_kernel_autotune=search`` it enqueues the (kernel, shape) for
the background tuner and still returns defaults — the swapped-in
winner applies at the next CompiledStore compile of that signature
(``runtime/compiled.py`` folds :func:`schedule_token` into the compile
identity, so a swap is a clean recompile, never a stale-trace hazard).
``off`` returns defaults without touching the cache or the counters —
zero tuner work on the dispatch path.
"""
from __future__ import annotations

import itertools
import threading

from ..flags import flag
from ..profiler import bump_counter

__all__ = ["ScheduleSpace", "register_schedule", "schedule_space",
           "spaces", "resolve", "shape_bucket", "aligned_bucket",
           "next_pow2", "capture_resolutions", "resolutions_stale"]


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (bucket edge for integer shape dims)."""
    n = int(n)
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def shape_bucket(info: dict) -> tuple:
    """Canonical shape-bucket key: integer dims round UP to the next
    power of two (nearby shapes share one tuned entry; the tuned params
    are re-validated against the exact shape at resolve time), non-int
    values (dtype strings, bools) pass through verbatim. Deterministic
    ordering by key name so the bucket is a stable cache-key part."""
    parts = []
    for k in sorted(info):
        v = info[k]
        if isinstance(v, bool) or not isinstance(v, int):
            parts.append((k, v))
        else:
            parts.append((k, next_pow2(v)))
    return tuple(parts)


def aligned_bucket(floors: dict):
    """Bucket factory for kernels whose dispatch path resolves with
    PADDED dims while offline ``tune()`` uses raw shapes: clamp each
    integer dim to its tile floor before the pow2 bucket, so both key
    ONE bucket (``next_pow2(ceil_to_align(x)) == next_pow2(max(x,
    align))`` for any power-of-two alignment). ``floors`` maps dim name
    to an int floor or a ``callable(info) -> int`` (dtype-dependent
    sublane floors)."""

    def bucket(info):
        parts = []
        for k in sorted(info):
            v = info[k]
            if isinstance(v, bool) or not isinstance(v, int):
                parts.append((k, v))
                continue
            floor = floors.get(k, 1)
            if callable(floor):
                floor = floor(info)
            parts.append((k, next_pow2(max(v, int(floor)))))
        return tuple(parts)

    return bucket


class ScheduleSpace:
    """One kernel's declarative schedule space.

    ``params`` maps each schedule parameter to its candidate values.
    ``default(info) -> dict`` computes the historical hardcoded
    geometry for a concrete shape (the byte-identical untuned point).
    ``supported(info, cand) -> bool`` prunes invalid candidates
    (VMEM overflow, unsupported tile) before any compile happens.
    ``bench(info) -> builder`` returns a measurement builder for the
    tuner: ``builder(cand) -> run`` where ``run()`` executes one
    jitted call and blocks on the result (the value-fetch barrier).
    ``version`` participates in the cache key semantics: bumping it
    invalidates every persisted entry for the kernel (stale entries
    degrade to defaults, counted as ``autotune::cache_reject``).
    """

    __slots__ = ("name", "version", "params", "_default", "_supported",
                 "_bench", "_bucket")

    def __init__(self, name, *, version, params, default, supported=None,
                 bench=None, bucket=None):
        self.name = name
        self.version = int(version)
        self.params = {k: tuple(v) for k, v in dict(params).items()}
        self._default = default
        self._supported = supported
        self._bench = bench
        self._bucket = bucket

    # -- points --------------------------------------------------------------

    def default_params(self, info: dict) -> dict:
        return dict(self._default(dict(info)))

    def is_supported(self, info: dict, cand: dict) -> bool:
        if self._supported is None:
            return True
        try:
            return bool(self._supported(dict(info), dict(cand)))
        except Exception:
            return False

    def candidates(self, info: dict) -> list:
        """Cartesian product of the parameter axes, default point first
        (deduped) — the tuner must always measure the baseline it is
        claiming a speedup over."""
        default = self.default_params(info)
        names = sorted(self.params)
        out, seen = [], set()
        for point in [default] + [
            dict(zip(names, vals))
            for vals in itertools.product(*(self.params[n] for n in names))
        ]:
            merged = {**default, **point}
            key = tuple(sorted(merged.items()))
            if key not in seen:
                seen.add(key)
                out.append(merged)
        return out

    def bucket(self, info: dict) -> tuple:
        if self._bucket is not None:
            return tuple(self._bucket(dict(info)))
        return shape_bucket(info)

    def bench(self, info: dict):
        if self._bench is None:
            from ..errors import UnimplementedError

            raise UnimplementedError(
                f"schedule space {self.name!r} registered no bench builder"
            )
        return self._bench(dict(info))

    def __repr__(self):
        return (f"ScheduleSpace({self.name!r}, v{self.version}, "
                f"params={sorted(self.params)})")


_SPACES: dict[str, ScheduleSpace] = {}
_LOCK = threading.Lock()


def register_schedule(space: ScheduleSpace) -> ScheduleSpace:
    """Register a kernel's schedule space (idempotent by name: kernels
    register at import; re-import keeps the latest definition)."""
    with _LOCK:
        _SPACES[space.name] = space
    return space


def schedule_space(name: str) -> ScheduleSpace:
    space = _SPACES.get(name)
    if space is None:
        # the kernels register their spaces at import; a tune/resolve of
        # a not-yet-imported kernel should find it, not NotFound
        try:
            import importlib

            importlib.import_module("paddle_tpu.ops.pallas")
        except Exception:
            pass
        space = _SPACES.get(name)
    if space is None:
        from ..errors import NotFoundError

        raise NotFoundError(
            f"unknown kernel schedule space {name!r}; "
            f"registered: {sorted(_SPACES)}")
    return space


def spaces() -> dict:
    """Snapshot of name -> ScheduleSpace."""
    with _LOCK:
        return dict(_SPACES)


def _resolution(space: ScheduleSpace, info: dict):
    """The QUIET resolution core: ``(params, outcome)`` with no
    counters and no search enqueue — shared by :func:`resolve` (which
    adds both) and :func:`resolutions_stale` (which must observe the
    current state without perturbing the tuner's accounting)."""
    default = space.default_params(info)
    if flag("kernel_autotune") == "off":
        return default, "off"
    from .cache import tuning_cache

    entry = tuning_cache().lookup(space, info)
    if entry is not None:
        params = {**default, **entry["params"]}
        if space.is_supported(info, params):
            return params, "hit"
        # bucket coarser than shape: tuned point does not admit this
        # exact shape — defaults, never a crash (and never a search)
        return default, "reject"
    return default, "miss"


# trace-time resolution capture: CompiledStore records which schedules
# a program baked in while it traced, so a tuned swap-in invalidates
# ONLY the signatures that actually resolved the changed kernel —
# never the whole fleet of compiled programs
_capture = threading.local()


class capture_resolutions:
    """Context manager recording every ``resolve()`` outcome inside its
    scope as ``{(kernel, info-items): params-items}`` (``.log`` after
    exit). Re-entrant: an inner capture shadows (and restores) the
    outer one."""

    def __enter__(self):
        self._prev = getattr(_capture, "log", None)
        _capture.log = {}
        return self

    def __exit__(self, *exc):
        self.log = _capture.log
        _capture.log = self._prev
        return False


def _note(kernel, info, params):
    log = getattr(_capture, "log", None)
    if log is not None:
        log[(kernel, tuple(sorted(info.items())))] = tuple(
            sorted(params.items()))


def resolutions_stale(log) -> bool:
    """Whether any captured resolution would resolve DIFFERENTLY now —
    the precise invalidation predicate behind ``<label>::
    schedule_refresh``. Quiet: perturbs no counters, enqueues nothing."""
    for (kernel, info_items), params_items in log.items():
        space = _SPACES.get(kernel)
        if space is None:
            return True  # space unregistered since: rebuild to be safe
        try:
            params, _ = _resolution(space, dict(info_items))
        except Exception:
            return True
        if tuple(sorted(params.items())) != params_items:
            return True
    return False


def resolve(kernel: str, **info) -> dict:
    """Schedule for one concrete kernel call: tuned params on a cache
    hit, the byte-identical defaults otherwise. Dict-lookup cheap —
    safe on the eager dispatch path and at trace time (all values are
    static Python ints)."""
    space = _SPACES[kernel] if kernel in _SPACES else schedule_space(kernel)
    params, outcome = _resolution(space, info)
    if outcome == "hit":
        bump_counter("autotune::cache_hit")
    elif outcome == "reject":
        bump_counter("autotune::cache_reject")
    elif outcome == "miss":
        bump_counter("autotune::cache_miss")
        if flag("kernel_autotune") == "search":
            from .tuner import enqueue_search

            enqueue_search(kernel, info)
    _note(kernel, info, params)
    return params
