"""Persistent kernel-tuning cache: versioned JSON next to the XLA
persistent compile cache.

One file (``kernel_tuning_cache.json`` inside
``FLAGS_persistent_compile_cache_dir``; in-memory only when that flag
is empty) holds every tuned winner, keyed by
``(kernel, device_kind, shape-bucket, dtype, schedule-space version)``
— entries for other device kinds coexist in the same file (a cache
tuned on v5e travels to a v4 host without poisoning it: the v4 lookups
simply miss and run on defaults).

Robustness contract (the PR-14 "inconclusive never blocks"
discipline): a truncated file, a wrong-schema file, or a structurally
malformed entry degrades to defaults with ONE warning + a
``autotune_cache_reject`` flight event + the ``autotune::cache_reject``
counter — never a crash, never a retry loop. Stale entries (older
``space_version`` after a kernel's schedule space changed shape) are
rejected the same way at lookup.

``schedule_token()`` is the runtime coupling: ``runtime/compiled.py``
folds it into every compile identity, so any cache mutation (a file
load, a background-search swap-in, ``set_flags`` turning the tuner
off) bumps the token and the next dispatch of an affected signature is
a CLEAN recompile under the new schedule — tuned swaps can never run
against a stale trace.
"""
from __future__ import annotations

import json
import os
import threading
import warnings

from ..flags import flag, watch_flag
from ..profiler import bump_counter

__all__ = ["CACHE_SCHEMA_VERSION", "CACHE_FILE_NAME", "TuningCache",
           "tuning_cache", "reset_tuning_cache", "cache_path",
           "schedule_token", "tuned_table"]

CACHE_SCHEMA_VERSION = 1
CACHE_FILE_NAME = "kernel_tuning_cache.json"


def cache_path() -> str | None:
    """Where the tuning cache persists: next to the XLA persistent
    compile cache (``FLAGS_persistent_compile_cache_dir``); ``None``
    (in-memory only) when that flag is empty."""
    root = str(flag("persistent_compile_cache_dir") or "").strip()
    if not root:
        return None
    return os.path.join(root, CACHE_FILE_NAME)


def _flight():
    from ..monitor import flight_recorder

    return flight_recorder


def _device_kind() -> str:
    from ..monitor.cost_model import _device_kind as kind

    return kind()


def _entry_valid(value) -> bool:
    return (isinstance(value, dict)
            and isinstance(value.get("params"), dict)
            and all(isinstance(k, str) and isinstance(v, int)
                    and not isinstance(v, bool)
                    for k, v in value["params"].items())
            and isinstance(value.get("space_version"), int))


class TuningCache:
    """The tuned-schedule store: lazy-loaded, thread-safe, atomic
    persistence, generation-counted for the runtime token."""

    def __init__(self, path=None):
        # path=None defers to cache_path() (the flag) at first load;
        # an explicit path pins it (tests, the smoke's fresh-process leg)
        self._explicit_path = path
        self._entries: dict[str, dict] = {}
        self._loaded = False
        self._lock = threading.RLock()
        self._generation = 0
        self._stale_warned: set = set()  # one reject per stale key

    # -- identity ------------------------------------------------------------

    @property
    def path(self) -> str | None:
        return (self._explicit_path if self._explicit_path is not None
                else cache_path())

    @property
    def generation(self) -> int:
        """Bumps on every mutation (load, put, clear) — the
        schedule_token() ingredient that forces clean recompiles."""
        with self._lock:
            return self._generation

    @staticmethod
    def key_of(space, info, device_kind=None) -> str:
        kind = device_kind if device_kind is not None else _device_kind()
        bucket = "/".join(f"{k}={v}" for k, v in space.bucket(info))
        return f"{space.name}|{kind}|{bucket}"

    # -- load / reject -------------------------------------------------------

    def _reject(self, reason, **fields):
        bump_counter("autotune::cache_reject")
        try:
            _flight().record_event("autotune_cache_reject", reason=reason,
                                   path=str(self.path), **fields)
        except Exception:
            pass
        warnings.warn(
            f"kernel tuning cache rejected ({reason}) at {self.path!r}: "
            "continuing on default schedules", RuntimeWarning,
            stacklevel=3)

    def ensure_loaded(self):
        with self._lock:
            if self._loaded:
                return
            self._loaded = True
            self._generation += 1
            path = self.path
            if path is None or not os.path.exists(path):
                return
            try:
                with open(path, "r", encoding="utf-8") as f:
                    raw = json.load(f)
            except Exception as e:  # truncated / not JSON / unreadable
                self._reject(f"unreadable: {type(e).__name__}")
                return
            if (not isinstance(raw, dict)
                    or raw.get("schema") != CACHE_SCHEMA_VERSION
                    or not isinstance(raw.get("entries"), dict)):
                self._reject(
                    "wrong schema "
                    f"{raw.get('schema') if isinstance(raw, dict) else '?'}"
                    f" (want {CACHE_SCHEMA_VERSION})")
                return
            bad = 0
            for key, value in raw["entries"].items():
                if isinstance(key, str) and _entry_valid(value):
                    self._entries[key] = value
                else:
                    bad += 1
            if bad:
                self._reject(f"{bad} malformed entries dropped",
                             kept=len(self._entries))

    # -- lookup / mutate -----------------------------------------------------

    def lookup(self, space, info, device_kind=None) -> dict | None:
        self.ensure_loaded()
        key = self.key_of(space, info, device_kind)
        with self._lock:
            entry = self._entries.get(key)
        if entry is None:
            return None
        if entry.get("space_version") != space.version:
            # schedule space changed shape since this was tuned: stale,
            # degrade to defaults (the tuner will re-search under
            # mode=search; 'cached' just runs defaults). ONE reject per
            # key — lookups repeat per dispatch and must not inflate
            # the counter into a phantom ongoing-corruption signal
            with self._lock:
                first = key not in self._stale_warned
                self._stale_warned.add(key)
            if first:
                self._reject(
                    f"stale space_version "
                    f"{entry.get('space_version')} (want "
                    f"{space.version}) for {key}")
            return None
        return entry

    def put(self, space, info, params, device_kind=None, **meta):
        """Record a tuned winner and persist (atomic tmp+rename when a
        cache path is configured)."""
        self.ensure_loaded()
        entry = {
            "params": {k: int(v) for k, v in params.items()},
            "space_version": space.version,
            "kernel": space.name,
            "device_kind": (device_kind if device_kind is not None
                            else _device_kind()),
            "bucket": dict(space.bucket(info)),
            **meta,
        }
        with self._lock:
            self._entries[self.key_of(space, info, device_kind)] = entry
            self._generation += 1
        self.save()
        return entry

    def entries(self) -> dict:
        self.ensure_loaded()
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._generation += 1

    def save(self):
        path = self.path
        if path is None:
            return
        with self._lock:
            payload = {"schema": CACHE_SCHEMA_VERSION,
                       "entries": dict(self._entries)}
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
            os.replace(tmp, path)  # atomic publish: readers never see a torn file
        except OSError as e:
            # an unwritable cache dir must not take training down
            warnings.warn(f"kernel tuning cache not persisted: {e}",
                          RuntimeWarning)


_cache = [None]
_cache_lock = threading.Lock()
# bumps on every singleton swap: two different cache INSTANCES can reach
# the same per-instance generation count, and the schedule token must
# never read equal across them (a CompiledStore entry compiled under the
# old cache would otherwise serve under the new one's schedules)
_cache_epoch = [0]


def tuning_cache() -> TuningCache:
    """The process-wide tuning cache singleton."""
    with _cache_lock:
        if _cache[0] is None:
            _cache[0] = TuningCache()
        return _cache[0]


def reset_tuning_cache(path=None) -> TuningCache:
    """Swap in a fresh cache (tests; also the flag-watch hook so a
    ``set_flags`` changing the cache dir re-resolves the path)."""
    with _cache_lock:
        _cache_epoch[0] += 1
        _cache[0] = TuningCache(path)
        return _cache[0]


watch_flag("persistent_compile_cache_dir", lambda _v: reset_tuning_cache())


def schedule_token() -> tuple:
    """The schedule ingredient of every CompiledStore compile identity:
    differs whenever schedule resolution could differ (tuner off vs on,
    any cache mutation), so a tuned swap-in forces a clean recompile of
    affected signatures instead of running under a stale trace."""
    mode = flag("kernel_autotune")
    if mode == "off":
        return ("sched-off",)
    cache = tuning_cache()
    cache.ensure_loaded()  # a pending file load must not split the token
    return ("sched", _cache_epoch[0], cache.generation)


def tuned_table(device_kind=None) -> list:
    """The /statz "tuned kernels" table: every cache entry for this
    device kind with its measured tuned-vs-default microseconds."""
    kind = device_kind if device_kind is not None else _device_kind()
    rows = []
    for key, entry in sorted(tuning_cache().entries().items()):
        if entry.get("device_kind") != kind:
            continue
        best = entry.get("best_us")
        default = entry.get("default_us")
        rows.append({
            "kernel": entry.get("kernel"),
            "bucket": entry.get("bucket"),
            "params": entry.get("params"),
            "space_version": entry.get("space_version"),
            "best_us": best,
            "default_us": default,
            "speedup": (round(default / best, 3)
                        if best and default else None),
            "key": key,
        })
    return rows
