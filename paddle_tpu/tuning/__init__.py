"""paddle_tpu.tuning — per-device kernel schedule search with a
persistent tuning cache (ROADMAP item 3, the TVM-spirit autotuner).

Three pieces, one contract:

- :mod:`.schedule` — every gated pallas kernel registers a declarative
  :class:`ScheduleSpace` (block rows/cols, tile geometry, unroll;
  today's hardcoded geometry as the default point); call sites ask
  :func:`resolve` for their schedule. Miss -> byte-identical defaults;
  hit -> the tuned winner. Never an inline search on a hot path.
- :mod:`.tuner` — :class:`KernelTuner` measures candidates offline
  (best-of-N timed jitted calls, value-fetch barrier, invalid points
  pruned before any compile) per ``device_kind``; under
  ``FLAGS_kernel_autotune=search`` resolve-misses enqueue background
  tuning.
- :mod:`.cache` — winners persist in a versioned JSON file next to
  ``FLAGS_persistent_compile_cache_dir``, keyed by (kernel,
  device_kind, shape-bucket, dtype, schedule-space version); corrupt /
  wrong-version / foreign-device content degrades to defaults with one
  warning + ``autotune::cache_reject``, never a crash.
  :func:`schedule_token` couples the cache to ``runtime/compiled.py``:
  every compile identity embeds it, so a tuned swap-in is a clean
  recompile, not a stale-trace hazard.
"""
from .cache import (  # noqa: F401
    CACHE_FILE_NAME,
    CACHE_SCHEMA_VERSION,
    TuningCache,
    cache_path,
    reset_tuning_cache,
    schedule_token,
    tuned_table,
    tuning_cache,
)
from .schedule import (  # noqa: F401
    ScheduleSpace,
    next_pow2,
    register_schedule,
    resolve,
    schedule_space,
    shape_bucket,
    spaces,
)
from .tuner import (  # noqa: F401
    KernelTuner,
    TuneResult,
    drain_background,
    enqueue_search,
    pending_searches,
    tune,
)

__all__ = [
    "CACHE_FILE_NAME",
    "CACHE_SCHEMA_VERSION",
    "KernelTuner",
    "ScheduleSpace",
    "TuneResult",
    "TuningCache",
    "cache_path",
    "drain_background",
    "enqueue_search",
    "next_pow2",
    "pending_searches",
    "register_schedule",
    "reset_tuning_cache",
    "resolve",
    "schedule_space",
    "schedule_token",
    "shape_bucket",
    "spaces",
    "tune",
    "tuned_table",
    "tuning_cache",
]
