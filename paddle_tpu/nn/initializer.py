"""Parameter initializers.

Reference parity: python/paddle/fluid/initializer.py (Constant/Normal/
Uniform/Xavier/MSRA/TruncatedNormal) — here they produce jax arrays from the
global PRNG (framework/random.py).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework import random as _random
from ..framework.dtype import convert_dtype


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        k = _random.split_key()
        return jax.random.normal(k, tuple(shape), convert_dtype(dtype)) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        k = _random.split_key()
        out = jax.random.truncated_normal(k, -2.0, 2.0, tuple(shape), convert_dtype(dtype))
        return out * self.std + self.mean


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        k = _random.split_key()
        return jax.random.uniform(k, tuple(shape), convert_dtype(dtype), self.low, self.high)


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels OIHW: receptive = prod(spatial)
    receptive = math.prod(shape[2:])
    return shape[1] * receptive, shape[0] * receptive


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None):
        self.fan_in, self.fan_out = fan_in, fan_out

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = math.sqrt(2.0 / (fi + fo))
        k = _random.split_key()
        return jax.random.normal(k, tuple(shape), convert_dtype(dtype)) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None):
        self.fan_in, self.fan_out = fan_in, fan_out

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = math.sqrt(6.0 / (fi + fo))
        k = _random.split_key()
        return jax.random.uniform(k, tuple(shape), convert_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        std = gain / math.sqrt(fi)
        k = _random.split_key()
        return jax.random.normal(k, tuple(shape), convert_dtype(dtype)) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        limit = gain * math.sqrt(3.0 / fi)
        k = _random.split_key()
        return jax.random.uniform(k, tuple(shape), convert_dtype(dtype), -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        arr = jnp.asarray(self.value, convert_dtype(dtype))
        assert tuple(arr.shape) == tuple(shape), "Assign initializer shape mismatch"
        return arr


def _resolve(init, is_bias=False):
    if init is None:
        return Constant(0.0) if is_bias else XavierUniform()
    if isinstance(init, Initializer):
        return init
    if isinstance(init, (int, float)):
        return Constant(float(init))
    raise TypeError(f"bad initializer {init!r}")
