"""Layer base class.

Reference parity: python/paddle/fluid/dygraph/layers.py (Layer) — parameter/
sublayer/buffer registration, train/eval mode, state_dict, hooks. TPU note:
parameters are plain Tensors over jax arrays; functionalization for jitted
train steps extracts them as a pytree (framework/jit.py).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ..framework.dtype import get_default_dtype
from ..framework.tensor import Parameter, Tensor
from . import initializer as I

_layer_name_count = {}


def _unique_layer_name(prefix):
    idx = _layer_name_count.get(prefix, 0)
    _layer_name_count[prefix] = idx + 1
    return f"{prefix}_{idx}"


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        self.training = True
        self._dtype = dtype
        self._full_name = _unique_layer_name(
            name_scope or self.__class__.__name__.lower()
        )
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()

    # -- registration -------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                del params[name]
            if layers is not None and name in layers:
                del layers[name]
            if buffers is not None and name in buffers:
                if isinstance(value, Tensor):
                    buffers[name] = value
                    return
                del buffers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}"
        )

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None:
            tensor.persistable = persistable
        self._buffers[name] = tensor
        return tensor

    def create_parameter(
        self,
        shape,
        dtype=None,
        is_bias=False,
        default_initializer=None,
        attr=None,
    ):
        """LayerHelper.create_parameter equivalent (fluid/layer_helper.py)."""
        init = default_initializer
        name = None
        trainable = True
        if attr is not None and attr is not False:
            if isinstance(attr, I.Initializer):
                # paddle accepts a bare Initializer as weight_attr
                init = attr
            else:
                # ParamAttr-like: accept dict or ParamAttr
                init = getattr(attr, "initializer", None) or init
                name = getattr(attr, "name", None)
                trainable = getattr(attr, "trainable", True)
        init = I._resolve(init, is_bias=is_bias)
        arr = init(shape, dtype or self._dtype or get_default_dtype())
        return Parameter.from_array(arr, name=name, trainable=trainable)

    # -- traversal ----------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True, _seen=None):
        # `_seen` is threaded through the whole module tree so a Parameter
        # shared between layers (e.g. a tied embedding/decoder weight) yields
        # exactly one canonical leaf — aliased leaves would silently shadow
        # each other in functionalized train steps (framework/jit.py).
        seen = set() if _seen is None else _seen
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}{name}", p)
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                yield from layer.named_parameters(
                    prefix=f"{prefix}{lname}.", _seen=seen
                )

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}{name}", b)
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                yield from layer.named_buffers(prefix=f"{prefix}{lname}.")

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for layer in self._sub_layers.values():
            if layer is not None:
                out.extend(layer.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix.rstrip("."), self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            full = f"{prefix}{name}"
            yield full, layer
            yield from layer.named_sublayers(prefix=f"{full}.")

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._full_name

    # -- modes --------------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, include_sublayers=True):
        out = OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            out[name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            if b is not None and b.persistable:
                out[name] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing = []
        for name, target in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            value = state_dict[name]
            arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
            target.set_value(arr.astype(target.numpy().dtype))
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # -- call ---------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, args)
            if result is not None:
                args = result if isinstance(result, tuple) else (result,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, args, out)
            if result is not None:
                out = result
        return out

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            sub = repr(layer).split("\n")
            sub = [sub[0]] + ["  " + s for s in sub[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def extra_repr(self):
        return ""


class _HookHandle:
    _next_id = [0]

    def __init__(self, store):
        self.id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1
        self._store = store

    def remove(self):
        self._store.pop(self.id, None)
