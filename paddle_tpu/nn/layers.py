"""Core nn layers.

Reference parity: python/paddle/nn/layer/common.py, conv.py, norm.py,
pooling.py + fluid/dygraph/nn.py. Layers hold Parameters and dispatch to the
functional ops; everything composes under jit via functionalization.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from ..framework.tensor import Parameter, Tensor
from . import functional as F
from . import initializer as I
from .layer_base import Layer


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=I.XavierUniform()
        )
        if bias_attr is not False:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0) if weight_attr is None else None,
        )

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return ops.flatten(x, self.start_axis, self.stop_axis)


# -- conv --------------------------------------------------------------------


class Conv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size, kernel_size)
        self._attrs = dict(stride=stride, padding=padding, dilation=dilation, groups=groups)
        self.data_format = data_format
        fan_in = in_channels // groups * ks[0] * ks[1]
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]], attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in),
        )
        if bias_attr is not False:
            bound = 1.0 / np.sqrt(fan_in)
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound) if bias_attr is None else None,
            )
        else:
            self.bias = None

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, data_format=self.data_format, **self._attrs)


class Conv1D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, weight_attr=None, bias_attr=None):
        super().__init__()
        self._attrs = dict(stride=stride, padding=padding, dilation=dilation, groups=groups)
        fan_in = in_channels // groups * kernel_size
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kernel_size], attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in),
        )
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, **self._attrs)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None, bias_attr=None):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size, kernel_size)
        self._attrs = dict(stride=stride, padding=padding, output_padding=output_padding,
                           dilation=dilation, groups=groups)
        fan_in = in_channels * ks[0] * ks[1] // groups
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, ks[0], ks[1]], attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in),
        )
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.conv2d_transpose(x, self.weight, self.bias, **self._attrs)


# -- pooling -----------------------------------------------------------------


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW"):
        super().__init__()
        self._attrs = dict(kernel_size=kernel_size, stride=stride, padding=padding,
                           ceil_mode=ceil_mode, data_format=data_format)

    def forward(self, x):
        return F.max_pool2d(x, **self._attrs)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
                 data_format="NCHW"):
        super().__init__()
        self._attrs = dict(kernel_size=kernel_size, stride=stride, padding=padding,
                           ceil_mode=ceil_mode, exclusive=exclusive, data_format=data_format)

    def forward(self, x):
        return F.avg_pool2d(x, **self._attrs)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, data_format=self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, data_format=self.data_format)


def fused_conv_bn_relu(conv, bn, x):
    """``relu(bn(conv(x)))`` through the fused pallas conv+bn+relu
    kernel (``FLAGS_use_fused_conv_bn``) when the triple is admissible:
    a bias-free, ungrouped, undilated Conv2D feeding a matching
    BatchNorm2D — the vision models' hot sequence. The jnp fallback
    (and the unfused path here) executes the identical op kernels in
    the same order, so this is a scheduling choice, never a numeric
    one — the ``_residual_norm`` discipline applied to conv nets.

    Running statistics update exactly as ``F.batch_norm`` does in
    training (detached blend into the layer buffers).
    """
    from ..flags import flag
    from ..framework.tensor import Tensor

    attrs = conv._attrs
    if (flag("use_fused_conv_bn") and isinstance(x, Tensor)
            and conv.bias is None and attrs.get("groups", 1) == 1
            and attrs.get("dilation", 1) in (1, (1, 1), [1, 1])
            and isinstance(bn, _BatchNormBase)
            and bn.data_format == ("NCHW" if conv.data_format == "NCHW"
                                   else "NHWC")):
        from ..framework.autograd import no_grad
        from ..ops.pallas import conv_bn_relu as _fused

        # the unfused path autocasts the conv (white-listed op) but not
        # the bn params; mirror that exactly — x/weight take the AMP
        # dtype, gamma/beta/running stats stay f32
        weight = conv.weight
        from ..amp import _enabled as _amp_state

        scope = _amp_state()
        if scope is not None and "conv2d" in scope[1]:
            import jax.numpy as _jnp

            amp_dt = str(_jnp.dtype(scope[0]))
            if str(x.dtype) == "float32":
                x = x.astype(amp_dt)
            if str(weight.dtype) == "float32":
                weight = weight.astype(amp_dt)

        y, new_mean, new_var = _fused(
            x, weight, bn.weight, bn.bias, bn._mean, bn._variance,
            stride=attrs.get("stride", 1), padding=attrs.get("padding", 0),
            epsilon=bn.epsilon, momentum=bn.momentum,
            training=bn.training, data_format=conv.data_format)
        if bn.training:
            with no_grad():
                bn._mean.set_value(new_mean.detach())
                bn._variance.set_value(new_var.detach())
        return y
    return F.relu(bn(conv(x)))


# -- normalization -----------------------------------------------------------


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = "NCHW" if data_format in ("NCHW", "NCL", "NCDHW") else "NHWC"
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format,
        )


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


BatchNorm = BatchNorm2D  # fluid.dygraph.BatchNorm compat


class SyncBatchNorm(_BatchNormBase):
    """Under pjit/shard_map data parallelism the batch statistics are computed
    over the global (sharded) batch automatically when the reduction axes are
    replicated — matching nccl SyncBatchNorm semantics without extra comms
    code. Standalone eager use equals BatchNorm."""


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(self.normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None, bias_attr=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias, self.epsilon)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, weight_attr=None, bias_attr=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, self.weight, self.bias, self.epsilon)


# -- activations as layers ---------------------------------------------------


def _act_layer(name, fn_name, **defaults):
    def forward(self, x):
        fn = getattr(F, fn_name)
        return fn(x, **{k: getattr(self, k) for k in defaults})

    def __init__(self, **kwargs):
        Layer.__init__(self)
        for k, v in defaults.items():
            setattr(self, k, kwargs.get(k, v))

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _act_layer("ReLU", "relu")
ReLU6 = _act_layer("ReLU6", "relu6")
LeakyReLU = _act_layer("LeakyReLU", "leaky_relu", negative_slope=0.01)
ELU = _act_layer("ELU", "elu", alpha=1.0)
CELU = _act_layer("CELU", "celu", alpha=1.0)
SELU = _act_layer("SELU", "selu")
GELU = _act_layer("GELU", "gelu", approximate=False)
Sigmoid = _act_layer("Sigmoid", "sigmoid")
LogSigmoid = _act_layer("LogSigmoid", "log_sigmoid")
Tanh = _act_layer("Tanh", "tanh")
Hardsigmoid = _act_layer("Hardsigmoid", "hardsigmoid")
Hardswish = _act_layer("Hardswish", "hardswish")
Hardtanh = _act_layer("Hardtanh", "hardtanh", min=-1.0, max=1.0)
Hardshrink = _act_layer("Hardshrink", "hardshrink", threshold=0.5)
Softshrink = _act_layer("Softshrink", "softshrink", threshold=0.5)
Softplus = _act_layer("Softplus", "softplus", beta=1.0, threshold=20.0)
Softsign = _act_layer("Softsign", "softsign")
Swish = _act_layer("Swish", "swish")
Silu = _act_layer("Silu", "silu")
Mish = _act_layer("Mish", "mish")
Tanhshrink = _act_layer("Tanhshrink", "tanh_shrink")
Softmax = _act_layer("Softmax", "softmax", axis=-1)
LogSoftmax = _act_layer("LogSoftmax", "log_softmax", axis=-1)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr, default_initializer=I.Constant(init))

    def forward(self, x):
        w = self.weight
        if w.size > 1:
            shape = [1] * x.ndim
            shape[1] = w.size
            w = ops.reshape(w, shape)
        return F.prelu(x, w)


# -- containers (fluid/dygraph/container.py) --------------------------------


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and not isinstance(layers[0], Layer):
            layers = layers[0]
        for i, item in enumerate(layers):
            if isinstance(item, tuple):
                name, layer = item
            else:
                name, layer = str(i), item
            self.add_sublayer(name, layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, layer in enumerate(sublayers or []):
            self.add_sublayer(str(i), layer)

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def extend(self, layers):
        for layer in layers:
            self.append(layer)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx % len(self._sub_layers))]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, *args, **kwargs):
        raise NotImplementedError("LayerList is a container; call sublayers directly")


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def append(self, p):
        self.add_parameter(str(len(self._parameters)), p)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx % len(self._parameters))]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


# -- losses (paddle/nn/layer/loss.py) ---------------------------------------


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax

    def forward(self, input, label):
        return F.cross_entropy(
            input, label, weight=self.weight, soft_label=self.soft_label,
            axis=self.axis, ignore_index=self.ignore_index,
            reduction=self.reduction, use_softmax=self.use_softmax,
        )


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, reduction=self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, reduction=self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, reduction=self.reduction, delta=self.delta)


class BCELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, reduction=self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, reduction="mean", pos_weight=None):
        super().__init__()
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logits, label):
        return F.binary_cross_entropy_with_logits(
            logits, label, reduction=self.reduction, pos_weight=self.pos_weight)


class NLLLoss(Layer):
    def __init__(self, reduction="mean", ignore_index=-100):
        super().__init__()
        self.reduction = reduction
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.nll_loss(input, label, reduction=self.reduction, ignore_index=self.ignore_index)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, reduction=self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean"):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, margin=self.margin, reduction=self.reduction)


# -- misc --------------------------------------------------------------------


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False):
        super().__init__()
        self._attrs = dict(size=size, scale_factor=scale_factor, mode=mode, align_corners=align_corners)

    def forward(self, x):
        return F.interpolate(x, **self._attrs)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0):
        super().__init__()
        if isinstance(padding, int):
            padding = [padding] * 4
        # paddle Pad2D: [left, right, top, bottom] over NCHW spatial dims
        l, r, t, b = padding
        self.paddings = [0, 0, 0, 0, t, b, l, r]
        self.mode = mode
        self.value = value

    def forward(self, x):
        return ops.pad(x, self.paddings, mode=self.mode, value=self.value)


class CosineSimilarity(Layer):
    """paddle.nn.CosineSimilarity (nn/layer/distance.py)."""

    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self._axis, self._eps = axis, eps

    def forward(self, x1, x2):
        import jax.numpy as jnp

        a, b = x1._array, x2._array
        num = jnp.sum(a * b, axis=self._axis)
        den = jnp.maximum(
            jnp.linalg.norm(a, axis=self._axis)
            * jnp.linalg.norm(b, axis=self._axis),
            self._eps,
        )
        return Tensor._from_array(num / den)


class PairwiseDistance(Layer):
    """paddle.nn.PairwiseDistance (nn/layer/distance.py)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False):
        super().__init__()
        self._p, self._eps, self._keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        import jax.numpy as jnp

        d = x._array - y._array + self._eps
        out = jnp.linalg.norm(d, ord=self._p, axis=-1,
                              keepdims=self._keepdim)
        return Tensor._from_array(out)


class Bilinear(Layer):
    """paddle.nn.Bilinear: out_k = x1 @ W_k @ x2 + b_k
    (nn/layer/common.py Bilinear; operators/bilinear_tensor_product_op.cc)."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=I.XavierUniform(),
        )
        self.bias = (None if bias_attr is False else self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True))

    def forward(self, x1, x2):
        import jax.numpy as jnp

        out = jnp.einsum("bi,oij,bj->bo", x1._array, self.weight._array,
                         x2._array)
        if self.bias is not None:
            out = out + self.bias._array
        return Tensor._from_array(out)


class SpectralNorm(Layer):
    """paddle.nn.SpectralNorm (nn/layer/norm.py; spectral_norm_op.cc):
    normalizes a weight tensor by its largest singular value, keeping the
    power-iteration vectors as buffers."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12):
        super().__init__()
        import numpy as _np

        self._dim, self._iters, self._eps = dim, power_iters, eps
        h = weight_shape[dim]
        w = int(_np.prod(weight_shape)) // h
        rng = _np.random.RandomState(0)
        self.register_buffer(
            "weight_u", Tensor((rng.randn(h) / _np.sqrt(h)).astype("float32"))
        )
        self.register_buffer(
            "weight_v", Tensor((rng.randn(w) / _np.sqrt(w)).astype("float32"))
        )

    def forward(self, weight):
        from ..ops.registry import kernel

        w = weight._array if isinstance(weight, Tensor) else weight
        out = kernel("spectral_norm")(
            w, self.weight_u._array, self.weight_v._array,
            dim=self._dim, power_iters=self._iters, eps=self._eps,
        )
        return Tensor._from_array(out)


class Unfold(Layer):
    """paddle.nn.Unfold (im2col, nn/layer/common.py): [N,C,H,W] ->
    [N, C*kh*kw, L]."""

    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        pair = lambda v: tuple(v) if isinstance(v, (list, tuple)) else (v, v)
        self._ks = pair(kernel_sizes)
        self._st = pair(strides)
        self._pd = pair(paddings)
        self._dl = pair(dilations)

    def forward(self, x):
        import jax.numpy as jnp

        from ..ops.registry import kernel

        # one im2col implementation: the im2sequence kernel (compat.py)
        # produces [N, L, C*kh*kw]; Unfold's layout is the transpose
        p = self._pd
        rows = kernel("im2sequence")(
            x._array, kernels=self._ks, strides=self._st,
            paddings=(p[0], p[1], p[0], p[1]), dilations=self._dl,
        )
        return Tensor._from_array(jnp.swapaxes(rows, 1, 2))


class Fold(Layer):
    """paddle.nn.Fold (col2im): inverse of Unfold — overlapping patches
    sum back into the [N, C, H, W] image."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1):
        super().__init__()
        pair = lambda v: tuple(v) if isinstance(v, (list, tuple)) else (v, v)
        self._out = pair(output_sizes)
        self._ks = pair(kernel_sizes)
        self._st = pair(strides)
        self._pd = pair(paddings)
        self._dl = pair(dilations)

    def forward(self, x):
        import jax.numpy as jnp

        arr = x._array  # [N, C*kh*kw, L]
        kh, kw = self._ks
        oh, ow = self._out
        ph, pw = self._pd
        n, ckk, l = arr.shape
        c = ckk // (kh * kw)
        hh = oh + 2 * ph
        ww = ow + 2 * pw
        n_h = (hh - (self._dl[0] * (kh - 1) + 1)) // self._st[0] + 1
        n_w = (ww - (self._dl[1] * (kw - 1) + 1)) // self._st[1] + 1
        cols = arr.reshape(n, c, kh, kw, n_h, n_w)
        out = jnp.zeros((n, c, hh, ww), arr.dtype)
        for i in range(kh):
            for j in range(kw):
                yi = i * self._dl[0]
                xj = j * self._dl[1]
                out = out.at[
                    :, :,
                    yi:yi + n_h * self._st[0]:self._st[0],
                    xj:xj + n_w * self._st[1]:self._st[1],
                ].add(cols[:, :, i, j])
        out = out[:, :, ph:ph + oh, pw:pw + ow]
        return Tensor._from_array(out)
