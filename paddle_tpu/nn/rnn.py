"""Recurrent layers.

Reference parity: operators/lstm_op.cc, gru_op.cc, recurrent_op.cc and
python/paddle/fluid/dygraph/rnn.py. TPU-native: the time loop is a
`lax.scan` (static trip count, compiles to one fused XLA while-loop);
gates are computed as one big matmul per step so the MXU stays busy.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import ops
from ..framework.autograd import apply_op
from ..ops.registry import register_op
from . import initializer as I
from .layer_base import Layer


@register_op("rnn_lstm_layer", num_outputs=3)
def _lstm_layer_kernel(x, h0, c0, w_ih, w_hh, b_ih, b_hh, *, reverse=False):
    """x: [B, T, I]; returns (y [B, T, H], h [B, H], c [B, H])."""
    xs = jnp.swapaxes(x, 0, 1)  # [T, B, I]
    if reverse:
        xs = jnp.flip(xs, 0)
    # precompute input projections for all steps in one matmul
    gates_x = jnp.einsum("tbi,gi->tbg", xs, w_ih) + b_ih

    def step(carry, gx):
        h, c = carry
        gates = gx + h @ w_hh.T + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (h, c), ys = lax.scan(step, (h0, c0), gates_x)
    if reverse:
        ys = jnp.flip(ys, 0)
    return jnp.swapaxes(ys, 0, 1), h, c


@register_op("rnn_gru_layer", num_outputs=2)
def _gru_layer_kernel(x, h0, w_ih, w_hh, b_ih, b_hh, *, reverse=False):
    xs = jnp.swapaxes(x, 0, 1)
    if reverse:
        xs = jnp.flip(xs, 0)
    gates_x = jnp.einsum("tbi,gi->tbg", xs, w_ih) + b_ih

    def step(h, gx):
        gr_x, gz_x, gn_x = jnp.split(gx, 3, axis=-1)
        hh = h @ w_hh.T
        gr_h, gz_h, gn_h = jnp.split(hh + b_hh, 3, axis=-1)
        r = jax.nn.sigmoid(gr_x + gr_h)
        z = jax.nn.sigmoid(gz_x + gz_h)
        n = jnp.tanh(gn_x + r * gn_h)
        h = (1 - z) * n + z * h
        return h, h

    h, ys = lax.scan(step, h0, gates_x)
    if reverse:
        ys = jnp.flip(ys, 0)
    return jnp.swapaxes(ys, 0, 1), h


@register_op("rnn_simple_layer", num_outputs=2)
def _simple_rnn_layer_kernel(x, h0, w_ih, w_hh, b_ih, b_hh, *, activation="tanh", reverse=False):
    xs = jnp.swapaxes(x, 0, 1)
    if reverse:
        xs = jnp.flip(xs, 0)
    gates_x = jnp.einsum("tbi,hi->tbh", xs, w_ih) + b_ih
    act = jnp.tanh if activation == "tanh" else jax.nn.relu

    def step(h, gx):
        h = act(gx + h @ w_hh.T + b_hh)
        return h, h

    h, ys = lax.scan(step, h0, gates_x)
    if reverse:
        ys = jnp.flip(ys, 0)
    return jnp.swapaxes(ys, 0, 1), h


class RNNBase(Layer):
    MODE = "LSTM"
    GATES = 4

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 dropout=0.0, time_major=False, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.num_directions = 2 if direction in ("bidirect", "bidirectional") else 1
        self.dropout = dropout
        self.time_major = time_major
        g = self.GATES
        std = 1.0 / (hidden_size**0.5)
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer == 0 else hidden_size * self.num_directions
                suffix = f"_l{layer}" + ("_reverse" if d else "")
                self.add_parameter(
                    f"weight_ih{suffix}",
                    self.create_parameter([g * hidden_size, in_sz],
                                          default_initializer=I.Uniform(-std, std)))
                self.add_parameter(
                    f"weight_hh{suffix}",
                    self.create_parameter([g * hidden_size, hidden_size],
                                          default_initializer=I.Uniform(-std, std)))
                self.add_parameter(
                    f"bias_ih{suffix}",
                    self.create_parameter([g * hidden_size], is_bias=True,
                                          default_initializer=I.Uniform(-std, std)))
                self.add_parameter(
                    f"bias_hh{suffix}",
                    self.create_parameter([g * hidden_size], is_bias=True,
                                          default_initializer=I.Uniform(-std, std)))

    def _weights(self, layer, d):
        suffix = f"_l{layer}" + ("_reverse" if d else "")
        return (self._parameters[f"weight_ih{suffix}"],
                self._parameters[f"weight_hh{suffix}"],
                self._parameters[f"bias_ih{suffix}"],
                self._parameters[f"bias_hh{suffix}"])

    def forward(self, inputs, initial_states=None):
        if self.time_major:
            inputs = ops.transpose(inputs, [1, 0, 2])
        b = inputs.shape[0]
        nd = self.num_directions

        if self.MODE == "LSTM":
            if initial_states is None:
                h0 = ops.zeros([self.num_layers * nd, b, self.hidden_size], inputs.dtype)
                c0 = ops.zeros_like(h0)
            else:
                h0, c0 = initial_states
        else:
            h0 = initial_states if initial_states is not None else ops.zeros(
                [self.num_layers * nd, b, self.hidden_size], inputs.dtype)

        out = inputs
        last_h, last_c = [], []
        for layer in range(self.num_layers):
            outs_d = []
            for d in range(nd):
                idx = layer * nd + d
                w_ih, w_hh, b_ih, b_hh = self._weights(layer, d)
                if self.MODE == "LSTM":
                    y, h, c = apply_op(
                        "rnn_lstm_layer", _lstm_layer_kernel,
                        [out, h0[idx], c0[idx], w_ih, w_hh, b_ih, b_hh],
                        {"reverse": bool(d)}, )
                    last_c.append(c)
                elif self.MODE == "GRU":
                    y, h = apply_op(
                        "rnn_gru_layer", _gru_layer_kernel,
                        [out, h0[idx], w_ih, w_hh, b_ih, b_hh], {"reverse": bool(d)})
                else:
                    y, h = apply_op(
                        "rnn_simple_layer", _simple_rnn_layer_kernel,
                        [out, h0[idx], w_ih, w_hh, b_ih, b_hh],
                        {"activation": "tanh", "reverse": bool(d)})
                outs_d.append(y)
                last_h.append(h)
            out = outs_d[0] if nd == 1 else ops.concat(outs_d, axis=-1)
            if self.dropout and layer < self.num_layers - 1:
                from . import functional as F

                out = F.dropout(out, p=self.dropout, training=self.training)

        final_h = ops.stack(last_h, axis=0)
        if self.time_major:
            out = ops.transpose(out, [1, 0, 2])
        if self.MODE == "LSTM":
            return out, (final_h, ops.stack(last_c, axis=0))
        return out, final_h


class LSTM(RNNBase):
    MODE = "LSTM"
    GATES = 4


class GRU(RNNBase):
    MODE = "GRU"
    GATES = 3


class SimpleRNN(RNNBase):
    MODE = "RNN"
    GATES = 1


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size):
        super().__init__()
        std = 1.0 / (hidden_size**0.5)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter([4 * hidden_size], is_bias=True)
        self.bias_hh = self.create_parameter([4 * hidden_size], is_bias=True)
        self.hidden_size = hidden_size

    def forward(self, x, states=None):
        if states is None:
            h = ops.zeros([x.shape[0], self.hidden_size], x.dtype)
            c = ops.zeros_like(h)
        else:
            h, c = states
        gates = ops.matmul(x, self.weight_ih, transpose_y=True) + self.bias_ih \
            + ops.matmul(h, self.weight_hh, transpose_y=True) + self.bias_hh
        i, f, g, o = ops.split(gates, 4, axis=-1)
        i, f, o = ops.sigmoid(i), ops.sigmoid(f), ops.sigmoid(o)
        g = ops.tanh(g)
        c = f * c + i * g
        h = o * ops.tanh(c)
        return h, (h, c)


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size):
        super().__init__()
        std = 1.0 / (hidden_size**0.5)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter([3 * hidden_size], is_bias=True)
        self.bias_hh = self.create_parameter([3 * hidden_size], is_bias=True)
        self.hidden_size = hidden_size

    def forward(self, x, states=None):
        h = states if states is not None else ops.zeros([x.shape[0], self.hidden_size], x.dtype)
        gx = ops.matmul(x, self.weight_ih, transpose_y=True) + self.bias_ih
        gh = ops.matmul(h, self.weight_hh, transpose_y=True) + self.bias_hh
        rx, zx, nx = ops.split(gx, 3, axis=-1)
        rh, zh, nh = ops.split(gh, 3, axis=-1)
        r = ops.sigmoid(rx + rh)
        z = ops.sigmoid(zx + zh)
        n = ops.tanh(nx + r * nh)
        h = (1 - z) * n + z * h
        return h, h
