"""Transformer stack.

Reference parity: python/paddle/nn/layer/transformer.py:67 (MultiHeadAttention),
:385/:525 (encoder), :595 (decoder). TPU-native: attention math is pure jnp —
XLA fuses the softmax chain; a pallas flash-attention kernel can be swapped
in via paddle_tpu.ops.pallas_kernels for long sequences.
"""
from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np

import jax.numpy as jnp

from .. import ops
from ..framework.tensor import Tensor
from . import functional as F
from .layer_base import Layer
from .layers import Dropout, LayerList, LayerNorm, Linear

# Sequence length from which use_flash_attention dispatches to the pallas
# kernel; below it XLA's fused attention is faster on TPU (measured, see
# COVERAGE.md "Flash attention"). Tests may lower it to force the kernel.
FLASH_ATTENTION_MIN_SEQ = 512


def _residual_norm(norm, residual, y):
    """Post-norm ``LayerNorm(residual + y)`` through the fused pallas
    residual-add+layernorm kernel (``FLAGS_use_fused_layernorm``) when
    the norm is a plain last-dim LayerNorm with affine params — the jnp
    fallback and the unfused path execute the identical primitive
    sequence, so this is a scheduling choice, never a numeric one."""
    from ..flags import flag

    if (flag("use_fused_layernorm") and isinstance(norm, LayerNorm)
            and norm.weight is not None and norm.bias is not None
            and len(norm.normalized_shape) == 1):
        from ..ops.pallas import layernorm_residual

        return layernorm_residual(y, residual, norm.weight, norm.bias,
                                  norm.epsilon)
    return norm(residual + y)


def _convert_attention_mask(attn_mask, dtype):
    """Normalize a mask to an ADDITIVE mask broadcastable against the
    [B, H, Lq, Lk] score tensor.

    Accepts bool masks (True = keep, paddle semantics) and additive float
    masks, at rank 2 ``[Lq, Lk]``, rank 3 ``[B, Lq, Lk]``, or rank 4
    ``[B, 1|H, Lq, Lk]`` — all composed the same way on the encoder,
    decoder, and incremental-cache paths. Rank 3 in particular would
    silently broadcast against the wrong axes if added raw to the scores
    (``[B, Lq, Lk]`` lines up as ``[1, B, Lq, Lk]``), so ranks are
    normalized here, once, instead of per call site.
    """
    if attn_mask is None:
        return None
    if attn_mask.dtype == np.bool_ or str(attn_mask.dtype) == "bool":
        # True = keep, False = mask out (paddle semantics)
        zero = ops.zeros_like(ops.cast(attn_mask, dtype))
        neg = ops.full_like(zero, -1e9)
        attn_mask = ops.where(attn_mask, zero, neg)
    else:
        attn_mask = ops.cast(attn_mask, dtype)
    if attn_mask.ndim == 2:        # [Lq, Lk] -> [1, 1, Lq, Lk]
        attn_mask = ops.unsqueeze(attn_mask, [0, 1])
    elif attn_mask.ndim == 3:      # [B, Lq, Lk] -> [B, 1, Lq, Lk]
        attn_mask = ops.unsqueeze(attn_mask, [1])
    return attn_mask


def causal_mask(length, window=None, dtype="float32"):
    """Additive ``[L, L]`` causal mask; ``window=W`` additionally masks
    keys more than ``W-1`` positions behind the query (sliding-window
    attention) — the full-sequence equivalent of decoding with a ring KV
    cache of capacity ``W``, which keeps exactly the last ``W`` tokens.
    ``window=None`` is the standard full causal mask."""
    i = np.arange(length)[:, None]
    j = np.arange(length)[None, :]
    keep = j <= i
    if window is not None:
        keep = keep & (j > i - int(window))
    from ..framework.tensor import to_tensor

    return to_tensor(np.where(keep, 0.0, -1e9).astype(dtype))


class StaticCache(NamedTuple):
    """Fixed-shape ring KV cache for ONE attention layer.

    ``k``/``v`` are ``[B, H, C, D]`` arrays (C = cache capacity) and
    ``pos`` is ``[B]`` int32 — how many tokens each row has written so
    far. Writes are FUNCTIONAL index updates (``.at[].set`` /
    ``dynamic_update_slice``), so the pytree's shapes never change
    across decode steps: one XLA program decodes forever, and once
    ``pos`` passes ``C`` the write index wraps (``pos % C``) and the
    oldest entry is overwritten — O(1) memory, compile-once decoding
    (PAPERS.md: portable O(1) autoregressive caching). Validity/window
    masking is the CALLER's job (the mask composes causal + cache-fill,
    see generation/cache.py); the layer only writes and attends.
    """

    k: Any
    v: Any
    pos: Any


class QuantizedStaticCache(NamedTuple):
    """:class:`StaticCache` at int8 storage with per-head dynamic scales.

    ``k``/``v`` are int8 ``[B, H, C, D]``; ``k_scale``/``v_scale`` are
    f32 ``[B, H, C]`` — one abs-max scale per written head-vector,
    computed DYNAMICALLY at ring-write time (no calibration pass: each
    K/V row quantizes against its own magnitude, so attention sinks and
    outlier heads never clip the rest of the cache). The attention read
    dequantizes the full static window (``q · scale/127``) before the
    score matmul — decode HBM traffic drops to ~(D+4)/(4·D) of the f32
    cache (3.8× at head_dim 64), which is what lets the same HBM hold
    ~2× the decode slots (``FLAGS_generation_kv_cache_dtype=int8``).

    Ring semantics, functional updates, and the caller-owned mask
    contract are exactly :class:`StaticCache`'s; parity vs the full
    f32 forward holds at the int8 envelope documented in README
    "Quantization" (goldens in tests/test_quantization.py).
    """

    k: Any
    v: Any
    k_scale: Any
    v_scale: Any
    pos: Any


class PagedStaticCache(NamedTuple):
    """:class:`StaticCache` semantics over a PAGE-POOL layout.

    ``k``/``v`` are ``[P, H, ps, D]`` — the whole shared pool of ``P``
    physical pages (``ps`` tokens each) for ONE layer, not one slot's
    ring. ``table`` is ``[B, NP]`` int32: row ``b`` maps that slot's
    ``NP`` logical ring pages to physical pool pages, and ``pos`` is the
    shared ``[B]`` position vector. The LOGICAL cache is the exact same
    ring the contiguous cache implements — entry index ``pos % (NP*ps)``
    splits into logical page ``idx // ps`` and offset ``idx % ps``, so
    every mask (decode/prefill/verify) and the wraparound contract carry
    over unchanged, and greedy output is token-identical to the ring
    layout by construction.

    Writes scatter through the table (a functional ``.at[phys, :, off,
    :].set``); reads gather ``k[table]`` back into the contiguous
    ``[B, H, NP*ps, D]`` window the score matmul expects. Page
    ALLOCATION is host-side bookkeeping between steps
    (:mod:`paddle_tpu.generation.paging`): physical page 0 is reserved
    as the trash page — vacant slots and unallocated logical pages point
    at it, absorbing writes that the ring layout would make into a
    vacant slot's own storage. The pool owner guarantees every page a
    busy slot is about to write is PRIVATE (refcount 1); shared prefix
    pages are remapped copy-on-write before the step.
    """

    k: Any
    v: Any
    table: Any
    pos: Any


class QuantizedPagedCache(NamedTuple):
    """:class:`PagedStaticCache` at int8 storage: int8 ``k``/``v``
    ``[P, H, ps, D]`` plus f32 per-head dynamic scale pools
    ``k_scale``/``v_scale`` ``[P, H, ps]`` — :class:`QuantizedStaticCache`'s
    quantize-on-write / dequantize-on-read contract through the same
    page-table indirection."""

    k: Any
    v: Any
    k_scale: Any
    v_scale: Any
    table: Any
    pos: Any


#: int8 grid half-width for KV-cache quantization
KV_QUANT_BNT = 127.0
#: scale floor: an all-zero head-vector must not dequantize as NaN
KV_QUANT_EPS = 1e-8


def quantize_kv(x):
    """``[..., D]`` float → (int8 values, f32 abs-max scales ``[...]``).

    One dynamic scale per trailing vector (per head per cache entry) —
    the quantize-on-ring-write half of the int8 KV cache.
    """
    x = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=-1), KV_QUANT_EPS)
    q = jnp.round(jnp.clip(x / scale[..., None] * KV_QUANT_BNT,
                           -KV_QUANT_BNT, KV_QUANT_BNT))
    return q.astype(jnp.int8), scale


def dequantize_kv(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv` — the attention-read half."""
    return q.astype(dtype) * (scale[..., None] / KV_QUANT_BNT).astype(dtype)


class MultiHeadAttention(Layer):
    """Scaled dot-product multi-head attention (transformer.py:67)."""

    Cache = tuple  # (k, v)

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None,
                 use_ring_attention=False, use_flash_attention=False,
                 use_ulysses_attention=False):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        # TPU extensions: sequence-parallel attention over the sp mesh axis
        # — ring (parallel/ring_attention.py) or Ulysses all-to-all
        # (parallel/ulysses.py) — and the fused pallas flash kernel
        # (ops/pallas/flash_attention.py). Flash supports attention dropout
        # (in-kernel TPU PRNG); ring/Ulysses require dropout == 0.
        self.use_ring_attention = use_ring_attention
        self.use_ulysses_attention = use_ulysses_attention
        if use_ring_attention and use_ulysses_attention:
            raise ValueError("pick ONE sp attention mode: ring or ulysses")
        self.use_flash_attention = use_flash_attention
        if (use_ring_attention or use_ulysses_attention) and dropout:
            raise ValueError(
                "sequence-parallel attention (ring/ulysses) does not "
                "support attn dropout"
            )
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _shape(self, x):
        # [B, L, E] -> [B, H, L, D]
        b, l = x.shape[0], x.shape[1]
        x = ops.reshape(x, [b, l, self.num_heads, self.head_dim])
        return ops.transpose(x, [0, 2, 1, 3])

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._shape(self.q_proj(query))
        k = self._shape(self.k_proj(key))
        v = self._shape(self.v_proj(value))
        if isinstance(cache, (StaticCache, QuantizedStaticCache,
                              PagedStaticCache, QuantizedPagedCache)):
            # incremental path: write the new K/V into the ring cache by
            # functional index update, then attend over the FULL static
            # window — shapes never change across steps, so a jitted
            # decode step compiles exactly once (the caller's mask hides
            # not-yet-written entries). The quantized cache writes int8
            # + per-head scales and hands back the dequantized window;
            # the paged caches route the same logical ring indices
            # through a per-slot page table into a shared pool.
            k, v, new_cache = self._update_static_cache(cache, k, v)
        elif cache is not None:
            pk, pv = cache
            k = ops.concat([pk, k], axis=2)
            v = ops.concat([pv, v], axis=2)
            new_cache = (k, v)

        scale = float(self.head_dim) ** -0.5
        mask_ring_ok = attn_mask is None or (
            attn_mask.ndim == 4 and attn_mask.shape[-2] == 1
        )  # ring rotation supports only K-dim [B,1,1,L] masks
        if (self.use_ring_attention and not self.need_weights
                and cache is None and mask_ring_ok):
            from ..parallel.ring_attention import ring_attention

            mask = _convert_attention_mask(attn_mask, q.dtype)
            out = ring_attention(q, k, v, mask=mask, scale=scale)
        elif (self.use_ulysses_attention and not self.need_weights
                and cache is None and mask_ring_ok):
            from ..parallel.ulysses import ulysses_attention

            mask = _convert_attention_mask(attn_mask, q.dtype)
            out = ulysses_attention(q, k, v, mask=mask, scale=scale)
        elif (self.use_flash_attention and not self.need_weights
                and cache is None
                and k.shape[2] >= FLASH_ATTENTION_MIN_SEQ):
            # Pallas flash kernel: wins once the [L, L] score tiles stop
            # fitting XLA's fused-attention working set (measured on v5e:
            # >=1.5x at L=512+, but 0.8x at L=128 where XLA's batched
            # fusion is already optimal — see COVERAGE.md "Flash
            # attention"). Below the threshold the XLA path runs, so the
            # flag is always safe to enable.
            from ..ops.pallas import flash_attention

            mask = _convert_attention_mask(attn_mask, q.dtype)
            out = flash_attention(
                q, k, v, bias=mask, scale=scale,
                dropout_rate=self.dropout if self.training else 0.0,
            )
        else:
            if self.use_ring_attention or self.use_ulysses_attention:
                # an sp mode was requested but the call shape ruled it out
                # (need_weights / incremental cache / Lq>1 mask): record
                # the fallback so harness asserts can't false-pass on a
                # stale "sharded" entry
                from ..parallel.ring_attention import LAST_DISPATCH

                LAST_DISPATCH.clear()
                LAST_DISPATCH.update(
                    op=("ring_attention" if self.use_ring_attention
                        else "ulysses_attention"),
                    mode="fallback", axis_size=0,
                )
            scores = ops.matmul(q, k, transpose_y=True) * scale
            mask = _convert_attention_mask(attn_mask, q.dtype)
            if mask is not None:
                scores = scores + mask
            weights = F.softmax(scores, axis=-1)
            if self.dropout:
                weights = F.dropout(weights, p=self.dropout, training=self.training)
            out = ops.matmul(weights, v)  # [B, H, L, D]
        out = ops.transpose(out, [0, 2, 1, 3])
        b, l = out.shape[0], out.shape[1]
        out = ops.reshape(out, [b, l, self.embed_dim])
        out = self.out_proj(out)

        results = [out]
        if self.need_weights:
            results.append(weights)
        if cache is not None:
            results.append(new_cache)
        return out if len(results) == 1 else tuple(results)

    def gen_cache(self, key, value=None, type=None):
        b = key.shape[0]
        k = ops.zeros([b, self.num_heads, 0, self.head_dim], key.dtype)
        v = ops.zeros([b, self.num_heads, 0, self.head_dim], key.dtype)
        return (k, v)

    def gen_static_cache(self, batch, cache_len, dtype="float32"):
        """A zeroed :class:`StaticCache` of capacity ``cache_len``."""
        shape = (int(batch), self.num_heads, int(cache_len), self.head_dim)
        return StaticCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                           jnp.zeros((int(batch),), jnp.int32))

    def _update_static_cache(self, cache, k, v):
        """Write the freshly projected K/V into the ring cache.

        Decode (Lq == 1): every row writes its own ring index
        ``pos % C`` — a batched scatter, so co-batched sequences at
        different positions share one program. Multi-token (Lq > 1,
        prefill and speculative verify): each row writes its span at
        its OWN offset ``(pos + t) % C`` — the same batched scatter
        over a ``[B, T]`` index plane, so per-slot positions may differ
        and the span may wrap the ring (the verify step's window-exact
        in-place write; see generation/cache.py "store vs window").
        """
        if isinstance(cache, QuantizedStaticCache):
            return self._update_quantized_cache(cache, k, v)
        if isinstance(cache, (PagedStaticCache, QuantizedPagedCache)):
            return self._update_paged_cache(cache, k, v)
        kc, vc, pos = cache
        kn = k._array if isinstance(k, Tensor) else jnp.asarray(k)
        vn = v._array if isinstance(v, Tensor) else jnp.asarray(v)
        kn = kn.astype(kc.dtype)
        vn = vn.astype(vc.dtype)
        c = kc.shape[2]
        if kn.shape[2] == 1:
            rows = jnp.arange(kc.shape[0])
            idx = jnp.mod(pos, c)
            kc = kc.at[rows, :, idx, :].set(kn[:, :, 0, :])
            vc = vc.at[rows, :, idx, :].set(vn[:, :, 0, :])
        else:
            t = kn.shape[2]
            rows = jnp.arange(kc.shape[0])[:, None]
            idx = jnp.mod(pos[:, None] + jnp.arange(t)[None, :], c)
            # advanced indices split by the H slice put the [B, T] index
            # dims first, so the payload transposes to [B, T, H, D]
            kc = kc.at[rows, :, idx, :].set(jnp.moveaxis(kn, 2, 1))
            vc = vc.at[rows, :, idx, :].set(jnp.moveaxis(vn, 2, 1))
        return (Tensor._from_array(kc), Tensor._from_array(vc),
                StaticCache(kc, vc, pos))

    def _update_quantized_cache(self, cache, k, v):
        """Int8 twin of :meth:`_update_static_cache`.

        The fresh K/V projections quantize per head-vector (one dynamic
        abs-max scale each, :func:`quantize_kv`) before the ring write —
        int8 values and f32 scales land at the same ring index the f32
        cache would write. The attention read then dequantizes the FULL
        window: masked (never-written / stale) entries dequantize to
        whatever garbage they hold, exactly as in the f32 cache, and the
        caller's mask hides them.
        """
        kc, vc, ks, vs, pos = cache
        kn = k._array if isinstance(k, Tensor) else jnp.asarray(k)
        vn = v._array if isinstance(v, Tensor) else jnp.asarray(v)
        out_dtype = kn.dtype
        kq, ksc = quantize_kv(kn)
        vq, vsc = quantize_kv(vn)
        c = kc.shape[2]
        if kn.shape[2] == 1:
            rows = jnp.arange(kc.shape[0])
            idx = jnp.mod(pos, c)
            kc = kc.at[rows, :, idx, :].set(kq[:, :, 0, :])
            vc = vc.at[rows, :, idx, :].set(vq[:, :, 0, :])
            ks = ks.at[rows, :, idx].set(ksc[:, :, 0])
            vs = vs.at[rows, :, idx].set(vsc[:, :, 0])
        else:
            t = kn.shape[2]
            rows = jnp.arange(kc.shape[0])[:, None]
            idx = jnp.mod(pos[:, None] + jnp.arange(t)[None, :], c)
            kc = kc.at[rows, :, idx, :].set(jnp.moveaxis(kq, 2, 1))
            vc = vc.at[rows, :, idx, :].set(jnp.moveaxis(vq, 2, 1))
            ks = ks.at[rows, :, idx].set(jnp.moveaxis(ksc, 2, 1))
            vs = vs.at[rows, :, idx].set(jnp.moveaxis(vsc, 2, 1))
        kf = dequantize_kv(kc, ks, out_dtype)
        vf = dequantize_kv(vc, vs, out_dtype)
        return (Tensor._from_array(kf), Tensor._from_array(vf),
                QuantizedStaticCache(kc, vc, ks, vs, pos))

    @staticmethod
    def _paged_indices(table, pos, t, store, ps):
        """Physical (page, offset) coordinates for a ``t``-token write
        starting at each row's ``pos`` — the logical ring index
        ``(pos + j) % store`` split into the table lookup."""
        if t == 1:
            idx = jnp.mod(pos, store)
            rows = jnp.arange(table.shape[0])
            return table[rows, idx // ps], jnp.mod(idx, ps)
        idx = jnp.mod(pos[:, None] + jnp.arange(t)[None, :], store)
        rows = jnp.arange(table.shape[0])[:, None]
        return table[rows, idx // ps], jnp.mod(idx, ps)

    def _update_paged_cache(self, cache, k, v):
        """Paged twin of :meth:`_update_static_cache`: the identical
        logical ring write/read, with the page table translating logical
        pages to shared-pool pages. The write scatters into the pool
        (the pool owner pre-guarantees written pages are private — CoW
        happened host-side before this step); the read gathers each
        row's ``NP`` pages back into the contiguous ``[B, H, NP*ps, D]``
        window so the attention math — and hence the numerics — is
        byte-identical to the ring layout's."""
        quant = isinstance(cache, QuantizedPagedCache)
        if quant:
            kc, vc, ks, vs, table, pos = cache
        else:
            kc, vc, table, pos = cache
        kn = k._array if isinstance(k, Tensor) else jnp.asarray(k)
        vn = v._array if isinstance(v, Tensor) else jnp.asarray(v)
        out_dtype = kn.dtype
        ps = kc.shape[2]
        b, np_ = table.shape
        store = np_ * ps
        t = kn.shape[2]
        phys, off = self._paged_indices(table, pos, t, store, ps)
        if quant:
            kq, ksc = quantize_kv(kn)
            vq, vsc = quantize_kv(vn)
            if t == 1:
                kc = kc.at[phys, :, off, :].set(kq[:, :, 0, :])
                vc = vc.at[phys, :, off, :].set(vq[:, :, 0, :])
                ks = ks.at[phys, :, off].set(ksc[:, :, 0])
                vs = vs.at[phys, :, off].set(vsc[:, :, 0])
            else:
                kc = kc.at[phys, :, off, :].set(jnp.moveaxis(kq, 2, 1))
                vc = vc.at[phys, :, off, :].set(jnp.moveaxis(vq, 2, 1))
                ks = ks.at[phys, :, off].set(jnp.moveaxis(ksc, 2, 1))
                vs = vs.at[phys, :, off].set(jnp.moveaxis(vsc, 2, 1))
        else:
            kn = kn.astype(kc.dtype)
            vn = vn.astype(vc.dtype)
            if t == 1:
                kc = kc.at[phys, :, off, :].set(kn[:, :, 0, :])
                vc = vc.at[phys, :, off, :].set(vn[:, :, 0, :])
            else:
                kc = kc.at[phys, :, off, :].set(jnp.moveaxis(kn, 2, 1))
                vc = vc.at[phys, :, off, :].set(jnp.moveaxis(vn, 2, 1))
        # gather the per-row window: [B, NP, H, ps, D] -> [B, H, NP*ps, D]
        h, d = kc.shape[1], kc.shape[3]

        def window(pool):
            g = pool[table]
            return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(
                b, h, store, d)

        if quant:
            def swindow(spool):
                g = spool[table]  # [B, NP, H, ps]
                return jnp.transpose(g, (0, 2, 1, 3)).reshape(b, h, store)

            kw = dequantize_kv(window(kc), swindow(ks), out_dtype)
            vw = dequantize_kv(window(vc), swindow(vs), out_dtype)
            new = QuantizedPagedCache(kc, vc, ks, vs, table, pos)
        else:
            kw = window(kc).astype(out_dtype)
            vw = window(vc).astype(out_dtype)
            new = PagedStaticCache(kc, vc, table, pos)
        return Tensor._from_array(kw), Tensor._from_array(vw), new


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, use_flash_attention=False,
                 sp_attention="none"):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        if sp_attention not in ("none", "ring", "ulysses"):
            raise ValueError(f"sp_attention must be none|ring|ulysses, "
                             f"got {sp_attention!r}")
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr,
                                            use_flash_attention=use_flash_attention,
                                            use_ring_attention=sp_attention == "ring",
                                            use_ulysses_attention=sp_attention == "ulysses")
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, new_cache = self.self_attn(src, src, src, src_mask, cache)
        if self.normalize_before:
            src = residual + self.dropout1(src)
        else:
            src = _residual_norm(self.norm1, residual, self.dropout1(src))

        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        if self.normalize_before:
            src = residual + self.dropout2(src)
        else:
            src = _residual_norm(self.norm2, residual, self.dropout2(src))
        return src if cache is None else (src, new_cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    """Decoder block: self-attention (+ optional cross-attention) + FFN.

    ``with_cross_attention=False`` builds a decoder-ONLY block (GPT
    style): no cross-attention parameters exist at all — not merely
    skipped, so the functional state stays free of zombie weights — and
    ``memory`` may be omitted at call time.
    """

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, with_cross_attention=True):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        if with_cross_attention:
            self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                                 weight_attr=weight_attr, bias_attr=bias_attr)
            self.norm2 = LayerNorm(d_model)
            self.dropout2 = Dropout(dropout)
        else:
            self.cross_attn = None
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory=None, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, new_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache)
        if self.normalize_before:
            tgt = residual + self.dropout1(tgt)
        else:
            tgt = _residual_norm(self.norm1, residual, self.dropout1(tgt))

        if self.cross_attn is not None:
            if memory is None:
                raise ValueError(
                    "this TransformerDecoderLayer was built with cross-"
                    "attention; pass memory (or build it with "
                    "with_cross_attention=False for decoder-only use)")
            residual = tgt
            if self.normalize_before:
                tgt = self.norm2(tgt)
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
            if self.normalize_before:
                tgt = residual + self.dropout2(tgt)
            else:
                tgt = _residual_norm(self.norm2, residual,
                                     self.dropout2(tgt))

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        if self.normalize_before:
            tgt = residual + self.dropout3(tgt)
        else:
            tgt = _residual_norm(self.norm3, residual, self.dropout3(tgt))
        return tgt if cache is None else (tgt, new_cache)


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask, memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    """Full encoder-decoder transformer (transformer.py Transformer class)."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6, num_decoder_layers=6,
                 dim_feedforward=2048, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        mask = np.triu(np.full((length, length), -1e9, np.float32), k=1)
        from ..framework.tensor import to_tensor

        return to_tensor(mask)
