"""Lazy bridge from the pallas kernels to the tuning subsystem.

The kernel modules (``ops/pallas/*``) register their schedule spaces
and resolve schedules through these two functions instead of importing
``paddle_tpu.tuning`` directly: the tuning package pulls in flags /
profiler / monitor plumbing that must stay OUT of the kernel modules'
import graph (ops.pallas is imported during bootstrap), and the lazy
indirection keeps the kernels importable — falling back to their
hardcoded default geometry — even if schedule resolution ever fails.
"""
from __future__ import annotations

__all__ = ["register_schedule", "resolve_schedule"]


def register_schedule(*, name, version, params, default, supported=None,
                      bench=None, bucket=None):
    """Declare one kernel's schedule space (see tuning/schedule.py)."""
    from .tuning.schedule import ScheduleSpace
    from .tuning.schedule import register_schedule as _register

    return _register(ScheduleSpace(
        name, version=version, params=params, default=default,
        supported=supported, bench=bench, bucket=bucket))


def resolve_schedule(kernel, **info) -> dict:
    """Tuned schedule params on a cache hit, the kernel's byte-identical
    defaults otherwise. Degrades to defaults on ANY resolution failure:
    a broken tuning cache must never take a kernel down."""
    from .tuning.schedule import resolve, schedule_space

    try:
        return resolve(kernel, **info)
    except Exception:
        # cache/flag plumbing failure: the kernel still runs, on its
        # hardcoded defaults (no space registered at all stays an error)
        return schedule_space(kernel).default_params(info)
