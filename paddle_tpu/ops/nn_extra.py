"""3D conv/pool, deformable conv, data_norm, roi pooling, shuffles.

Reference parity (each op cites its C++ source):
- conv3d / conv3d_transpose / pool3d: operators/conv_op.cc (3D paths),
  conv_transpose_op.cc, pool_op.cc
- deformable_conv: operators/deformable_conv_op.cc (v2, modulated) and
  deformable_conv_v1_op.cc
- data_norm: operators/data_norm_op.cc
- roi_pool: operators/roi_pool_op.cc; psroi_pool: operators/psroi_pool_op.cc
- pixel_unshuffle/channel_shuffle: the manipulation family around
  pixel_shuffle_op.cc

TPU-native: everything is static-shape lax/vmap code — deformable conv
is bilinear-gather + one big matmul (im2col form) so the FLOPs land on
the MXU instead of the reference's per-position CUDA kernels.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v, v)


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


# ---------------------------------------------------------------------------
# 3D convolution / pooling
# ---------------------------------------------------------------------------


@register_op("conv3d")
def conv3d(x, w, *, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    """operators/conv_op.cc 3D path. x [N,C,D,H,W], w [O,C/g,kD,kH,kW]."""
    assert data_format == "NCDHW"
    stride, dilation = _triple(stride), _triple(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _triple(padding)
        pad = [(p[0], p[0]), (p[1], p[1]), (p[2], p[2])]
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape, ("NCDHW", "OIDHW", "NCDHW")
    )
    return lax.conv_general_dilated(
        x, w, stride, pad, rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
    )


@register_op("conv3d_transpose")
def conv3d_transpose(x, w, *, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, data_format="NCDHW"):
    """conv_transpose_op.cc 3D path; w layout IODHW (paddle deconv)."""
    assert data_format == "NCDHW"
    stride, dilation = _triple(stride), _triple(dilation)
    p = _triple(padding)
    opad = _triple(output_padding)
    ks = [(w.shape[2 + i] - 1) * dilation[i] + 1 for i in range(3)]
    pad = [
        (ks[i] - 1 - p[i], ks[i] - 1 - p[i] + opad[i]) for i in range(3)
    ]
    w_flip = jnp.flip(w, axis=(2, 3, 4))
    if groups > 1:
        in_c = x.shape[1]
        w_g = w_flip.reshape(groups, in_c // groups, *w.shape[1:])
        w_t = jnp.concatenate(
            [jnp.swapaxes(w_g[g], 0, 1) for g in range(groups)], axis=0
        )
    else:
        w_t = jnp.swapaxes(w_flip, 0, 1)
    dn = lax.conv_dimension_numbers(
        x.shape, w_t.shape, ("NCDHW", "OIDHW", "NCDHW")
    )
    return lax.conv_general_dilated(
        x, w_t, window_strides=(1, 1, 1), padding=pad, lhs_dilation=stride,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
    )


@register_op("pool3d")
def pool3d(x, *, kernel_size, stride=None, padding=0, pooling_type="max",
           ceil_mode=False, exclusive=True, data_format="NCDHW"):
    """pool_op.cc 3D path via reduce_window."""
    assert data_format == "NCDHW"
    ks = _triple(kernel_size)
    st = _triple(stride) if stride is not None else ks
    p = _triple(padding)
    window = (1, 1) + ks
    strides = (1, 1) + st
    pads = ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p)
    if ceil_mode:
        pads = ((0, 0), (0, 0)) + tuple(
            (pi, pi + si - 1) for pi, si in zip(p, st)
        )
    if pooling_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else (
            jnp.iinfo(x.dtype).min
        )
        return lax.reduce_window(x, init, lax.max, window, strides, pads)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    if exclusive:
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return s / cnt
    return s / float(np.prod(ks))


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NCDHW"):
    return pool3d(x, kernel_size=kernel_size, stride=stride, padding=padding,
                  pooling_type="max", ceil_mode=ceil_mode,
                  data_format=data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCDHW"):
    return pool3d(x, kernel_size=kernel_size, stride=stride, padding=padding,
                  pooling_type="avg", ceil_mode=ceil_mode,
                  exclusive=exclusive, data_format=data_format)


# ---------------------------------------------------------------------------
# deformable convolution
# ---------------------------------------------------------------------------


def _bilinear_chw(img, y, x):
    """Sample img [C,H,W] at float coords (y[K], x[K]) -> [C,K]; zero
    outside (the deformable-conv border contract)."""
    c, h, w = img.shape
    inb = (y > -1.0) & (y < h) & (x > -1.0) & (x < w)
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1 = y - y0
    wx1 = x - x0
    wy0 = 1.0 - wy1
    wx0 = 1.0 - wx1

    def at(yy, xx):
        ok = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        return jnp.where(ok[None, :], img[:, yc, xc], 0.0)

    v = (at(y0, x0) * (wy0 * wx0)[None]
         + at(y0, x0 + 1) * (wy0 * wx1)[None]
         + at(y0 + 1, x0) * (wy1 * wx0)[None]
         + at(y0 + 1, x0 + 1) * (wy1 * wx1)[None])
    return jnp.where(inb[None, :], v, 0.0)


@register_op("deformable_conv")
def deformable_conv(x, offset, mask, weight, *, stride=1, padding=0,
                    dilation=1, deformable_groups=1, groups=1,
                    im2col_step=1):
    """operators/deformable_conv_op.cc (modulated, v2; pass mask=None for
    v1 semantics — deformable_conv_v1_op.cc).

    x [N,C,H,W]; offset [N, 2*dg*kh*kw, Ho, Wo] ((dy,dx) interleaved per
    tap); mask [N, dg*kh*kw, Ho, Wo]; weight [O, C/g, kh, kw].

    Design: sampled im2col columns + one [O, C*kh*kw] x [C*kh*kw, Ho*Wo]
    matmul per group — the gather feeds the MXU.
    """
    n, c, h, w_in = x.shape
    o, cpg, kh, kw = weight.shape
    st, dil, p = _pair(stride), _pair(dilation), _pair(padding)
    ho = (h + 2 * p[0] - (dil[0] * (kh - 1) + 1)) // st[0] + 1
    wo = (w_in + 2 * p[1] - (dil[1] * (kw - 1) + 1)) // st[1] + 1
    dg = deformable_groups
    cpdg = c // dg

    base_y = (jnp.arange(ho) * st[0] - p[0])[:, None, None]  # [Ho,1,1]
    base_x = (jnp.arange(wo) * st[1] - p[1])[None, :, None]  # [1,Wo,1]
    ky = (jnp.arange(kh) * dil[0])[None, None, :, None]
    kx = (jnp.arange(kw) * dil[1])[None, None, None, :]

    def per_image(img, off, msk):
        # off [2*dg*kh*kw, Ho, Wo] -> [dg, kh, kw, 2, Ho, Wo]
        off = off.reshape(dg, kh, kw, 2, ho, wo)
        if msk is not None:
            msk = msk.reshape(dg, kh, kw, ho, wo)
        cols = []
        for g in range(dg):
            dy = jnp.transpose(off[g, :, :, 0], (2, 3, 0, 1))  # [Ho,Wo,kh,kw]
            dx = jnp.transpose(off[g, :, :, 1], (2, 3, 0, 1))
            yy = base_y[:, :, :, None] + ky + dy  # [Ho,Wo,kh,kw]
            xx = base_x[:, :, :, None] + kx + dx
            v = _bilinear_chw(
                img[g * cpdg:(g + 1) * cpdg], yy.reshape(-1), xx.reshape(-1)
            ).reshape(cpdg, ho, wo, kh, kw)
            if msk is not None:
                # msk[g]: [kh, kw, Ho, Wo] -> [1, Ho, Wo, kh, kw]
                v = v * jnp.transpose(msk[g], (2, 3, 0, 1))[None]
            cols.append(v)
        col = jnp.concatenate(cols, axis=0)  # [C, Ho, Wo, kh, kw]
        col = jnp.transpose(col, (0, 3, 4, 1, 2)).reshape(c * kh * kw,
                                                          ho * wo)
        outs = []
        opg = o // groups
        for g in range(groups):
            wg = weight[g * opg:(g + 1) * opg].reshape(opg, cpg * kh * kw)
            cg = col[g * cpg * kh * kw:(g + 1) * cpg * kh * kw]
            outs.append(wg @ cg)
        return jnp.concatenate(outs, axis=0).reshape(o, ho, wo)

    if mask is None:
        return jax.vmap(lambda i, of: per_image(i, of, None))(x, offset)
    return jax.vmap(per_image)(x, offset, mask)


# ---------------------------------------------------------------------------
# data_norm
# ---------------------------------------------------------------------------


@register_op("data_norm", num_outputs=3)
def data_norm(x, batch_size, batch_sum, batch_square_sum, *, epsilon=1e-4):
    """operators/data_norm_op.cc forward: normalize by accumulated global
    stats. means = sum/size; scales = sqrt(size/square_sum).
    Returns (y, means, scales)."""
    means = batch_sum / batch_size
    scales = jnp.sqrt(batch_size / batch_square_sum)
    return (x - means[None, :]) * scales[None, :], means, scales


def data_norm_update(x, batch_size, batch_sum, batch_square_sum,
                     summary_decay=0.9999999):
    """The accumulator update the reference folds into the grad kernel
    (data_norm_op.cc backward): decayed running (size, sum, square_sum)."""
    n = x.shape[0]
    new_size = batch_size * summary_decay + n
    new_sum = batch_sum * summary_decay + x.sum(axis=0)
    new_sq = batch_square_sum * summary_decay + (x * x).sum(axis=0)
    return new_size, new_sum, new_sq


# ---------------------------------------------------------------------------
# RoI pooling family
# ---------------------------------------------------------------------------


@register_op("roi_pool")
def roi_pool(x, rois, *, batch_indices=None, pooled_height=1,
             pooled_width=1, spatial_scale=1.0):
    """operators/roi_pool_op.cc: max-pool each RoI bin (quantized
    boundaries, the pre-roi_align design). rois [R, 4] (x1,y1,x2,y2)."""
    r = rois.shape[0]
    c, h, w = x.shape[1:]
    bi = (jnp.zeros(r, jnp.int32) if batch_indices is None
          else batch_indices.astype(jnp.int32))
    ph, pw = int(pooled_height), int(pooled_width)

    def one(roi, b):
        x1 = jnp.round(roi[0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = x[b]  # [C, H, W]
        ys = jnp.arange(h)
        xs = jnp.arange(w)

        def bin_val(py, px):
            hs = y1 + (py * rh) // ph
            he = y1 + ((py + 1) * rh + ph - 1) // ph
            ws_ = x1 + (px * rw) // pw
            we = x1 + ((px + 1) * rw + pw - 1) // pw
            m = ((ys >= hs) & (ys < jnp.maximum(he, hs + 1)))[None, :, None] \
                & ((xs >= ws_) & (xs < jnp.maximum(we, ws_ + 1)))[None, None, :]
            return jnp.max(jnp.where(m, img, -jnp.inf), axis=(1, 2))

        grid = [[bin_val(py, px) for px in range(pw)] for py in range(ph)]
        return jnp.stack([jnp.stack(row, 1) for row in grid], 1)  # [C,ph,pw]

    return jax.vmap(one)(rois, bi)


@register_op("psroi_pool")
def psroi_pool(x, rois, *, batch_indices=None, output_channels=1,
               pooled_height=1, pooled_width=1, spatial_scale=1.0):
    """operators/psroi_pool_op.cc: position-sensitive average pooling —
    bin (py,px) reads channel group (py*pw+px) of its output channel."""
    r = rois.shape[0]
    c, h, w = x.shape[1:]
    ph, pw = int(pooled_height), int(pooled_width)
    oc = int(output_channels)
    assert c == oc * ph * pw, (c, oc, ph, pw)
    bi = (jnp.zeros(r, jnp.int32) if batch_indices is None
          else batch_indices.astype(jnp.int32))

    def one(roi, b):
        x1 = jnp.round(roi[0]) * spatial_scale
        y1 = jnp.round(roi[1]) * spatial_scale
        x2 = jnp.round(roi[2] + 1.0) * spatial_scale
        y2 = jnp.round(roi[3] + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h = rh / ph
        bin_w = rw / pw
        img = x[b].reshape(oc, ph * pw, h, w)
        ys = jnp.arange(h)
        xs = jnp.arange(w)

        def bin_val(py, px):
            hs = jnp.floor(y1 + py * bin_h)
            he = jnp.ceil(y1 + (py + 1) * bin_h)
            ws_ = jnp.floor(x1 + px * bin_w)
            we = jnp.ceil(x1 + (px + 1) * bin_w)
            m = ((ys >= hs) & (ys < he))[:, None] \
                & ((xs >= ws_) & (xs < we))[None, :]
            cnt = jnp.maximum(jnp.sum(m), 1)
            g = img[:, py * pw + px]  # [oc, H, W]
            return jnp.sum(jnp.where(m[None], g, 0.0), axis=(1, 2)) / cnt

        grid = [[bin_val(py, px) for px in range(pw)] for py in range(ph)]
        return jnp.stack([jnp.stack(row, 1) for row in grid], 1)

    return jax.vmap(one)(rois, bi)


# ---------------------------------------------------------------------------
# pixel / channel shuffles
# ---------------------------------------------------------------------------


@register_op("pixel_unshuffle")
def pixel_unshuffle(x, *, downscale_factor, data_format="NCHW"):
    """pixel_shuffle's inverse: [N,C,H*r,W*r] -> [N,C*r*r,H,W]."""
    assert data_format == "NCHW"
    n, c, hr, wr = x.shape
    r = int(downscale_factor)
    h, w = hr // r, wr // r
    x = x.reshape(n, c, h, r, w, r)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    return x.reshape(n, c * r * r, h, w)


@register_op("channel_shuffle")
def channel_shuffle(x, *, groups, data_format="NCHW"):
    """ShuffleNet channel shuffle: interleave channel groups."""
    assert data_format == "NCHW"
    n, c, h, w = x.shape
    g = int(groups)
    x = x.reshape(n, g, c // g, h, w)
    x = jnp.swapaxes(x, 1, 2)
    return x.reshape(n, c, h, w)


@register_op("prroi_pool")
def prroi_pool(x, rois, *, batch_indices=None, pooled_height=1,
               pooled_width=1, spatial_scale=1.0):
    """operators/prroi_pool_op.cc: Precise RoI Pooling — the EXACT
    integral of the bilinearly-interpolated feature map over each bin
    (no sampling-point approximation), continuously differentiable in
    both features and RoI coordinates.

    The triangle (bilinear) kernel integral has the closed form
    F(u) = 0, (u+1)^2/2, 1-(1-u)^2/2, 1 over the pieces of u=(t-i);
    per-bin weights are the separable products of per-axis integrals.
    """
    r = rois.shape[0]
    c, h, w = x.shape[1:]
    ph, pw = int(pooled_height), int(pooled_width)
    bi = (jnp.zeros(r, jnp.int32) if batch_indices is None
          else batch_indices.astype(jnp.int32))

    def tri_integral(a, b, centers):
        """∫_a^b max(0, 1-|t-i|) dt for every center i (vectorized)."""
        def F(u):
            return jnp.where(
                u <= -1.0, 0.0,
                jnp.where(
                    u <= 0.0, 0.5 * (u + 1.0) ** 2,
                    jnp.where(u <= 1.0, 1.0 - 0.5 * (1.0 - u) ** 2, 1.0),
                ),
            )

        return F(b - centers) - F(a - centers)

    ys = jnp.arange(h, dtype=x.dtype)
    xs = jnp.arange(w, dtype=x.dtype)

    def one(roi, b):
        x1 = roi[0] * spatial_scale
        y1 = roi[1] * spatial_scale
        x2 = roi[2] * spatial_scale
        y2 = roi[3] * spatial_scale
        bin_w = jnp.maximum(x2 - x1, 1e-6) / pw
        bin_h = jnp.maximum(y2 - y1, 1e-6) / ph
        img = x[b]  # [C, H, W]

        def bin_val(py, px):
            ax = x1 + px * bin_w
            bx = x1 + (px + 1) * bin_w
            ay = y1 + py * bin_h
            by = y1 + (py + 1) * bin_h
            wx = tri_integral(ax, bx, xs)  # [W]
            wy = tri_integral(ay, by, ys)  # [H]
            area = jnp.maximum((bx - ax) * (by - ay), 1e-6)
            return jnp.einsum("chw,h,w->c", img, wy, wx) / area

        grid = [[bin_val(py, px) for px in range(pw)] for py in range(ph)]
        return jnp.stack([jnp.stack(row, 1) for row in grid], 1)

    return jax.vmap(one)(rois, bi)
