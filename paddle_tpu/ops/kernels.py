"""Pure-JAX op kernels.

Reference parity: paddle/fluid/operators/ (~457 op types; SURVEY.md §2.2).
Each kernel is a pure function over jax arrays; XLA fuses elementwise chains
into surrounding matmuls automatically, so kernels stay simple and the
executor jits whole blocks (SURVEY.md §7 step 2). CUDA kernels in the
reference map to jnp/lax here; hand-fused CUDA ops map to XLA fusion or
pallas kernels (ops/pallas_kernels.py).

Conventions:
- positional args are tensor (traced) inputs; keyword args are static attrs
  (except PRNG keys, which are traced values passed as kwargs — they carry
  no gradient so keeping them out of the vjp positional list is free).
- NCHW is the default conv/pool layout, matching fluid.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

# ---------------------------------------------------------------------------
# Elementwise binary (operators/elementwise/)
# ---------------------------------------------------------------------------


def _register_binary(name, fn):
    register_op(name)(fn)


_register_binary("elementwise_add", lambda x, y, **kw: jnp.add(x, y))
_register_binary("elementwise_sub", lambda x, y, **kw: jnp.subtract(x, y))
_register_binary("elementwise_mul", lambda x, y, **kw: jnp.multiply(x, y))
_register_binary("elementwise_div", lambda x, y, **kw: jnp.divide(x, y))
_register_binary("elementwise_pow", lambda x, y, **kw: jnp.power(x, y))
_register_binary("elementwise_max", lambda x, y, **kw: jnp.maximum(x, y))
_register_binary("elementwise_min", lambda x, y, **kw: jnp.minimum(x, y))
_register_binary("elementwise_mod", lambda x, y, **kw: jnp.mod(x, y))
_register_binary("elementwise_floordiv", lambda x, y, **kw: jnp.floor_divide(x, y))
_register_binary("atan2", lambda x, y, **kw: jnp.arctan2(x, y))

_register_binary("equal", lambda x, y, **kw: jnp.equal(x, y))
_register_binary("not_equal", lambda x, y, **kw: jnp.not_equal(x, y))
_register_binary("less_than", lambda x, y, **kw: jnp.less(x, y))
_register_binary("less_equal", lambda x, y, **kw: jnp.less_equal(x, y))
_register_binary("greater_than", lambda x, y, **kw: jnp.greater(x, y))
_register_binary("greater_equal", lambda x, y, **kw: jnp.greater_equal(x, y))

_register_binary("logical_and", lambda x, y, **kw: jnp.logical_and(x, y))
_register_binary("logical_or", lambda x, y, **kw: jnp.logical_or(x, y))
_register_binary("logical_xor", lambda x, y, **kw: jnp.logical_xor(x, y))
register_op("logical_not")(lambda x, **kw: jnp.logical_not(x))

_register_binary("bitwise_and", lambda x, y, **kw: jnp.bitwise_and(x, y))
_register_binary("bitwise_or", lambda x, y, **kw: jnp.bitwise_or(x, y))
_register_binary("bitwise_xor", lambda x, y, **kw: jnp.bitwise_xor(x, y))
register_op("bitwise_not")(lambda x, **kw: jnp.bitwise_not(x))

# ---------------------------------------------------------------------------
# Elementwise unary (operators/activation_op.cc and friends)
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x),
    "square": jnp.square,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "atan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "asinh": jnp.arcsinh,
    "acosh": jnp.arccosh,
    "atanh": jnp.arctanh,
    "tanh": jnp.tanh,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "sign": jnp.sign,
    "reciprocal": lambda x: 1.0 / x,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "digamma": jax.scipy.special.digamma,
    "lgamma": jax.scipy.special.gammaln,
    "sigmoid": jax.nn.sigmoid,
    "logsigmoid": jax.nn.log_sigmoid,
    "softsign": jax.nn.soft_sign,
    "isnan": jnp.isnan,
    "isinf": jnp.isinf,
    "isfinite": jnp.isfinite,
    "trunc": jnp.trunc,
}
for _name, _fn in _UNARY.items():
    register_op(_name)(partial(lambda f, x, **kw: f(x), _fn))


@register_op("scale")
def scale(x, *, scale=1.0, bias=0.0, bias_after_scale=True):
    # operators/scale_op.cc
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


@register_op("clip")
def clip(x, *, min=None, max=None):
    return jnp.clip(x, min, max)


@register_op("pow")
def pow_(x, *, factor=1.0):
    return jnp.power(x, factor)


# Activations with attrs ----------------------------------------------------


@register_op("relu")
def relu(x, **kw):
    return jax.nn.relu(x)


@register_op("relu6")
def relu6(x, *, threshold=6.0):
    return jnp.clip(x, 0.0, threshold)


@register_op("leaky_relu")
def leaky_relu(x, *, alpha=0.01):
    return jnp.where(x >= 0, x, alpha * x)


@register_op("elu")
def elu(x, *, alpha=1.0):
    return jax.nn.elu(x, alpha)


@register_op("selu")
def selu(x, **kw):
    return jax.nn.selu(x)


@register_op("celu")
def celu(x, *, alpha=1.0):
    return jax.nn.celu(x, alpha)


@register_op("gelu")
def gelu(x, *, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


@register_op("hard_sigmoid")
def hard_sigmoid(x, *, slope=0.1666667, offset=0.5):
    return jnp.clip(x * slope + offset, 0.0, 1.0)


@register_op("hard_swish")
def hard_swish(x, *, threshold=6.0, scale=6.0, offset=3.0):
    return x * jnp.clip(x + offset, 0.0, threshold) / scale


@register_op("hard_tanh")
def hard_tanh(x, *, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


@register_op("hard_shrink")
def hard_shrink(x, *, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@register_op("softshrink")
def softshrink(x, *, lambda_=0.5):
    return jnp.where(x > lambda_, x - lambda_, jnp.where(x < -lambda_, x + lambda_, 0.0))


@register_op("tanh_shrink")
def tanh_shrink(x, **kw):
    return x - jnp.tanh(x)


@register_op("swish")
def swish(x, **kw):
    return jax.nn.silu(x)


@register_op("mish")
def mish(x, **kw):
    return x * jnp.tanh(jax.nn.softplus(x))


@register_op("softplus")
def softplus(x, *, beta=1.0, threshold=20.0):
    scaled = beta * x
    return jnp.where(scaled > threshold, x, jax.nn.softplus(scaled) / beta)


@register_op("prelu")
def prelu(x, alpha, **kw):
    return jnp.where(x >= 0, x, alpha * x)


@register_op("softmax")
def softmax(x, *, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def log_softmax(x, *, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@register_op("maxout")
def maxout(x, *, groups, axis=1):
    shape = list(x.shape)
    c = shape[axis]
    shape[axis : axis + 1] = [c // groups, groups]
    return jnp.max(jnp.reshape(x, shape), axis=axis + 1)


# ---------------------------------------------------------------------------
# Matrix ops (operators/matmul_op.cc, mul_op.cc, bmm, dot)
# ---------------------------------------------------------------------------


@register_op("matmul")
def matmul(x, y, *, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@register_op("mul")
def mul(x, y, *, x_num_col_dims=1, y_num_col_dims=1):
    # operators/mul_op.cc — flatten then 2D matmul
    xs = x.reshape((math.prod(x.shape[:x_num_col_dims]), -1))
    ys = y.reshape((math.prod(y.shape[:y_num_col_dims]), -1))
    out = xs @ ys
    return out.reshape(x.shape[:x_num_col_dims] + y.shape[y_num_col_dims:])


@register_op("bmm")
def bmm(x, y, **kw):
    return jnp.matmul(x, y)


@register_op("dot")
def dot(x, y, **kw):
    return jnp.sum(x * y, axis=-1)


@register_op("addmm")
def addmm(input, x, y, *, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


@register_op("linear")
def linear(x, w, b=None, **kw):
    # fused x@w+b — the fc_fuse_pass equivalent falls out of XLA fusion
    out = jnp.matmul(x, w)
    if b is not None:
        out = out + b
    return out


@register_op("cross")
def cross(x, y, *, axis=-1):
    return jnp.cross(x, y, axis=axis)


@register_op("cholesky")
def cholesky(x, *, upper=False):
    l = jnp.linalg.cholesky(x)
    return jnp.swapaxes(l, -1, -2) if upper else l


@register_op("matrix_power")
def matrix_power(x, *, n):
    return jnp.linalg.matrix_power(x, n)


@register_op("inverse")
def inverse(x, **kw):
    return jnp.linalg.inv(x)


@register_op("einsum")
def einsum(*operands, equation):
    return jnp.einsum(equation, *operands)


# ---------------------------------------------------------------------------
# Reductions (operators/reduce_ops/)
# ---------------------------------------------------------------------------


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return int(axis)


@register_op("reduce_sum")
def reduce_sum(x, *, dim=None, keep_dim=False):
    return jnp.sum(x, axis=_norm_axis(dim), keepdims=keep_dim)


@register_op("reduce_mean")
def reduce_mean(x, *, dim=None, keep_dim=False):
    return jnp.mean(x, axis=_norm_axis(dim), keepdims=keep_dim)


@register_op("reduce_max")
def reduce_max(x, *, dim=None, keep_dim=False):
    return jnp.max(x, axis=_norm_axis(dim), keepdims=keep_dim)


@register_op("reduce_min")
def reduce_min(x, *, dim=None, keep_dim=False):
    return jnp.min(x, axis=_norm_axis(dim), keepdims=keep_dim)


@register_op("reduce_prod")
def reduce_prod(x, *, dim=None, keep_dim=False):
    return jnp.prod(x, axis=_norm_axis(dim), keepdims=keep_dim)


@register_op("reduce_any")
def reduce_any(x, *, dim=None, keep_dim=False):
    return jnp.any(x, axis=_norm_axis(dim), keepdims=keep_dim)


@register_op("reduce_all")
def reduce_all(x, *, dim=None, keep_dim=False):
    return jnp.all(x, axis=_norm_axis(dim), keepdims=keep_dim)


@register_op("logsumexp")
def logsumexp(x, *, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=_norm_axis(axis), keepdims=keepdim)


@register_op("arg_max")
def arg_max(x, *, axis=None, keepdims=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdims if axis is not None else False)
    return out.astype(dtype)


@register_op("arg_min")
def arg_min(x, *, axis=None, keepdims=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdims if axis is not None else False)
    return out.astype(dtype)


@register_op("p_norm")
def p_norm(x, *, porder=2.0, axis=None, keepdim=False, epsilon=1e-12):
    axis = _norm_axis(axis)
    if porder == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if porder == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    return jnp.power(
        jnp.sum(jnp.power(jnp.abs(x), porder), axis=axis, keepdims=keepdim) + epsilon,
        1.0 / porder,
    )


@register_op("cumsum")
def cumsum(x, *, axis=None, flatten=False):
    if axis is None or flatten:
        return jnp.cumsum(x.reshape(-1))
    return jnp.cumsum(x, axis=axis)


@register_op("cumprod")
def cumprod(x, *, dim=None):
    return jnp.cumprod(x, axis=dim)


@register_op("mean_all")
def mean_all(x, **kw):
    # operators/mean_op.cc — full mean to scalar
    return jnp.mean(x)


# ---------------------------------------------------------------------------
# Tensor manipulation (reshape/transpose/concat/split/…)
# ---------------------------------------------------------------------------


@register_op("reshape")
def reshape(x, *, shape):
    return jnp.reshape(x, shape)


@register_op("transpose")
def transpose(x, *, perm):
    return jnp.transpose(x, perm)


@register_op("flatten")
def flatten(x, *, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return x.reshape((1,))
    start = start_axis % nd
    stop = stop_axis % nd
    shape = list(x.shape[:start]) + [-1] + list(x.shape[stop + 1 :])
    return jnp.reshape(x, shape)


@register_op("squeeze")
def squeeze(x, *, axes=None):
    if axes is None or axes == []:
        return jnp.squeeze(x)
    axes = [axes] if isinstance(axes, int) else list(axes)
    axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return jnp.squeeze(x, axis=axes) if axes else x


@register_op("unsqueeze")
def unsqueeze(x, *, axes):
    axes = [axes] if isinstance(axes, int) else list(axes)
    out = x
    for a in axes:
        out = jnp.expand_dims(out, a)
    return out


@register_op("concat")
def concat(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


@register_op("split", num_outputs=-1)
def split(x, *, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    sections = list(num_or_sections)
    total = x.shape[axis]
    if any(s in (-1, None) for s in sections):
        known = sum(s for s in sections if s not in (-1, None))
        sections = [total - known if s in (-1, None) else s for s in sections]
    idx = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        idx.append(acc)
    return tuple(jnp.split(x, idx, axis=axis))


@register_op("stack")
def stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


@register_op("unstack", num_outputs=-1)
def unstack(x, *, axis=0, num=None):
    n = num or x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis))


@register_op("slice")
def slice_(x, *, axes, starts, ends, strides=None):
    # operators/slice_op.cc semantics (clamped ends, negative indices)
    out = x
    strides = strides or [1] * len(axes)
    index = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        index[ax] = slice(st, en, sd)
    return out[tuple(index)]


@register_op("strided_slice")
def strided_slice(x, *, axes, starts, ends, strides):
    return slice_(x, axes=axes, starts=starts, ends=ends, strides=strides)


@register_op("getitem")
def getitem(x, *, idx):
    return x[idx]


@register_op("gather")
def gather(x, index, *, axis=0):
    if index.ndim == 0:
        index = index[None]
    return jnp.take(x, index, axis=axis)


@register_op("gather_nd")
def gather_nd(x, index, **kw):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@register_op("scatter")
def scatter(x, index, updates, *, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    # paddle scatter(overwrite=False) accumulates on zeroed rows
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


@register_op("scatter_nd_add")
def scatter_nd_add(x, index, updates, **kw):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


@register_op("index_select")
def index_select(x, index, *, axis=0):
    return jnp.take(x, index, axis=axis)


@register_op("index_sample")
def index_sample(x, index, **kw):
    return jnp.take_along_axis(x, index, axis=-1)


@register_op("take_along_axis")
def take_along_axis(x, index, *, axis):
    return jnp.take_along_axis(x, index, axis=axis)


@register_op("tile")
def tile(x, *, repeat_times):
    return jnp.tile(x, repeat_times)


@register_op("expand")
def expand(x, *, shape):
    # -1 keeps the corresponding (trailing-aligned) input dim
    offset = len(shape) - x.ndim
    shape = [
        x.shape[i - offset] if (s == -1 and i >= offset) else s
        for i, s in enumerate(shape)
    ]
    return jnp.broadcast_to(x, shape)


@register_op("broadcast_to")
def broadcast_to(x, *, shape):
    return jnp.broadcast_to(x, shape)


@register_op("where")
def where(cond, x, y, **kw):
    return jnp.where(cond, x, y)


@register_op("masked_fill")
def masked_fill(x, mask, *, value):
    return jnp.where(mask, value, x)


@register_op("pad")
def pad(x, *, paddings, mode="constant", value=0.0):
    # paddings: flat [before0, after0, before1, after1, ...]
    pairs = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(len(paddings) // 2)]
    while len(pairs) < x.ndim:
        pairs.insert(0, (0, 0))
    if mode == "constant":
        return jnp.pad(x, pairs, mode="constant", constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, pairs, mode=jmode)


@register_op("roll")
def roll(x, *, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@register_op("flip")
def flip(x, *, axis):
    return jnp.flip(x, axis=axis)


@register_op("tril")
def tril(x, *, diagonal=0):
    return jnp.tril(x, k=diagonal)


@register_op("triu")
def triu(x, *, diagonal=0):
    return jnp.triu(x, k=diagonal)


@register_op("diag")
def diag(x, *, offset=0, padding_value=0.0):
    if x.ndim == 1:
        out = jnp.diag(x, k=offset)
        if padding_value != 0.0:
            mask = jnp.diag(jnp.ones_like(x), k=offset).astype(bool)
            out = jnp.where(mask, out, padding_value)
        return out
    return jnp.diagonal(x, offset=offset)


@register_op("cast")
def cast(x, *, dtype):
    return x.astype(dtype)


@register_op("assign")
def assign(x, **kw):
    return x + 0 if jnp.issubdtype(x.dtype, jnp.number) else jnp.array(x)


@register_op("one_hot")
def one_hot(x, *, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


@register_op("top_k", num_outputs=2)
def top_k(x, *, k, axis=-1, largest=True, sorted=True):
    if largest:
        vals, idx = lax.top_k(jnp.moveaxis(x, axis, -1), k)
    else:
        vals, idx = lax.top_k(-jnp.moveaxis(x, axis, -1), k)
        vals = -vals
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis).astype(jnp.int64)


@register_op("argsort", num_outputs=2)
def argsort(x, *, axis=-1, descending=False):
    sign = -1 if descending else 1
    idx = jnp.argsort(sign * x, axis=axis, stable=True)
    vals = jnp.take_along_axis(x, idx, axis=axis)
    return vals, idx.astype(jnp.int64)


@register_op("sort")
def sort(x, *, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


@register_op("kthvalue", num_outputs=2)
def kthvalue(x, *, k, axis=-1, keepdim=False):
    vals = jnp.sort(x, axis=axis)
    idx = jnp.argsort(x, axis=axis, stable=True)
    v = jnp.take(vals, k - 1, axis=axis)
    i = jnp.take(idx, k - 1, axis=axis)
    if keepdim:
        v, i = jnp.expand_dims(v, axis), jnp.expand_dims(i, axis)
    return v, i.astype(jnp.int64)


@register_op("unbind", num_outputs=-1)
def unbind(x, *, axis=0):
    return tuple(jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis=axis))


@register_op("meshgrid", num_outputs=-1)
def meshgrid(*xs, **kw):
    return tuple(jnp.meshgrid(*xs, indexing="ij"))


@register_op("repeat_interleave")
def repeat_interleave(x, *, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register_op("shard_index")
def shard_index(x, *, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    lo, hi = shard_id * shard_size, (shard_id + 1) * shard_size
    in_shard = (x >= lo) & (x < hi)
    return jnp.where(in_shard, x - lo, ignore_value)


# ---------------------------------------------------------------------------
# NN ops (conv/pool/norm/embedding/dropout) — operators/conv_op.cc etc.
# ---------------------------------------------------------------------------


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


@register_op("conv2d")
def conv2d(x, w, *, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW"):
    stride, dilation = _pair(stride), _pair(dilation)
    if isinstance(padding, str):
        pad = padding.upper()  # "SAME" / "VALID"
    elif (
        isinstance(padding, (list, tuple)) and len(padding) == 2
        and all(isinstance(q, (list, tuple)) for q in padding)
    ):
        pad = [tuple(padding[0]), tuple(padding[1])]  # [(t,b),(l,r)]
    else:
        p = _pair(padding) if not (isinstance(padding, (list, tuple)) and len(padding) == 4) else padding
        if len(p) == 2:
            pad = [(p[0], p[0]), (p[1], p[1])]
        else:
            pad = [(p[0], p[1]), (p[2], p[3])]
    # weight layout is OIHW for both data formats (paddle convention); for
    # NHWC only the activation layout changes. XLA:TPU folds the weight
    # relayout into the conv.
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape, ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC")
    )
    return lax.conv_general_dilated(
        x, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.float32 else None,
    )


@register_op("depthwise_conv2d")
def depthwise_conv2d(x, w, *, stride=1, padding=0, dilation=1, groups=None, data_format="NCHW"):
    c = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    return conv2d(x, w, stride=stride, padding=padding, dilation=dilation, groups=c, data_format=data_format)


@register_op("conv2d_transpose")
def conv2d_transpose(x, w, *, stride=1, padding=0, output_padding=0, dilation=1, groups=1, data_format="NCHW"):
    stride, dilation = _pair(stride), _pair(dilation)
    p = _pair(padding)
    opad = _pair(output_padding)
    # w layout IOHW for paddle conv2d_transpose
    kh = (w.shape[2] - 1) * dilation[0] + 1
    kw_ = (w.shape[3] - 1) * dilation[1] + 1
    pad = [
        (kh - 1 - p[0], kh - 1 - p[0] + opad[0]),
        (kw_ - 1 - p[1], kw_ - 1 - p[1] + opad[1]),
    ]
    w_flip = jnp.flip(w, axis=(2, 3))
    w_t = jnp.swapaxes(w_flip, 0, 1)  # -> OIHW with O=out
    if groups > 1:
        # grouped transpose conv: w is (in, out//g, kh, kw)
        in_c = x.shape[1]
        w_g = w_flip.reshape(groups, in_c // groups, *w.shape[1:])
        w_t = jnp.concatenate([jnp.swapaxes(w_g[g], 0, 1) for g in range(groups)], axis=0)
    dn = lax.conv_dimension_numbers(x.shape, w_t.shape, ("NCHW", "OIHW", "NCHW"))
    return lax.conv_general_dilated(
        x, w_t, window_strides=(1, 1), padding=pad, lhs_dilation=stride,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
    )


@register_op("conv1d")
def conv1d(x, w, *, stride=1, padding=0, dilation=1, groups=1):
    x4 = x[:, :, None, :]
    w4 = w[:, :, None, :]
    s = stride if isinstance(stride, int) else stride[0]
    d = dilation if isinstance(dilation, int) else dilation[0]
    p = padding if isinstance(padding, int) else padding[0]
    out = conv2d(x4, w4, stride=(1, s), padding=[(0, 0), (p, p)], dilation=(1, d), groups=groups)
    return out[:, :, 0, :]


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _max_pool_fused(x, ks, st, p, window, strides, pads):
    return lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)


def _max_pool_fused_fwd(x, ks, st, p, window, strides, pads):
    y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
    return y, (x, y)


def _max_pool_fused_bwd(ks, st, p, window, strides, pads, res, dy):
    from .pallas.pool_backward import max_pool2d_backward

    x, y = res
    dx = max_pool2d_backward(
        x, y, dy.astype(y.dtype), kernel=tuple(ks), stride=tuple(st),
        padding=tuple(p),
    )
    return (dx,)


_max_pool_fused.defvjp(_max_pool_fused_fwd, _max_pool_fused_bwd)


@register_op("pool2d")
def pool2d(x, *, kernel_size, stride=None, padding=0, pooling_type="max",
           ceil_mode=False, exclusive=True, adaptive=False, data_format="NCHW"):
    if adaptive:
        return _adaptive_pool2d(x, kernel_size, pooling_type, data_format)
    ks = _pair(kernel_size)
    st = _pair(stride) if stride is not None else ks
    p = _pair(padding)
    h_ax = 2 if data_format == "NCHW" else 1
    spatial = x.shape[h_ax:h_ax + 2]
    if data_format == "NCHW":
        window = (1, 1, ks[0], ks[1])
        strides = (1, 1, st[0], st[1])
    else:  # NHWC
        window = (1, ks[0], ks[1], 1)
        strides = (1, st[0], st[1], 1)
    hp, wp = (p[0], p[0]), (p[1], p[1])
    if ceil_mode:
        extra = []
        for dim, k, s, pp in zip(spatial, ks, st, p):
            out_ceil = -(-(dim + 2 * pp - k) // s) + 1
            need = (out_ceil - 1) * s + k - (dim + 2 * pp)
            extra.append(max(0, need))
        hp, wp = (p[0], p[0] + extra[0]), (p[1], p[1] + extra[1])
    if data_format == "NCHW":
        pads = ((0, 0), (0, 0), hp, wp)
    else:
        pads = ((0, 0), hp, wp, (0, 0))
    if pooling_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        from ..flags import flag as _flag

        if _flag("use_pallas_pool_bwd"):
            from .pallas.pool_backward import max_pool_backward_supported

            ceil_extra = (hp[1] - p[0], wp[1] - p[1])
            if max_pool_backward_supported(
                    x.shape, x.dtype, ks, st, p, ceil_extra, data_format):
                # fused pallas backward (ops/pallas/pool_backward.py)
                # replaces XLA's select_and_scatter lowering — identical
                # first-max subgradient, one HBM pass
                return _max_pool_fused(x, ks, st, p, window, strides, pads)
        return lax.reduce_window(x, init, lax.max, window, strides, pads)
    # avg
    summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    if exclusive and (p != (0, 0) or ceil_mode):
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return summed / counts
    return summed / (ks[0] * ks[1])


def _adaptive_pool2d(x, output_size, pooling_type, data_format="NCHW"):
    oh, ow = _pair(output_size)
    if data_format == "NHWC":
        # delegate: XLA folds the transposes into the reductions
        y = _adaptive_pool2d(jnp.moveaxis(x, 3, 1), output_size,
                             pooling_type)
        return jnp.moveaxis(y, 1, 3)
    n, c, h, w = x.shape
    if h % oh == 0 and w % ow == 0:
        xr = x.reshape(n, c, oh, h // oh, ow, w // ow)
        red = jnp.max if pooling_type == "max" else jnp.mean
        return red(xr, axis=(3, 5))
    # general adaptive pooling via per-output-window reduce
    out = jnp.zeros((n, c, oh, ow), x.dtype)
    for i in range(oh):
        hs, he = (i * h) // oh, -(-((i + 1) * h) // oh)
        for j in range(ow):
            ws, we = (j * w) // ow, -(-((j + 1) * w) // ow)
            win = x[:, :, hs:he, ws:we]
            red = jnp.max if pooling_type == "max" else jnp.mean
            out = out.at[:, :, i, j].set(red(win, axis=(2, 3)))
    return out


@register_op("adaptive_pool2d")
def adaptive_pool2d(x, *, output_size, pooling_type="avg", data_format="NCHW"):
    return _adaptive_pool2d(x, output_size, pooling_type, data_format)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _bn_train_core(x, scale, bias, epsilon, axes, shape):
    """Training-mode BN with a memory-lean VJP: the backward recomputes
    x-hat from the ORIGINAL (bf16) input instead of letting autodiff save
    the f32-upcast intermediates — on an HBM-bound conv net that halves
    the BN-related backward traffic (cudnn's bn kernels do the same:
    /root/reference/paddle/fluid/operators/batch_norm_op.cu saved_mean/
    saved_inv_var + raw x)."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=axes)
    varb = jnp.var(xf, axis=axes)
    inv = lax.rsqrt(varb + epsilon)
    y = (
        (xf - mu.reshape(shape)) * inv.reshape(shape) * scale.reshape(shape)
        + bias.reshape(shape)
    ).astype(x.dtype)
    return y, mu, varb


def _bn_train_fwd(x, scale, bias, epsilon, axes, shape):
    out = _bn_train_core(x, scale, bias, epsilon, axes, shape)
    _, mu, varb = out
    inv = lax.rsqrt(varb + epsilon)
    return out, (x, mu, inv, scale)


def _bn_train_bwd(epsilon, axes, shape, res, cts):
    dy = cts[0]  # cotangents of (mu, varb) — running-stat paths — dropped,
    # matching the reference (saved stats are not differentiated through)
    x, mu, inv, scale = res
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    n = 1
    for a in axes:
        n *= x.shape[a]
    xhat = (xf - mu.reshape(shape)) * inv.reshape(shape)
    dbias = jnp.sum(dyf, axis=axes)
    dscale = jnp.sum(dyf * xhat, axis=axes)
    dx = (
        inv.reshape(shape) * scale.reshape(shape).astype(jnp.float32)
        * (dyf - (dbias / n).reshape(shape) - xhat * (dscale / n).reshape(shape))
    )
    return (dx.astype(x.dtype), dscale.astype(scale.dtype),
            dbias.astype(scale.dtype))


_bn_train_core.defvjp(_bn_train_fwd, _bn_train_bwd)


@register_op("batch_norm", num_outputs=3)
def batch_norm(x, scale, bias, mean, var, *, momentum=0.9, epsilon=1e-5,
               training=True, data_format="NCHW"):
    """Returns (y, new_running_mean, new_running_var).

    operators/batch_norm_op.cc — running stats follow paddle's
    running = momentum*running + (1-momentum)*batch.

    TPU dtype discipline: statistics accumulate in float32 regardless of
    the carrier dtype (bf16 mean/var would lose ~3 decimal digits), but
    the OUTPUT keeps x.dtype — under bf16 AMP the activation never
    round-trips through an f32 HBM buffer. ResNet-50 at batch 128 is
    HBM-bound; carrying f32 activations around every BN costs ~2x the
    step time (see COVERAGE.md ResNet-50 section).
    """
    axes = tuple(i for i in range(x.ndim) if i != (1 if data_format == "NCHW" else x.ndim - 1))
    shape = [1] * x.ndim
    caxis = 1 if data_format == "NCHW" else x.ndim - 1
    shape[caxis] = x.shape[caxis]

    if training:
        y, batch_mean, batch_var = _bn_train_core(
            x, scale, bias, epsilon, tuple(axes), tuple(shape)
        )
        new_mean = momentum * mean + (1 - momentum) * batch_mean
        new_var = momentum * var + (1 - momentum) * batch_var
        return y, new_mean, new_var

    xf = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
    inv = lax.rsqrt(var + epsilon)
    y = (xf - mean.reshape(shape)) * inv.reshape(shape) * scale.reshape(shape) + bias.reshape(shape)
    return y.astype(x.dtype), mean, var


@register_op("layer_norm")
def layer_norm(x, scale=None, bias=None, *, epsilon=1e-5, begin_norm_axis=-1):
    # operators/layer_norm_op.cc — normalize over trailing dims.
    # Statistics in f32, output in x.dtype (same bf16-carrier discipline
    # as batch_norm: no f32 activation round-trips under AMP).
    if begin_norm_axis < 0:
        begin_norm_axis = x.ndim + begin_norm_axis
    axes = tuple(range(begin_norm_axis, x.ndim))
    xf = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + epsilon)
    if scale is not None:
        y = y * scale
    if bias is not None:
        y = y + bias
    return y.astype(x.dtype)


@register_op("group_norm")
def group_norm(x, scale=None, bias=None, *, groups, epsilon=1e-5, data_format="NCHW"):
    n, c = x.shape[0], x.shape[1]
    xr = x.reshape(n, groups, c // groups, *x.shape[2:])
    axes = tuple(range(2, xr.ndim))
    mean = jnp.mean(xr, axis=axes, keepdims=True)
    var = jnp.var(xr, axis=axes, keepdims=True)
    y = ((xr - mean) * lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = [1, c] + [1] * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y


@register_op("instance_norm")
def instance_norm(x, scale=None, bias=None, *, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + epsilon)
    shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y


@register_op("lookup_table")
def lookup_table(w, ids, *, padding_idx=-1):
    # operators/lookup_table_op.cc (embedding)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


@register_op("dropout")
def dropout(x, *, p=0.5, training=True, mode="upscale_in_train", key=None):
    if not training or p == 0.0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


@register_op("interpolate")
def interpolate(x, *, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW"):
    n, c, h, w = x.shape
    if size is None:
        sf = _pair(scale_factor)
        size = (int(h * sf[0]), int(w * sf[1]))
    oh, ow = size
    jmode = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic"}[mode]
    xt = jnp.moveaxis(x, 1, -1)  # N H W C for image resize
    out = jax.image.resize(xt, (n, oh, ow, c), method=jmode)
    return jnp.moveaxis(out, -1, 1)


@register_op("pixel_shuffle")
def pixel_shuffle(x, *, upscale_factor, data_format="NCHW"):
    n, c, h, w = x.shape
    r = upscale_factor
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, c // (r * r), h * r, w * r)


@register_op("unfold")
def unfold(x, *, kernel_sizes, strides=1, paddings=0, dilations=1):
    ks, st, p, d = _pair(kernel_sizes), _pair(strides), _pair(paddings), _pair(dilations)
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])))
    oh = (h + 2 * p[0] - d[0] * (ks[0] - 1) - 1) // st[0] + 1
    ow = (w + 2 * p[1] - d[1] * (ks[1] - 1) - 1) // st[1] + 1
    patches = []
    for i in range(ks[0]):
        for j in range(ks[1]):
            patch = xp[:, :, i * d[0] : i * d[0] + oh * st[0] : st[0], j * d[1] : j * d[1] + ow * st[1] : st[1]]
            patches.append(patch)
    out = jnp.stack(patches, axis=2)  # n, c, kh*kw, oh, ow
    return out.reshape(n, c * ks[0] * ks[1], oh * ow)


# ---------------------------------------------------------------------------
# Losses (operators/softmax_with_cross_entropy_op.cc etc.)
# ---------------------------------------------------------------------------


@register_op("softmax_with_cross_entropy")
def softmax_with_cross_entropy(logits, label, *, soft_label=False, axis=-1, ignore_index=-100):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        return -jnp.sum(label * logp, axis=axis, keepdims=True)
    lbl = label
    squeeze_back = False
    if lbl.ndim == logits.ndim:
        lbl = jnp.squeeze(lbl, axis=axis)
        squeeze_back = True
    picked = jnp.take_along_axis(logp, jnp.expand_dims(jnp.clip(lbl, 0, None), axis), axis=axis)
    loss = -picked
    mask = jnp.expand_dims(lbl != ignore_index, axis)
    loss = jnp.where(mask, loss, 0.0)
    if not squeeze_back:
        pass
    return loss


@register_op("cross_entropy")
def cross_entropy_kernel(logits, label, *, soft_label=False, axis=-1,
                         ignore_index=-100, reduction="mean", use_softmax=True,
                         weight=None):
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.clip(logits, 1e-12, None))
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis)
        valid = jnp.ones_like(loss, dtype=bool)
    else:
        lbl = label
        if lbl.ndim == logits.ndim:
            lbl = jnp.squeeze(lbl, axis=axis)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(jnp.clip(lbl, 0, None), axis), axis=axis)
        loss = -jnp.squeeze(picked, axis=axis)
        valid = lbl != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if weight is not None:
            wsel = jnp.take(weight, jnp.clip(lbl, 0, None))
            loss = loss * jnp.where(valid, wsel, 0.0)
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    if weight is not None and not soft_label:
        lbl2 = label if label.ndim != logits.ndim else jnp.squeeze(label, axis=axis)
        wsel = jnp.take(weight, jnp.clip(lbl2, 0, None))
        denom = jnp.maximum(jnp.sum(jnp.where(valid, wsel, 0.0)), 1e-12)
    return jnp.sum(loss) / denom


@register_op("mse_loss")
def mse_loss(x, y, *, reduction="mean"):
    loss = jnp.square(x - y)
    return _reduce_loss(loss, reduction)


@register_op("l1_loss")
def l1_loss(x, y, *, reduction="mean"):
    return _reduce_loss(jnp.abs(x - y), reduction)


@register_op("smooth_l1_loss")
def smooth_l1_loss(x, y, *, reduction="mean", delta=1.0):
    d = jnp.abs(x - y)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce_loss(loss, reduction)


@register_op("bce_loss")
def bce_loss(x, label, *, reduction="mean"):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.clip(x, eps, None)) + (1 - label) * jnp.log(jnp.clip(1 - x, eps, None)))
    return _reduce_loss(loss, reduction)


@register_op("bce_with_logits")
def bce_with_logits(logits, label, *, reduction="mean", pos_weight=None):
    max_val = jnp.clip(-logits, 0, None)
    if pos_weight is not None:
        log_weight = (pos_weight - 1) * label + 1
        loss = (1 - label) * logits + log_weight * (jnp.log(jnp.exp(-max_val) + jnp.exp(-logits - max_val)) + max_val)
    else:
        loss = (1 - label) * logits + max_val + jnp.log(jnp.exp(-max_val) + jnp.exp(-logits - max_val))
    return _reduce_loss(loss, reduction)


@register_op("nll_loss")
def nll_loss(logp, label, *, reduction="mean", ignore_index=-100):
    picked = jnp.take_along_axis(logp, jnp.expand_dims(jnp.clip(label, 0, None), 1), axis=1)
    loss = -jnp.squeeze(picked, axis=1)
    valid = label != ignore_index
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)


@register_op("kl_div")
def kl_div(x, target, *, reduction="mean"):
    loss = target * (jnp.log(jnp.clip(target, 1e-12, None)) - x)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return _reduce_loss(loss, reduction)


@register_op("log_loss")
def log_loss(pred, label, *, epsilon=1e-4):
    return -label * jnp.log(pred + epsilon) - (1 - label) * jnp.log(1 - pred + epsilon)


@register_op("hinge_loss")
def hinge_loss(logits, label, **kw):
    return jnp.clip(1 - logits * (2 * label - 1), 0, None)


@register_op("square_error_cost")
def square_error_cost(x, y, **kw):
    return jnp.square(x - y)


@register_op("margin_ranking_loss")
def margin_ranking_loss(x, y, label, *, margin=0.0, reduction="mean"):
    loss = jnp.clip(-label * (x - y) + margin, 0, None)
    return _reduce_loss(loss, reduction)


@register_op("cosine_similarity")
def cosine_similarity(x, y, *, axis=1, eps=1e-8):
    dot_ = jnp.sum(x * y, axis=axis)
    nx = jnp.linalg.norm(x, axis=axis)
    ny = jnp.linalg.norm(y, axis=axis)
    return dot_ / jnp.clip(nx * ny, eps, None)


def _reduce_loss(loss, reduction):
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    return jnp.mean(loss)


# ---------------------------------------------------------------------------
# AMP primitive (operators/amp/amp_check_finite_and_scale_op)
# ---------------------------------------------------------------------------


@register_op("check_finite_and_unscale", num_outputs=-1)
def check_finite_and_unscale(*xs, scale):
    found_inf = jnp.zeros((), jnp.bool_)
    outs = []
    for x in xs:
        finite = jnp.all(jnp.isfinite(x))
        found_inf = found_inf | ~finite
        outs.append(x / scale)
    return tuple(outs) + (found_inf,)


@register_op("update_loss_scaling", num_outputs=3)
def update_loss_scaling(scale, good_steps, found_inf, *, incr_every_n_steps=2000,
                        decr_every_n_nan_or_inf=1, incr_ratio=2.0, decr_ratio=0.5):
    new_good = jnp.where(found_inf, 0, good_steps + 1)
    should_incr = new_good >= incr_every_n_steps
    new_scale = jnp.where(
        found_inf, jnp.maximum(scale * decr_ratio, 1.0),
        jnp.where(should_incr, scale * incr_ratio, scale),
    )
    new_good = jnp.where(should_incr, 0, new_good)
    return new_scale, new_good, found_inf


# ---------------------------------------------------------------------------
# Metrics (operators/metrics/accuracy_op.cc)
# ---------------------------------------------------------------------------


@register_op("accuracy")
def accuracy(pred_topk_idx, label, **kw):
    if label.ndim == pred_topk_idx.ndim:
        lbl = label
    else:
        lbl = label[:, None]
    correct = jnp.any(pred_topk_idx == lbl, axis=-1)
    return jnp.mean(correct.astype(jnp.float32))


# ---------------------------------------------------------------------------
# RNG ops (operators/uniform_random_op.cc, gaussian_random_op.cc, …)
# ---------------------------------------------------------------------------


@register_op("uniform_random")
def uniform_random(*, shape, min=-1.0, max=1.0, dtype="float32", key=None):
    return jax.random.uniform(key, shape, dtype=jnp.dtype(dtype), minval=min, maxval=max)


@register_op("gaussian_random")
def gaussian_random(*, shape, mean=0.0, std=1.0, dtype="float32", key=None):
    return jax.random.normal(key, shape, dtype=jnp.dtype(dtype)) * std + mean


@register_op("randint")
def randint(*, low, high, shape, dtype="int64", key=None):
    return jax.random.randint(key, shape, low, high, dtype=jnp.dtype(dtype))


@register_op("randperm")
def randperm(*, n, dtype="int64", key=None):
    return jax.random.permutation(key, n).astype(jnp.dtype(dtype))


@register_op("bernoulli")
def bernoulli(x, *, key=None):
    return jax.random.bernoulli(key, x).astype(x.dtype)


@register_op("multinomial")
def multinomial(x, *, num_samples=1, replacement=False, key=None):
    logits = jnp.log(jnp.clip(x, 1e-30, None))
    if replacement:
        return jax.random.categorical(key, logits, axis=-1, shape=(*x.shape[:-1], num_samples)).astype(jnp.int64)
    # Gumbel top-k trick for sampling without replacement
    g = jax.random.gumbel(key, x.shape, dtype=logits.dtype)
    _, idx = lax.top_k(logits + g, num_samples)
    return idx.astype(jnp.int64)


@register_op("truncated_gaussian_random")
def truncated_gaussian_random(*, shape, mean=0.0, std=1.0, dtype="float32", key=None):
    out = jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype=jnp.dtype(dtype))
    return out * std + mean


# ---------------------------------------------------------------------------
# Fill / init ops (operators/fill_constant_op.cc) + static-graph helpers
# ---------------------------------------------------------------------------


@register_op("fill_constant")
def fill_constant(*, shape, value, dtype="float32"):
    return jnp.full(tuple(shape), value, jnp.dtype(dtype))


@register_op("fill_any_like")
def fill_any_like(x, *, value):
    return jnp.full(x.shape, value, x.dtype)


@register_op("sum_n")
def sum_n(*xs, **kw):
    # grad accumulation (fluid/backward.py inserts sum ops for multi-consumer vars)
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


# ---------------------------------------------------------------------------
# Optimizer update ops (operators/optimizers/*.cc) — static-graph versions.
# lr is a traced scalar input so schedules don't retrigger compilation.
# ---------------------------------------------------------------------------


@register_op("sgd")
def sgd_update(param, grad, lr, **kw):
    return param - lr * grad


@register_op("momentum_update", num_outputs=2)
def momentum_update(param, grad, velocity, lr, *, mu=0.9, use_nesterov=False):
    v = mu * velocity + grad
    if use_nesterov:
        new_p = param - lr * (grad + mu * v)
    else:
        new_p = param - lr * v
    return new_p, v


@register_op("adam_update", num_outputs=3)
def adam_update(param, grad, moment1, moment2, lr, step, *, beta1=0.9, beta2=0.999,
                epsilon=1e-8):
    m = beta1 * moment1 + (1 - beta1) * grad
    v = beta2 * moment2 + (1 - beta2) * grad * grad
    t = step.astype(param.dtype)
    mhat = m / (1 - beta1**t)
    vhat = v / (1 - beta2**t)
    new_p = param - lr * mhat / (jnp.sqrt(vhat) + epsilon)
    return new_p, m, v


@register_op("increment")
def increment(x, *, value=1.0):
    return x + jnp.asarray(value, x.dtype)
