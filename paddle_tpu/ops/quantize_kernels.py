"""Fake-quantization op family (INT8 simulation).

Reference parity: paddle/fluid/operators/fake_quantize_op.cc
(fake_quantize_abs_max :608, fake_quantize_dequantize_abs_max :616,
fake_quantize_range_abs_max :624, fake_quantize_moving_average_abs_max
:632, fake_channel_wise_quantize_abs_max :650,
moving_average_abs_max_scale :658) and fake_dequantize_op.cc.

TPU-native: quantization on TPU is *simulated* (quant-dequant in the
compiled graph — the MXU computes in bf16/f32 either way); the value is
(a) QAT: training that bakes in int8 rounding so exported models run on
int8 inference hardware, and (b) scale calibration for deployment. The
quantize→round→dequantize chain gets a straight-through estimator
gradient (custom_vjp), matching FakeQuantDequantGrad's identity pass.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

__all__ = []


def _qdq(x, scale, bit_length):
    """Quantize to [-bnt, bnt] then dequantize (the simulation core)."""
    bnt = float((1 << (bit_length - 1)) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.round(jnp.clip(x / s * bnt, -bnt, bnt))
    return q * s / bnt


@register_op("fake_quantize_abs_max", num_outputs=2)
def fake_quantize_abs_max(x, *, bit_length=8):
    """Returns (quantized_int_values, scale). Out holds the rounded
    integer grid values (as float, like the reference's Out tensor)."""
    bnt = float((1 << (bit_length - 1)) - 1)
    scale = jnp.max(jnp.abs(x))
    s = jnp.maximum(scale, 1e-8)
    q = jnp.round(jnp.clip(x / s * bnt, -bnt, bnt))
    return q, scale


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _qdq_ste(x, scale, bit_length):
    return _qdq(x, scale, bit_length)


def _qdq_fwd(x, scale, bit_length):
    return _qdq(x, scale, bit_length), scale


def _qdq_bwd(bit_length, scale, gy):
    # FakeQuantDequantGrad: straight-through — dL/dx = dL/dout; the
    # scale is an observed statistic, not a trained parameter
    return gy, jnp.zeros_like(scale)


_qdq_ste.defvjp(_qdq_fwd, _qdq_bwd)


@register_op("fake_quantize_dequantize_abs_max", num_outputs=2)
def fake_quantize_dequantize_abs_max(x, *, bit_length=8):
    """Quant-dequant with dynamic abs-max scale + STE gradient."""
    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    return _qdq_ste(x, scale, bit_length), scale


@register_op("fake_quantize_range_abs_max", num_outputs=2)
def fake_quantize_range_abs_max(x, in_scale, *, bit_length=8,
                                window_size=10000, is_test=False):
    """Scale from the running window max (training keeps the max of the
    current and stored scale — the reference's window behavior folded to
    its steady state). Returns (out_int_values, out_scale)."""
    cur = jnp.max(jnp.abs(x))
    scale = jnp.where(
        jnp.asarray(is_test), in_scale.reshape(()),
        jnp.maximum(cur, in_scale.reshape(())),
    )
    bnt = float((1 << (bit_length - 1)) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.round(jnp.clip(x / s * bnt, -bnt, bnt))
    return q, scale


@register_op("fake_quantize_moving_average_abs_max", num_outputs=4)
def fake_quantize_moving_average_abs_max(x, in_scale, in_state, in_accum, *,
                                         bit_length=8, moving_rate=0.9,
                                         is_test=False):
    """EMA abs-max scale (the QAT activation quantizer). Returns
    (out_int_values, out_scale, out_state, out_accum)."""
    cur = jnp.max(jnp.abs(x))
    state = jnp.where(jnp.asarray(is_test), in_state,
                      in_state * moving_rate + 1.0)
    accum = jnp.where(jnp.asarray(is_test), in_accum,
                      in_accum * moving_rate + cur)
    scale = jnp.where(jnp.asarray(is_test), in_scale.reshape(()),
                      accum / jnp.maximum(state, 1e-8)).reshape(())
    bnt = float((1 << (bit_length - 1)) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.round(jnp.clip(x / s * bnt, -bnt, bnt))
    return q, scale, state, accum


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             num_outputs=4)
def fake_quantize_dequantize_moving_average_abs_max(
        x, in_scale, in_state, in_accum, *, bit_length=8, moving_rate=0.9,
        is_test=False):
    """QAT activation quant-dequant: EMA scale + STE gradient.
    Returns (out, out_scale, out_state, out_accum)."""
    cur = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    state = jnp.where(jnp.asarray(is_test), in_state,
                      in_state * moving_rate + 1.0)
    accum = jnp.where(jnp.asarray(is_test), in_accum,
                      in_accum * moving_rate + cur)
    scale = jnp.where(jnp.asarray(is_test), in_scale.reshape(()),
                      accum / jnp.maximum(state, 1e-8)).reshape(())
    return _qdq_ste(x, scale, bit_length), scale, state, accum


@register_op("fake_channel_wise_quantize_abs_max", num_outputs=2)
def fake_channel_wise_quantize_abs_max(x, *, bit_length=8, quant_axis=0):
    """Per-output-channel abs-max weight quantization. Returns
    (out_int_values, scales [C])."""
    axes = tuple(d for d in range(x.ndim) if d != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes)
    bnt = float((1 << (bit_length - 1)) - 1)
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    s = jnp.maximum(scale, 1e-8).reshape(shape)
    q = jnp.round(jnp.clip(x / s * bnt, -bnt, bnt))
    return q, scale


@register_op("fake_channel_wise_quantize_dequantize_abs_max",
             num_outputs=2)
def fake_channel_wise_quantize_dequantize_abs_max(x, *, bit_length=8,
                                                  quant_axis=0):
    """Per-channel quant-dequant with STE (the QAT weight quantizer)."""
    axes = tuple(d for d in range(x.ndim) if d != quant_axis)
    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(x), axis=axes))
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    return _qdq_ste(x, scale.reshape(shape), bit_length), scale


@register_op("moving_average_abs_max_scale", num_outputs=4)
def moving_average_abs_max_scale(x, in_scale, in_state, in_accum, *,
                                 moving_rate=0.9, is_test=False):
    """Scale observer only (no quantization): out == x.
    Returns (out, out_scale, out_state, out_accum)."""
    cur = jnp.max(jnp.abs(x))
    state = jnp.where(jnp.asarray(is_test), in_state,
                      in_state * moving_rate + 1.0)
    accum = jnp.where(jnp.asarray(is_test), in_accum,
                      in_accum * moving_rate + cur)
    scale = jnp.where(jnp.asarray(is_test), in_scale.reshape(()),
                      accum / jnp.maximum(state, 1e-8)).reshape(())
    return x, scale, state, accum


@register_op("fake_dequantize_max_abs")
def fake_dequantize_max_abs(x, scale, *, max_range):
    """fake_dequantize_op.cc: x * scale / max_range."""
    return x * scale.reshape(()) / float(max_range)


@register_op("fake_channel_wise_dequantize_max_abs")
def fake_channel_wise_dequantize_max_abs(x, scale, *, quant_bits=(8,),
                                         quant_axis=0):
    bnt = float((1 << (int(quant_bits[0]) - 1)) - 1)
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    return x * scale.reshape(shape) / bnt


@register_op("quant_dequant_static")
def quant_dequant_static(x, *, scale, bit_length=8):
    """PTQ simulation op with a calibrated constant scale
    (quantization_pass.py's inserted quant/dequant pair)."""
    return _qdq(x, jnp.asarray(scale, x.dtype), bit_length)


# ---------------------------------------------------------------------------
# Deployable int8 ops (slim/ptq.py save_int8_model): REAL int8 storage and
# compute, not quant-dequant simulation. The program carries int8 weights
# plus per-tensor calibrated activation scales; matmul/mul contract the
# int8 operands into int32 on the MXU (ops/pallas/int8_matmul.py behind
# FLAGS_use_int8_matmul; identical jnp dot_general fallback) and apply the
# combined dequant scale once on the int32 accumulator.
# ---------------------------------------------------------------------------


def _bnt(bit_length):
    return float((1 << (int(bit_length) - 1)) - 1)


@register_op("quantize_static")
def quantize_static(x, *, scale, bit_length=8):
    """f32 -> int8 with a calibrated constant scale (the activation
    quantize in a deployed int8 program)."""
    bnt = _bnt(bit_length)
    s = max(float(scale), 1e-8)
    q = jnp.round(jnp.clip(x.astype(jnp.float32) / s * bnt, -bnt, bnt))
    return q.astype(jnp.int8)


@register_op("dequantize_static")
def dequantize_static(x, *, scale, bit_length=8, dtype="float32"):
    """int8 -> float with a constant scale (restores f32 weights for ops
    with no int8 compute path yet, e.g. conv2d — the weight still ships
    and loads as int8 bytes)."""
    bnt = _bnt(bit_length)
    return x.astype(dtype) * (float(scale) / bnt)


@register_op("matmul_int8")
def matmul_int8(x, y, *, scale_x, scale_y, bit_length=8,
                y_bit_length=None, transpose_x=False, transpose_y=False):
    """int8 × int8 matmul with int32 accumulation and one dequant.

    ``x``/``y`` are int8 on the calibrated grids ``scale_x``/``scale_y``
    (``bit_length`` = x's grid width, ``y_bit_length`` = y's, defaulting
    to x's — activation and weight bits may differ); the int32 product
    dequantizes by ``scale_x·scale_y / (bnt_x·bnt_y)`` — the only
    rounding in the op is the operands' own quantization (the
    contraction itself is exact integer math).
    """
    from .pallas.int8_matmul import int8_matmul as _mm

    if transpose_x and x.ndim > 1:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y and y.ndim > 1:
        y = jnp.swapaxes(y, -1, -2)
    lead = x.shape[:-1]
    acc = _mm(x.reshape((-1, x.shape[-1])), y)
    bnt_x = _bnt(bit_length)
    bnt_y = _bnt(bit_length if y_bit_length is None else y_bit_length)
    out = acc.astype(jnp.float32) * (
        float(scale_x) * float(scale_y) / (bnt_x * bnt_y))
    return out.reshape(lead + (y.shape[-1],))


@register_op("mul_int8")
def mul_int8(x, y, *, scale_x, scale_y, bit_length=8, y_bit_length=None,
             x_num_col_dims=1, y_num_col_dims=1):
    """int8 twin of the ``mul`` op (flatten then 2D matmul); bit-length
    semantics as in :func:`matmul_int8`."""
    import math as _math

    from .pallas.int8_matmul import int8_matmul as _mm

    xs = x.reshape((_math.prod(x.shape[:x_num_col_dims]), -1))
    ys = y.reshape((_math.prod(y.shape[:y_num_col_dims]), -1))
    acc = _mm(xs, ys)
    bnt_x = _bnt(bit_length)
    bnt_y = _bnt(bit_length if y_bit_length is None else y_bit_length)
    out = acc.astype(jnp.float32) * (
        float(scale_x) * float(scale_y) / (bnt_x * bnt_y))
    return out.reshape(x.shape[:x_num_col_dims] + y.shape[y_num_col_dims:])
