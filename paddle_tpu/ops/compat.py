"""Reference op-type compatibility layer: aliases + small tail kernels.

Reference parity: the op types of paddle/fluid/operators/ whose
semantics already exist here under a different registry name (the *_v2 /
*2 io-variant families) plus small kernels closing the remaining tail
(tools/check_op_coverage.py tracks the list).

An alias registers the reference op type dispatching to the existing
kernel — programs/op tests written against reference op names run
unchanged.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import get_op, register_op


def _alias(ref_name, target, num_outputs=1):
    k = get_op(target).fn

    def fn(*args, **kwargs):
        return k(*args, **kwargs)

    fn.__name__ = ref_name
    fn.__doc__ = f"alias of {target!r} (reference op type {ref_name!r})"
    register_op(ref_name, num_outputs=num_outputs)(fn)


# -- v2 / *2 io-variants (identical math, different slot layout) -------------
_alias("matmul_v2", "matmul")
_alias("reshape2", "reshape")
_alias("transpose2", "transpose")
_alias("squeeze2", "squeeze")
_alias("unsqueeze2", "unsqueeze")
_alias("flatten2", "flatten")
_alias("flatten_contiguous_range", "flatten")
_alias("top_k_v2", "top_k", num_outputs=2)
_alias("lookup_table_v2", "lookup_table")
_alias("elementwise_minus", "elementwise_sub")
_alias("minus", "elementwise_sub")


@register_op("space_to_depth")
def space_to_depth(x, *, blocksize):
    """operators/space_to_depth_op.cc — pixel_unshuffle under the
    reference attr name."""
    return get_op("pixel_unshuffle").fn(x, downscale_factor=blocksize)


@register_op("shuffle_channel")
def shuffle_channel(x, *, group=None, groups=None):
    """operators/shuffle_channel_op.cc — channel_shuffle under the
    reference attr name (``group``)."""
    return get_op("channel_shuffle").fn(
        x, groups=group if group is not None else groups
    )


@register_op("fill_constant_batch_size_like")
def fill_constant_batch_size_like(x, *, shape, value=0.0, dtype="float32",
                                  input_dim_idx=0, output_dim_idx=0):
    """operators/fill_constant_batch_size_like_op.cc: constant tensor of
    ``shape`` with dim ``output_dim_idx`` taken from the input's dim
    ``input_dim_idx``."""
    from ..framework.dtype import convert_dtype

    out_shape = list(shape)
    out_shape[output_dim_idx] = x.shape[input_dim_idx]
    return jnp.full(out_shape, value, convert_dtype(dtype))


@register_op("tril_triu")
def tril_triu(x, *, diagonal=0, lower=True):
    """operators/tril_triu_op.cc: one op, attr-selected variant."""
    return (jnp.tril if lower else jnp.triu)(x, k=diagonal)


# -- interpolation family (interpolate_op.cc registers one op per mode) ------


def _interp_mode(mode):
    def fn(x, *, out_h=None, out_w=None, out_d=None, scale=None,
           align_corners=False, align_mode=1, data_format="NCHW"):
        k = get_op("interpolate").fn
        size = None
        if out_h is not None and out_w is not None:
            size = ([out_d, out_h, out_w] if out_d is not None
                    else [out_h, out_w])
        return k(x, size=size, scale_factor=scale, mode=mode,
                 align_corners=align_corners, data_format=data_format)
    fn.__name__ = f"{mode}_interp"
    return fn


for _mode in ("nearest", "bilinear", "trilinear", "bicubic", "linear"):
    register_op(f"{_mode}_interp")(_interp_mode(_mode))


# -- pooling with indices -----------------------------------------------------


@register_op("max_pool2d_with_index", num_outputs=2)
def max_pool2d_with_index(x, *, kernel_size, stride=None, padding=0,
                          global_pooling=False, adaptive=False):
    """operators/pool_with_index_op.cc: max pool + flat argmax indices."""
    n, c, h, w = x.shape
    ks = (kernel_size if isinstance(kernel_size, (list, tuple))
          else (kernel_size, kernel_size))
    st = (stride if isinstance(stride, (list, tuple))
          else (stride, stride)) if stride is not None else ks
    p = padding if isinstance(padding, (list, tuple)) else (padding, padding)
    if global_pooling:
        ks, st, p = (h, w), (1, 1), (0, 0)
    # Index carrier is int32 regardless of x.dtype: bf16/f16 cannot
    # represent integers above ~256 (and f32 breaks past 2**24), which
    # silently corrupts the argmax plane.
    flat_idx = jnp.arange(h * w, dtype=jnp.int32).reshape(1, 1, h, w)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)
    neg = jnp.finfo(x.dtype).min

    def sel(acc, cur):
        av, ai = acc
        cv, ci = cur
        take = cv > av
        return jnp.where(take, cv, av), jnp.where(take, ci, ai)

    window = (1, 1, ks[0], ks[1])
    strides = (1, 1, st[0], st[1])
    pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    out, idx = lax.reduce_window(
        (x, flat_idx),
        (jnp.asarray(neg, x.dtype), jnp.asarray(-1, jnp.int32)),
        sel, window, strides, pads,
    )
    return out, idx


@register_op("unpool")
def unpool(x, indices, *, output_size):
    """operators/unpool_op.cc: scatter pooled values back to the flat
    positions recorded by max_pool2d_with_index."""
    n, c, h, w = x.shape
    oh, ow = int(output_size[0]), int(output_size[1])
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    idx = indices.reshape(n, c, -1).astype(jnp.int32)
    vals = x.reshape(n, c, -1)
    flat = jax.vmap(jax.vmap(lambda f, i, v: f.at[i].set(v)))(flat, idx, vals)
    return flat.reshape(n, c, oh, ow)


# -- small math/vision tail ---------------------------------------------------


@register_op("squared_l2_norm")
def squared_l2_norm(x):
    return jnp.sum(jnp.square(x))


@register_op("squared_l2_distance", num_outputs=2)
def squared_l2_distance(x, y):
    sub = x - y
    return sub, jnp.sum(jnp.square(sub), axis=tuple(range(1, x.ndim)))


@register_op("pad_constant_like")
def pad_constant_like(x, y, *, pad_value=0.0):
    """Pad y up to x's shape with pad_value."""
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return jnp.pad(y, pads, constant_values=pad_value)


@register_op("lrn", num_outputs=2)
def lrn(x, *, n=5, k=1.0, alpha=1e-4, beta=0.75):
    """operators/lrn_op.cc: local response normalization over channels.
    Returns (out, mid) — mid is the normalization denominator base."""
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, n - 1 - half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return x / jnp.power(mid, beta), mid


@register_op("temporal_shift")
def temporal_shift(x, *, seg_num, shift_ratio=0.25):
    """operators/temporal_shift_op.cc (TSM): shift channel slices across
    the time dimension of [N*T, C, H, W]."""
    nt, c, h, w = x.shape
    t = int(seg_num)
    b = nt // t
    v = x.reshape(b, t, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    fwd = jnp.concatenate(
        [jnp.zeros_like(v[:, :1, :c1]), v[:, :-1, :c1]], axis=1
    )
    bwd = jnp.concatenate(
        [v[:, 1:, c1:c2], jnp.zeros_like(v[:, :1, c1:c2])], axis=1
    )
    return jnp.concatenate([fwd, bwd, v[:, :, c2:]], axis=2).reshape(x.shape)


@register_op("cos_sim")
def cos_sim(x, y):
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    sim = jnp.sum(x * y, axis=-1, keepdims=True) / jnp.maximum(
        xn * yn, 1e-12
    )
    return sim


@register_op("rank_loss")
def rank_loss(label, left, right):
    """operators/rank_loss_op.cc: RankNet pairwise loss."""
    d = left - right
    return jnp.log1p(jnp.exp(d)) - label * d


@register_op("margin_rank_loss", num_outputs=2)
def margin_rank_loss(label, left, right, *, margin=0.0):
    out = jnp.maximum(0.0, -label * (left - right) + margin)
    act = (out > 0).astype(left.dtype)
    return out, act


@register_op("bpr_loss")
def bpr_loss(x, label):
    """operators/bpr_loss_op.cc: Bayesian personalized ranking over
    logits [N, C] with positive-class labels [N, 1]."""
    n, c = x.shape
    lbl = label.reshape(-1)
    pos = jnp.take_along_axis(x, lbl[:, None], axis=1)
    diff = pos - x  # [N, C]
    mask = jnp.arange(c)[None, :] != lbl[:, None]
    losses = -jnp.log(jax.nn.sigmoid(diff)) * mask
    return jnp.sum(losses, axis=1, keepdims=True) / (c - 1)


@register_op("center_loss", num_outputs=3)
def center_loss(x, label, centers, *, alpha=0.1, update=True):
    """operators/center_loss_op.cc: intra-class compactness loss.
    Returns (loss [N,1], diff, new_centers)."""
    ctr = centers[label.reshape(-1)]
    diff = x - ctr
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    if update:
        cnt = jnp.zeros(centers.shape[0], x.dtype).at[label.reshape(-1)].add(1.0)
        upd = jnp.zeros_like(centers).at[label.reshape(-1)].add(diff)
        new_centers = centers + alpha * upd / (1.0 + cnt)[:, None]
    else:
        new_centers = centers
    return loss, diff, new_centers


@register_op("conv_shift")
def conv_shift(x, y):
    """operators/conv_shift_op.cc: circular correlation of [B, N] with
    [B, M] (M odd, M <= N)."""
    b, n_len = x.shape
    m = y.shape[1]
    half = m // 2
    idx = (jnp.arange(n_len)[:, None] + jnp.arange(-half, half + 1)[None, :]
           ) % n_len
    return jnp.einsum("bnm,bm->bn", x[:, idx], y)


@register_op("partial_concat")
def partial_concat(xs, *, start_index=0, length=-1):
    parts = []
    for x in xs:
        end = x.shape[1] if length == -1 else start_index + length
        parts.append(x[:, start_index:end])
    return jnp.concatenate(parts, axis=1)


@register_op("partial_sum")
def partial_sum(xs, *, start_index=0, length=-1):
    out = None
    for x in xs:
        end = x.shape[1] if length == -1 else start_index + length
        s = x[:, start_index:end]
        out = s if out is None else out + s
    return out


@register_op("shuffle_batch", num_outputs=2)
def shuffle_batch(x, *, key):
    perm = jax.random.permutation(key, x.shape[0])
    return x[perm], perm.astype(jnp.int64)


@register_op("sequence_reshape")
def sequence_reshape(x, *, new_dim):
    """sequence_ops/sequence_reshape_op.cc on the flat representation."""
    return x.reshape(-1, int(new_dim))


@register_op("sequence_scatter")
def sequence_scatter(x, index, updates):
    """sequence_ops/sequence_scatter_op.cc (flat segments design):
    add updates at flat row indices."""
    return x.at[index.reshape(-1)].add(updates)


@register_op("spectral_norm")
def spectral_norm(weight, u, v, *, dim=0, power_iters=1, eps=1e-12):
    """operators/spectral_norm_op.cc: normalize weight by its largest
    singular value (power iteration on the given u/v vectors)."""
    w = jnp.moveaxis(weight, dim, 0)
    h = w.shape[0]
    mat = w.reshape(h, -1)
    for _ in range(max(int(power_iters), 0)):
        v = mat.T @ u
        v = v / jnp.maximum(jnp.linalg.norm(v), eps)
        u = mat @ v
        u = u / jnp.maximum(jnp.linalg.norm(u), eps)
    sigma = u @ mat @ v
    return jnp.moveaxis((mat / sigma).reshape(w.shape), 0, dim)


@register_op("row_conv")
def row_conv(x, w):
    """operators/row_conv_op.cc: lookahead row convolution over
    [B, T, D] with filter [future_context + 1, D]."""
    ctx = w.shape[0]
    b, t, d = x.shape
    pad = jnp.pad(x, ((0, 0), (0, ctx - 1), (0, 0)))
    return sum(pad[:, i:i + t] * w[i][None, None, :] for i in range(ctx))


@register_op("affine_channel")
def affine_channel(x, scale, bias, *, data_format="NCHW"):
    shape = [1, -1] + [1] * (x.ndim - 2) if data_format == "NCHW" else (
        [1] * (x.ndim - 1) + [-1]
    )
    return x * scale.reshape(shape) + bias.reshape(shape)


@register_op("print")
def print_op(x, *, message="", summarize=20, first_n=-1):
    """operators/print_op.cc via the host-callback print path; identity
    on the value (XLA keeps the data flowing)."""
    jax.debug.print(message + " {}", x)
    return x


@register_op("py_func")
def py_func(*args, func, out_shapes, out_dtypes):
    """operators/py_func_op.cc: run a python callable as an op, via
    jax.pure_callback (works eagerly and under jit)."""
    dts = [jnp.dtype(d) for d in out_dtypes]
    spec = [
        jax.ShapeDtypeStruct(tuple(s), d) for s, d in zip(out_shapes, dts)
    ]

    def wrapped(*a):
        out = func(*a)
        outs = out if isinstance(out, (tuple, list)) else [out]
        cast = tuple(
            np.asarray(o, dtype=d) for o, d in zip(outs, dts)
        )
        return cast if len(cast) > 1 else cast[0]

    if len(spec) == 1:
        spec = spec[0]
    return jax.pure_callback(wrapped, spec, *args, vmap_method="sequential")


@register_op("shard_index_ref")
def shard_index_ref(x, *, index_num, nshards, shard_id, ignore_value=-1):
    """operators/shard_index_op.cc semantics under its reference name."""
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return jnp.where(in_shard, x % shard_size, ignore_value)


# -- round-3 batch 2: remaining reference tail --------------------------------

_alias("expand_v2", "expand")
_alias("expand_as_v2", "expand_as")
_alias("grid_sampler", "grid_sample")
_alias("cross_entropy2", "cross_entropy")
_alias("kldiv_loss", "kl_div")


@register_op("deformable_conv_v1")
def deformable_conv_v1(x, offset, weight, **kw):
    """deformable_conv_v1_op.cc: the unmodulated variant (no mask)."""
    return get_op("deformable_conv").fn(x, offset, None, weight, **kw)


@register_op("depthwise_conv2d_transpose")
def depthwise_conv2d_transpose(x, w, *, stride=1, padding=0,
                               output_padding=0, dilation=1,
                               data_format="NCHW"):
    channels = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    return get_op("conv2d_transpose").fn(
        x, w, stride=stride, padding=padding,
        output_padding=output_padding, dilation=dilation,
        groups=channels, data_format=data_format,
    )


@register_op("frobenius_norm")
def frobenius_norm(x, *, axis=None, keepdim=False):
    axes = tuple(axis) if axis is not None else None
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=keepdim))


@register_op("l1_norm")
def l1_norm(x):
    return jnp.sum(jnp.abs(x))


@register_op("huber_loss", num_outputs=2)
def huber_loss(x, y, *, delta=1.0):
    """operators/huber_loss_op.cc: returns (out, residual)."""
    r = y - x
    a = jnp.abs(r)
    out = jnp.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))
    return out, r


@register_op("crop_tensor")
def crop_tensor(x, *, shape, offsets=None):
    """operators/crop_tensor_op.cc: static-window crop."""
    off = list(offsets) if offsets is not None else [0] * x.ndim
    idx = tuple(
        slice(o, o + s) for o, s in zip(off, shape)
    )
    return x[idx]


_alias("crop", "crop_tensor")


@register_op("gather_tree")
def gather_tree(ids, parents):
    """operators/gather_tree_op.cc: beam-search backtracking.
    ids/parents [T, B, W] -> full sequences [T, B, W]."""
    t, b, w = ids.shape

    def step(beams, tp):
        step_ids, step_parents = tp
        new = jnp.take_along_axis(step_ids, beams, axis=1)
        parent = jnp.take_along_axis(step_parents, beams, axis=1)
        return parent, new

    init = jnp.broadcast_to(jnp.arange(w, dtype=parents.dtype), (b, w))
    _, out_rev = lax.scan(step, init, (ids[::-1], parents[::-1]))
    return out_rev[::-1]


@register_op("im2sequence")
def im2sequence(x, *, kernels, strides=(1, 1), paddings=(0, 0, 0, 0),
                dilations=(1, 1)):
    """operators/im2sequence_op.cc on the dense design: [N,C,H,W] ->
    [N, out_h*out_w, C*kh*kw] patch rows. Also the im2col core behind
    nn.Unfold (paddings are (top, left, bottom, right))."""
    kh, kw = kernels
    n, c, h, w = x.shape
    ph0, pw0, ph1, pw1 = paddings
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)))
    patches = lax.conv_general_dilated_patches(
        xp, (kh, kw), tuple(strides), "VALID",
        rhs_dilation=tuple(dilations),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, oh, ow]
    ckk = patches.shape[1]
    return jnp.transpose(
        patches.reshape(n, ckk, -1), (0, 2, 1)
    )


@register_op("fsp")
def fsp(x, y):
    """operators/fsp_op.cc: flow-of-solution-procedure matrix (knowledge
    distillation): [N,C1,H,W] x [N,C2,H,W] -> [N,C1,C2]."""
    n, c1, h, w = x.shape
    return jnp.einsum("nahw,nbhw->nab", x, y) / (h * w)


@register_op("cvm", num_outputs=1)
def cvm(x, cvm_in, *, use_cvm=True):
    """operators/cvm_op.cc: show/click feature handling — with use_cvm
    the first two columns are log-transformed, else dropped."""
    show = jnp.log(x[:, 0:1] + 1.0)
    click = jnp.log(x[:, 1:2] + 1.0) - jnp.log(x[:, 0:1] + 1.0)
    if use_cvm:
        return jnp.concatenate([show, click, x[:, 2:]], axis=1)
    return x[:, 2:]


@register_op("batch_fc")
def batch_fc(x, w, bias=None):
    """operators/batch_fc_op.cc: per-slot fc — [S,B,D] @ [S,D,O] + [S,1,O]."""
    out = jnp.einsum("sbd,sdo->sbo", x, w)
    if bias is not None:
        out = out + bias
    return out


@register_op("gru_unit", num_outputs=3)
def gru_unit(x, h_prev, weight, bias=None, *,
             activation="tanh", gate_activation="sigmoid"):
    """operators/gru_unit_op.cc: one GRU step. x [B,3D] (pre-projected),
    weight [D, 3D] (update|reset | candidate). Returns (h, reset_h, gates)."""
    b, d3 = x.shape
    d = d3 // 3
    act = ((lambda v: v) if activation == "identity"
           else getattr(jax.nn, activation))
    gate = ((lambda v: v) if gate_activation == "identity"
            else getattr(jax.nn, gate_activation))
    xs = x + (bias if bias is not None else 0.0)
    g_uz = gate(xs[:, :2 * d] + h_prev @ weight[:, :2 * d])
    u, r = g_uz[:, :d], g_uz[:, d:]
    rh = r * h_prev
    c = act(xs[:, 2 * d:] + rh @ weight[:, 2 * d:])
    h = u * h_prev + (1.0 - u) * c
    return h, rh, jnp.concatenate([g_uz, c], axis=1)


@register_op("lstm_unit", num_outputs=2)
def lstm_unit(x, c_prev, *, forget_bias=0.0):
    """operators/lstm_unit_op.cc: one LSTM cell step over pre-projected
    gates x [B, 4D]. Returns (c, h)."""
    b, d4 = x.shape
    d = d4 // 4
    i, f, o, g = (x[:, k * d:(k + 1) * d] for k in range(4))
    c = c_prev * jax.nn.sigmoid(f + forget_bias) + \
        jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return c, h


@register_op("lstmp", num_outputs=2)
def lstmp(x, w_proj, *, hidden_size):
    """operators/lstmp_op.cc capability: LSTM with a projection of the
    hidden state. x [T,B,4H] pre-projected gates; returns projected
    outputs [T,B,P] and final cell [B,H]."""
    t, b, h4 = x.shape
    h = int(hidden_size)

    def step(carry, xt):
        c_prev = carry
        c, hh = get_op("lstm_unit").fn(xt, c_prev)
        return c, hh @ w_proj

    c0 = jnp.zeros((b, h), x.dtype)
    c_final, ys = lax.scan(step, c0, x)
    return ys, c_final


@register_op("max_pool3d_with_index", num_outputs=2)
def max_pool3d_with_index(x, *, kernel_size, stride=None, padding=0):
    """pool_with_index_op.cc 3D path."""
    n, c, d, h, w = x.shape
    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    # int32 index carrier (see max_pool2d_with_index: bf16/f32 overflow)
    flat = jnp.arange(d * h * w, dtype=jnp.int32).reshape(1, 1, d, h, w)
    flat = jnp.broadcast_to(flat, x.shape)
    neg = jnp.finfo(x.dtype).min

    def sel(acc, cur):
        av, ai = acc
        cv, ci = cur
        take = cv > av
        return jnp.where(take, cv, av), jnp.where(take, ci, ai)

    out, idx = lax.reduce_window(
        (x, flat), (jnp.asarray(neg, x.dtype), jnp.asarray(-1, jnp.int32)),
        sel, (1, 1) + ks, (1, 1) + st,
        ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p),
    )
    return out, idx


@register_op("mean_iou", num_outputs=3)
def mean_iou(predictions, labels, *, num_classes):
    """operators/mean_iou_op.cc: mean intersection-over-union.
    Returns (mean_iou, out_wrong, out_correct)."""
    p = predictions.reshape(-1)
    l = labels.reshape(-1)
    k = int(num_classes)
    correct = jnp.zeros(k, jnp.int64).at[l].add(
        (p == l).astype(jnp.int64), mode="drop")
    pred_cnt = jnp.zeros(k, jnp.int64).at[p].add(1, mode="drop")
    label_cnt = jnp.zeros(k, jnp.int64).at[l].add(1, mode="drop")
    union = pred_cnt + label_cnt - correct
    valid = union > 0
    iou = jnp.where(valid, correct / jnp.maximum(union, 1), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
    return miou, (label_cnt - correct).astype(jnp.int32), \
        correct.astype(jnp.int32)


@register_op("linear_chain_crf", num_outputs=4)
def linear_chain_crf(emission, transition, label):
    """operators/linear_chain_crf_op.cc on the dense [B,T,C] design:
    negative log-likelihood of the label path under a linear-chain CRF.
    transition [C+2, C]: row 0 start, row 1 stop, rows 2.. pairwise.
    Returns (alpha [B,T,C], emission_exps, transition_exps, log_likelihood
    [B,1] as the nll)."""
    b, t, c = emission.shape
    start = transition[0]
    stop = transition[1]
    trans = transition[2:]

    def fwd(alpha, e_t):
        nxt = jax.nn.logsumexp(
            alpha[:, :, None] + trans[None, :, :], axis=1
        ) + e_t
        return nxt, nxt

    a0 = start[None, :] + emission[:, 0]
    alpha_f, alphas = lax.scan(
        fwd, a0, jnp.moveaxis(emission[:, 1:], 1, 0)
    )
    alphas = jnp.concatenate(
        [a0[None], alphas], axis=0
    )  # [T,B,C]
    logz = jax.nn.logsumexp(alpha_f + stop[None, :], axis=1)  # [B]

    # score of the gold path
    lbl = label.reshape(b, t)
    e_score = jnp.take_along_axis(
        emission, lbl[:, :, None], axis=2
    )[..., 0].sum(axis=1)
    tr_score = trans[lbl[:, :-1], lbl[:, 1:]].sum(axis=1) if t > 1 else 0.0
    path = e_score + tr_score + start[lbl[:, 0]] + stop[lbl[:, -1]]
    nll = (logz - path)[:, None]
    return (jnp.moveaxis(alphas, 0, 1), jnp.exp(emission),
            jnp.exp(transition), nll)


@register_op("nce")
def nce(x, weight, bias, label, sample_ids, *, num_total_classes,
        num_neg_samples):
    """operators/nce_op.cc capability: noise-contrastive estimation loss
    with caller-provided negative samples (static-shape contract; the
    reference samples internally). x [B,D]; weight [C,D]; label [B];
    sample_ids [B,S] negatives."""
    true_logit = jnp.sum(x * weight[label], axis=1) + (
        bias[label] if bias is not None else 0.0
    )
    neg_w = weight[sample_ids]  # [B,S,D]
    neg_logit = jnp.einsum("bd,bsd->bs", x, neg_w) + (
        bias[sample_ids] if bias is not None else 0.0
    )
    pos_loss = -jax.nn.log_sigmoid(true_logit)
    neg_loss = -jnp.sum(jax.nn.log_sigmoid(-neg_logit), axis=1)
    return (pos_loss + neg_loss)[:, None]


@register_op("sample_logits", num_outputs=4)
def sample_logits(logits, labels, *, key, num_samples, use_customized_samples=False,
                  customized_samples=None, customized_probabilities=None,
                  remove_accidental_hits=True, seed=0):
    """operators/sample_logits_op.cc: sampled-softmax preparation — keep
    the true-label logits plus ``num_samples`` uniformly sampled negative
    classes, with log-probability correction and accidental-hit removal.

    logits [B, C]; labels [B, T] (T true labels per row). Returns
    (samples [B, T+S], probabilities [B, T+S], sampled_logits [B, T+S],
    sampled_labels [B, T]) — labels remapped to positions 0..T-1, the
    fixed-size contract the reference's LoD-free path uses.
    """
    b, c = logits.shape
    t = labels.shape[1]
    s = int(num_samples)
    if use_customized_samples:
        samples = customized_samples
        probs = customized_probabilities
    else:
        neg = jax.random.randint(key, (b, s), 0, c)
        samples = jnp.concatenate([labels.astype(neg.dtype), neg], axis=1)
        # uniform proposal: q = 1/C for every sampled class
        probs = jnp.full((b, t + s), 1.0 / c, logits.dtype)
    picked = jnp.take_along_axis(logits, samples, axis=1)
    # subtract log(q) (sampled-softmax correction)
    sampled_logits = picked - jnp.log(jnp.maximum(probs, 1e-20))
    if remove_accidental_hits:
        # a negative that equals a true label would double-count: mask it
        hit = jnp.any(
            samples[:, None, t:] == labels[:, :, None], axis=1
        )  # [B, S]
        mask = jnp.concatenate(
            [jnp.zeros((b, t), bool), hit], axis=1
        )
        sampled_logits = jnp.where(mask, sampled_logits - 1e20,
                                   sampled_logits)
    sampled_labels = jnp.broadcast_to(jnp.arange(t), (b, t))
    return samples, probs, sampled_logits, sampled_labels


@register_op("filter_by_instag", num_outputs=3, eager_only=True)
def filter_by_instag(x, instags, filter_tags, *, is_lod=True,
                     out_val_if_empty=0.0):
    """operators/filter_by_instag_op.cc: keep rows whose instance tags
    intersect the filter set. Output row count is data-dependent —
    eager-only (same contract as masked_select). Returns
    (out, loss_weight [kept, 1], kept_index)."""
    xs = np.asarray(x)
    tags = np.asarray(instags)
    fset = set(np.asarray(filter_tags).reshape(-1).tolist())
    keep = np.array([
        bool(fset.intersection(row.reshape(-1).tolist()))
        for row in tags
    ])
    idx = np.nonzero(keep)[0]
    if idx.size == 0:
        out = np.full((1,) + xs.shape[1:], out_val_if_empty, xs.dtype)
        return (jnp.asarray(out), jnp.zeros((1, 1), xs.dtype),
                jnp.asarray(np.zeros(1, np.int64)))
    return (jnp.asarray(xs[idx]),
            jnp.ones((idx.size, 1), xs.dtype),
            jnp.asarray(idx.astype(np.int64)))
