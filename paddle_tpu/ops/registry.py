"""Op registry.

Reference parity: paddle/fluid/framework/op_registry.h (REGISTER_OPERATOR /
REGISTER_OP_*_KERNEL) + op_info.h OpInfoMap. TPU-native design: a "kernel"
is a pure JAX function `fn(*arrays, **attrs) -> array | tuple` — place/dtype
dispatch collapses because XLA compiles one kernel for every place/dtype;
there is exactly one registry keyed by op type. Gradient kernels are never
registered by hand: the executor and eager tracer derive them via jax.vjp
(see framework/autograd.py, static/backward.py).
"""
from __future__ import annotations

from typing import Callable, Dict, NamedTuple


class OpDef(NamedTuple):
    name: str
    fn: Callable
    num_outputs: int  # -1 = variadic (depends on attrs)


_REGISTRY: Dict[str, OpDef] = {}


def register_op(name: str, num_outputs: int = 1, eager_only: bool = False):
    """Decorator: register a pure-JAX kernel under a fluid op type name.

    ``eager_only`` marks kernels whose output shape depends on data
    (unique/nonzero/masked_select and the maxlen=None sequence forms) —
    they cannot live inside a compiled XLA block, and the static graph
    builder rejects them at append time (op_append.py) instead of
    letting whole-block jit fail with an opaque trace error.
    """

    def deco(fn):
        if name in _REGISTRY:
            raise ValueError(f"op {name!r} registered twice")
        _REGISTRY[name] = OpDef(name, fn, num_outputs)
        if eager_only:
            EAGER_ONLY_OPS.add(name)
        return fn

    return deco


# ops with data-dependent output shapes: forbidden in static programs
EAGER_ONLY_OPS: set[str] = set()


def get_op(name: str) -> OpDef:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise NotImplementedError(f"op {name!r} has no TPU kernel") from None


def kernel(name: str) -> Callable:
    return get_op(name).fn


def has_op(name: str) -> bool:
    return name in _REGISTRY


def all_ops():
    return dict(_REGISTRY)
