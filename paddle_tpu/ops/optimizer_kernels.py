"""Optimizer update op kernels.

Reference parity: paddle/fluid/operators/optimizers/ — the 17 update
kernels (sgd_op, momentum_op + lars_momentum_op, adam_op, adamax_op,
adagrad_op, adadelta_op, rmsprop_op, ftrl_op, lamb_op, dpsgd_op, proximal
ops). sgd/momentum/adam already live in kernels.py; this module adds the
rest as pure update rules: (param, grad, accumulators, lr) -> new values.
The Python optimizer classes (optimizer/__init__.py) are the user surface;
these ops exist so static programs and custom loops can apply the same
math as single fused XLA kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


@register_op("adamax_update", num_outputs=3)
def adamax_update(param, grad, moment, inf_norm, lr, step, *, beta1=0.9,
                  beta2=0.999, epsilon=1e-8):
    """optimizers/adamax_op.cc."""
    m = beta1 * moment + (1 - beta1) * grad
    u = jnp.maximum(beta2 * inf_norm, jnp.abs(grad))
    t = step.astype(param.dtype)
    new_p = param - lr / (1 - beta1**t) * m / (u + epsilon)
    return new_p, m, u


@register_op("adagrad_update", num_outputs=2)
def adagrad_update(param, grad, moment, lr, *, epsilon=1e-6):
    """optimizers/adagrad_op.cc."""
    g2 = moment + grad * grad
    new_p = param - lr * grad / (jnp.sqrt(g2) + epsilon)
    return new_p, g2


@register_op("adadelta_update", num_outputs=3)
def adadelta_update(param, grad, avg_squared_grad, avg_squared_update, lr,
                    *, rho=0.95, epsilon=1e-6):
    """optimizers/adadelta_op.cc."""
    g2 = rho * avg_squared_grad + (1 - rho) * grad * grad
    update = -jnp.sqrt((avg_squared_update + epsilon) / (g2 + epsilon)) * grad
    u2 = rho * avg_squared_update + (1 - rho) * update * update
    return param + lr * update, g2, u2


@register_op("rmsprop_update", num_outputs=3)
def rmsprop_update(param, grad, mean_square, moment, lr, *, rho=0.95,
                   epsilon=1e-6, momentum=0.0, centered=False,
                   mean_grad=None):
    """optimizers/rmsprop_op.cc (uncentered form)."""
    ms = rho * mean_square + (1 - rho) * grad * grad
    mom = momentum * moment + lr * grad / jnp.sqrt(ms + epsilon)
    return param - mom, ms, mom


@register_op("ftrl_update", num_outputs=3)
def ftrl_update(param, grad, squared_accum, linear_accum, lr, *, l1=0.0,
                l2=0.0, lr_power=-0.5):
    """optimizers/ftrl_op.cc."""
    new_sq = squared_accum + grad * grad
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(squared_accum)) / lr
    else:
        sigma = (new_sq ** (-lr_power) - squared_accum ** (-lr_power)) / lr
    new_lin = linear_accum + grad - sigma * param
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = new_sq ** (-lr_power) / lr + 2 * l2
    return pre / denom, new_sq, new_lin


@register_op("lamb_update", num_outputs=3)
def lamb_update(param, grad, moment1, moment2, lr, step, *, beta1=0.9,
                beta2=0.999, epsilon=1e-6, weight_decay=0.01):
    """optimizers/lamb_op.cc: layer-adaptive moment scaling."""
    m = beta1 * moment1 + (1 - beta1) * grad
    v = beta2 * moment2 + (1 - beta2) * grad * grad
    t = step.astype(param.dtype)
    mhat = m / (1 - beta1**t)
    vhat = v / (1 - beta2**t)
    r = mhat / (jnp.sqrt(vhat) + epsilon) + weight_decay * param
    w_norm = jnp.linalg.norm(param)
    r_norm = jnp.linalg.norm(r)
    ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    return param - lr * ratio * r, m, v


@register_op("lars_momentum_update", num_outputs=2)
def lars_momentum_update(param, grad, velocity, lr, *, mu=0.9,
                         lars_coeff=0.001, lars_weight_decay=0.0005,
                         epsilon=0.0):
    """optimizers/lars_momentum_op.cc: layer-wise adaptive rate scaling."""
    w_norm = jnp.linalg.norm(param)
    g_norm = jnp.linalg.norm(grad)
    local_lr = jnp.where(
        (w_norm > 0) & (g_norm > 0),
        lars_coeff * w_norm
        / (g_norm + lars_weight_decay * w_norm + epsilon),
        1.0,
    )
    v = mu * velocity + lr * local_lr * (grad + lars_weight_decay * param)
    return param - v, v


@register_op("proximal_gd_update")
def proximal_gd_update(param, grad, lr, *, l1=0.0, l2=0.0):
    """optimizers/proximal_gd_op.cc: prox step of l1/l2-regularized GD."""
    prox = param - lr * grad
    if l1 > 0:
        shrink = jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
        return jnp.sign(prox) * shrink / (1.0 + lr * l2)
    return prox / (1.0 + lr * l2)


@register_op("proximal_adagrad_update", num_outputs=2)
def proximal_adagrad_update(param, grad, moment, lr, *, l1=0.0, l2=0.0):
    """optimizers/proximal_adagrad_op.cc."""
    g2 = moment + grad * grad
    adapted_lr = lr / jnp.sqrt(g2)
    prox = param - adapted_lr * grad
    if l1 > 0:
        shrink = jnp.maximum(jnp.abs(prox) - adapted_lr * l1, 0.0)
        return jnp.sign(prox) * shrink / (1.0 + adapted_lr * l2), g2
    return prox / (1.0 + adapted_lr * l2), g2


@register_op("dpsgd_update")
def dpsgd_update(param, grad, lr, *, clip=10.0, batch_size=16.0,
                 sigma=1.0, key=None):
    """optimizers/dpsgd_op.cc: differentially-private SGD — clip the grad
    norm and add calibrated Gaussian noise."""
    import jax

    g_norm = jnp.linalg.norm(grad)
    scale = jnp.minimum(1.0, clip / jnp.maximum(g_norm, 1e-12))
    noise = sigma * clip * jax.random.normal(key, grad.shape, grad.dtype)
    return param - lr * (grad * scale + noise) / batch_size
