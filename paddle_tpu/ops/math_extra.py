"""Additional math/statistics/search op kernels.

Reference parity: scattered across paddle/fluid/operators/ (e.g.
histogram, bincount-like counting, searchsorted in later forks, isclose,
lerp) and python/paddle/tensor/{math,stat,search,logic}.py. Direct jnp
lowerings; ops whose OUTPUT SHAPE depends on data (unique, nonzero,
masked_select) follow the eager-only contract with a clear error under
tracing — the TPU-native alternative is the masked/padded form.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .registry import register_op


def _eager_only(name, *arrays):
    if any(isinstance(a, jax.core.Tracer) for a in arrays):
        raise NotImplementedError(
            f"{name} has a data-dependent output shape; call it eagerly or "
            "use the masked/padded equivalent under jit"
        )


# -- statistics --------------------------------------------------------------


@register_op("std")
def std(x, *, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


@register_op("var")
def var(x, *, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


@register_op("median")
def median(x, *, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


@register_op("nanmedian")
def nanmedian(x, *, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


@register_op("quantile")
def quantile(x, *, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim,
                        method=interpolation)


@register_op("mode", num_outputs=2)
def mode(x, *, axis=-1, keepdim=False):
    """Most frequent value along axis (+ its index)."""
    def mode1d(v):
        vals, _, counts = jnp.unique(
            v, return_inverse=True, return_counts=True, size=v.shape[0]
        )
        m = vals[jnp.argmax(counts)]
        idx = jnp.max(jnp.where(v == m, jnp.arange(v.shape[0]), -1))
        return m, idx

    moved = jnp.moveaxis(x, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    m, i = jax.vmap(mode1d)(flat)
    out_shape = moved.shape[:-1]
    m = m.reshape(out_shape)
    i = i.reshape(out_shape)
    if keepdim:
        m = jnp.expand_dims(m, axis)
        i = jnp.expand_dims(i, axis)
    return m, i


@register_op("histogram")
def histogram(x, *, bins=100, min=0, max=0, weight=None, density=False):
    lo, hi = float(min), float(max)
    if lo == 0 and hi == 0:
        lo, hi = None, None
    h, _ = jnp.histogram(
        x.reshape(-1), bins=int(bins),
        range=None if lo is None else (lo, hi), weights=weight,
        density=density,
    )
    return h


@register_op("bincount")
def bincount(x, *, weights=None, minlength=0, length=None):
    """length (static) overrides data-dependent sizing so the op jits."""
    if length is None:
        _eager_only("bincount (without static length=)", x)
        length = max(int(jnp.max(x)) + 1 if x.size else 0, int(minlength))
    return jnp.bincount(x.reshape(-1), weights=weights, length=int(length))


@register_op("nansum")
def nansum(x, *, axis=None, keepdim=False):
    return jnp.nansum(x, axis=axis, keepdims=keepdim)


@register_op("nanmean")
def nanmean(x, *, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


# -- search / comparison -----------------------------------------------------


@register_op("searchsorted")
def searchsorted(sorted_sequence, values, *, out_int32=False, right=False):
    out = jnp.searchsorted(sorted_sequence, values,
                           side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@register_op("unique", num_outputs=4, eager_only=True)
def unique(x, *, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    """Eager-only (data-dependent size); returns (out, index, inverse,
    counts) — callers slice what they asked for."""
    _eager_only("unique", x)
    out, index, inverse, counts = np.unique(
        np.asarray(x), return_index=True, return_inverse=True,
        return_counts=True, axis=axis,
    )
    return (jnp.asarray(out), jnp.asarray(index), jnp.asarray(inverse),
            jnp.asarray(counts))


@register_op("unique_consecutive", num_outputs=3, eager_only=True)
def unique_consecutive(x, *, return_inverse=False, return_counts=False,
                       axis=None):
    _eager_only("unique_consecutive", x)
    xs = np.asarray(x).reshape(-1) if axis is None else np.asarray(x)
    keep = np.ones(xs.shape[0], bool)
    keep[1:] = np.any(
        xs[1:].reshape(xs.shape[0] - 1, -1)
        != xs[:-1].reshape(xs.shape[0] - 1, -1), axis=1
    ) if xs.ndim > 1 else xs[1:] != xs[:-1]
    out = xs[keep]
    grp = np.cumsum(keep) - 1
    counts = np.bincount(grp)
    return jnp.asarray(out), jnp.asarray(grp), jnp.asarray(counts)


@register_op("masked_select", eager_only=True)
def masked_select(x, mask):
    _eager_only("masked_select", x, mask)
    return jnp.asarray(np.asarray(x)[np.asarray(mask)])


@register_op("nonzero", eager_only=True)
def nonzero(x, *, as_tuple=False):
    _eager_only("nonzero", x)
    nz = np.nonzero(np.asarray(x))
    if as_tuple:
        return tuple(jnp.asarray(i) for i in nz)
    return jnp.asarray(np.stack(nz, axis=1))


@register_op("allclose")
def allclose(x, y, *, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register_op("isclose")
def isclose(x, y, *, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register_op("equal_all")
def equal_all(x, y):
    return jnp.array_equal(x, y)


# -- pointwise extras --------------------------------------------------------


@register_op("lerp")
def lerp(x, y, weight):
    return x + weight * (y - x)


@register_op("logit")
def logit(x, *, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1 - eps)
    return jnp.log(x / (1 - x))


@register_op("logaddexp")
def logaddexp(x, y):
    return jnp.logaddexp(x, y)


@register_op("heaviside")
def heaviside(x, y):
    return jnp.heaviside(x, y)


@register_op("frac")
def frac(x):
    return x - jnp.trunc(x)


@register_op("gcd")
def gcd(x, y):
    return jnp.gcd(x, y)


@register_op("lcm")
def lcm(x, y):
    return jnp.lcm(x, y)


@register_op("rad2deg")
def rad2deg(x):
    return jnp.rad2deg(x)


@register_op("deg2rad")
def deg2rad(x):
    return jnp.deg2rad(x)


@register_op("diff")
def diff(x, *, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


@register_op("amax")
def amax(x, *, axis=None, keepdim=False):
    return jnp.amax(x, axis=axis, keepdims=keepdim)


@register_op("amin")
def amin(x, *, axis=None, keepdim=False):
    return jnp.amin(x, axis=axis, keepdims=keepdim)


@register_op("angle")
def angle(x):
    return jnp.angle(x)


@register_op("conj")
def conj(x):
    return jnp.conj(x)


@register_op("real")
def real(x):
    return jnp.real(x)


@register_op("imag")
def imag(x):
    return jnp.imag(x)


@register_op("as_complex")
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


@register_op("as_real")
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@register_op("nextafter")
def nextafter(x, y):
    return jnp.nextafter(x, y)


@register_op("ldexp")
def ldexp(x, y):
    return jnp.ldexp(x, y)


@register_op("copysign")
def copysign(x, y):
    return jnp.copysign(x, y)


@register_op("hypot")
def hypot(x, y):
    return jnp.hypot(x, y)


@register_op("i0")
def i0(x):
    return jnp.i0(x)


@register_op("sinc")
def sinc(x):
    return jnp.sinc(x)


@register_op("signbit")
def signbit(x):
    return jnp.signbit(x)


@register_op("label_smooth")
def label_smooth(label, *, epsilon=0.1, prior_dist=None):
    """operators/label_smooth_op.cc."""
    c = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / c


@register_op("glu")
def glu(x, *, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@register_op("rot90")
def rot90(x, *, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@register_op("expand_as")
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@register_op("pad3d")
def pad3d(x, *, paddings, mode="constant", value=0.0, data_format="NCDHW"):
    """operators/pad3d_op.cc: pad last three spatial dims
    (paddings = [l, r, top, bottom, front, back])."""
    l, r, t, b, f, bk = [int(p) for p in paddings]
    if data_format == "NCDHW":
        cfg = [(0, 0), (0, 0), (f, bk), (t, b), (l, r)]
    else:  # NDHWC
        cfg = [(0, 0), (f, bk), (t, b), (l, r), (0, 0)]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, mode=jmode, constant_values=value)
    return jnp.pad(x, cfg, mode=jmode)


@register_op("grid_sample")
def grid_sample(x, grid, *, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """operators/grid_sampler_op.cc: sample x [N,C,H,W] at normalized grid
    [N,Hg,Wg,2] locations (x, y in [-1, 1])."""
    n, c, h, w = x.shape

    def unnormalize(coord, size):
        if align_corners:
            return (coord + 1) / 2 * (size - 1)
        return ((coord + 1) * size - 1) / 2

    gx = unnormalize(grid[..., 0], w)                         # [N, Hg, Wg]
    gy = unnormalize(grid[..., 1], h)

    def sample(img, yy, xx):
        """img [C,H,W], yy/xx [Hg,Wg]"""
        if mode == "nearest":
            yi = jnp.clip(jnp.round(yy), 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(jnp.round(xx), 0, w - 1).astype(jnp.int32)
            vals = img[:, yi, xi]
            if padding_mode == "zeros":
                inb = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1))
                vals = vals * inb[None].astype(img.dtype)
            return vals
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        wy1 = yy - y0
        wx1 = xx - x0

        def at(yi, xi):
            yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
            v = img[:, yc, xc]
            if padding_mode == "zeros":
                inb = ((yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1))
                v = v * inb[None].astype(img.dtype)
            return v

        return (at(y0, x0) * ((1 - wy1) * (1 - wx1))[None]
                + at(y0, x0 + 1) * ((1 - wy1) * wx1)[None]
                + at(y0 + 1, x0) * (wy1 * (1 - wx1))[None]
                + at(y0 + 1, x0 + 1) * (wy1 * wx1)[None])

    return jax.vmap(sample)(x, gy, gx)


@register_op("affine_grid")
def affine_grid(theta, *, out_shape, align_corners=True):
    """operators/affine_grid_op.cc: theta [N, 2, 3] -> grid [N, H, W, 2]."""
    n, _, h, w = [int(s) for s in out_shape]

    def linspace(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    ys = linspace(h)
    xs = linspace(w)
    gx, gy = jnp.meshgrid(xs, ys)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)                 # [H, W, 3]
    return jnp.einsum("hwk,njk->nhwj", base, theta)
