"""Linear-algebra op kernels.

Reference parity: the reference's linalg ops live across paddle/fluid/
operators/ (determinant_op, svd_op (later forks), cholesky_op, matrix_rank,
solve family) and python/paddle/tensor/linalg.py. Each kernel is the
jnp/jax.scipy lowering — XLA ships native TPU implementations (QR/SVD/eigh
via Jacobi kernels), so these are direct registrations, with paddle
attr/shape conventions at the wrapper layer (ops/__init__.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("det")
def det(x):
    return jnp.linalg.det(x)


@register_op("slogdet", num_outputs=2)
def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return sign, logabs


@register_op("matrix_rank")
def matrix_rank(x, *, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, tol=tol)


@register_op("solve")
def solve(a, b):
    return jnp.linalg.solve(a, b)


@register_op("triangular_solve")
def triangular_solve(a, b, *, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        a, b, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular,
    )


@register_op("cholesky_solve")
def cholesky_solve(b, l, *, upper=False):
    return jax.scipy.linalg.cho_solve((l, not upper), b)


@register_op("lstsq", num_outputs=4)
def lstsq(a, b, *, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
    return sol, res, rank, sv


@register_op("svd", num_outputs=3)
def svd(x, *, full_matrices=False):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, vh


@register_op("qr", num_outputs=2)
def qr(x, *, mode="reduced"):
    q, r = jnp.linalg.qr(x, mode=mode)
    return q, r


@register_op("lu", num_outputs=3)
def lu(x):
    p, l, u = jax.scipy.linalg.lu(x)
    return p, l, u


@register_op("eig", num_outputs=2)
def eig(x):
    # CPU-only in XLA; TPU users should prefer eigh for symmetric inputs
    w, v = jnp.linalg.eig(x)
    return w, v


@register_op("eigh", num_outputs=2)
def eigh(x, *, UPLO="L"):
    w, v = jnp.linalg.eigh(x, symmetrize_input=True)
    return w, v


@register_op("eigvalsh")
def eigvalsh(x, *, UPLO="L"):
    return jnp.linalg.eigvalsh(x)


@register_op("pinv")
def pinv(x, *, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@register_op("matrix_norm")
def matrix_norm(x, *, p="fro", axis=(-2, -1), keepdim=False):
    return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)


@register_op("trace")
def trace(x, *, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@register_op("kron")
def kron(x, y):
    return jnp.kron(x, y)


@register_op("cov")
def cov(x, *, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@register_op("corrcoef")
def corrcoef(x, *, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@register_op("householder_product")
def householder_product(x, tau):
    """paddle.linalg.householder_product: accumulate Householder reflectors
    (the Q factor from a packed QR): Q = H_0 H_1 ... H_{k-1}."""
    m, n = x.shape[-2], x.shape[-1]

    def apply(q, args):
        i, = args
        v = jnp.where(jnp.arange(m) < i, 0.0, x[..., :, i])
        v = v.at[i].set(1.0)
        q = q - tau[i] * jnp.outer(v, v @ q)
        return q, None

    q = jnp.eye(m, dtype=x.dtype)
    q, _ = jax.lax.scan(apply, q, (jnp.arange(n),))
    return q[..., :, :n]


@register_op("multi_dot")
def multi_dot(*arrays):
    return jnp.linalg.multi_dot(list(arrays))
