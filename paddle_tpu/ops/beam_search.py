"""Beam search ops.

Reference parity: operators/beam_search_op.cc (one expansion step over
LoD-organized candidates) + beam_search_decode_op.cc (backtrack to full
hypotheses).

TPU-native design: fixed beam width everywhere — a step is one
top-k over [batch, beam*vocab] (MXU-free, but single fused XLA op), and
decoding is a reverse lax.scan over stored parent pointers. No LoD: the
batch of beams is a dense [batch, beam] lattice, finished beams are kept
alive with a -inf continuation mask (the standard dense-beam trick on
accelerators).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


@register_op("beam_search_step", num_outputs=3)
def beam_search_step(log_probs, beam_scores, *, beam_size, end_id=None,
                     first_step=False):
    """One beam expansion.

    log_probs  [batch, beam, vocab] — next-token log probabilities
    beam_scores [batch, beam]       — running hypothesis scores
    Returns (scores, parent_idx, token_ids), each [batch, beam].
    """
    b, k, v = log_probs.shape
    total = beam_scores[:, :, None] + log_probs            # [B, K, V]
    if first_step:
        # all beams start identical: expand only beam 0 to avoid duplicates
        mask = jnp.full((1, k, 1), -jnp.inf, total.dtype).at[0, 0, 0].set(0.0)
        total = total + mask
    flat = total.reshape(b, k * v)
    scores, idx = lax.top_k(flat, int(beam_size))          # [B, beam]
    parent = idx // v
    token = idx % v
    return scores, parent, token


@register_op("beam_search_decode", num_outputs=2)
def beam_search_decode(parents, tokens, final_scores, *, end_id=None):
    """Backtrack stored pointers to token sequences.

    parents/tokens [T, batch, beam] — per-step outputs of beam_search_step
    final_scores   [batch, beam]
    Returns (sequences [T, batch, beam], final_scores); sequences read
    time-major, best hypothesis at beam index of max score.
    """
    t, b, k = tokens.shape
    last = jnp.broadcast_to(jnp.arange(k)[None, :], (b, k))

    def step(beam_idx, pt):
        parent_t, token_t = pt
        tok = jnp.take_along_axis(token_t, beam_idx, axis=1)   # [B, K]
        prev = jnp.take_along_axis(parent_t, beam_idx, axis=1)
        return prev.astype(beam_idx.dtype), tok

    _, seqs = lax.scan(step, last, (parents, tokens), reverse=True)
    return seqs, final_scores
