"""First-class fused registry ops — the IR optimizer's rewrite targets.

Reference parity: the *_fuse_pass outputs of inference/api/paddle_pass_builder.cc
(conv_bn_fuse_pass, fc_elementwise_layernorm_fuse_pass, quant ops). The
reference registers fused operators that its graph passes rewrite chains
into; here the same role is played by thin registry entries over the
existing pallas kernels (ops/pallas/conv_bn_relu.py,
ops/pallas/layernorm_residual.py), so a REWRITTEN Program executes the
fused dispatch everywhere the hand-wired nn.Layer call sites already do
— pallas on TPU for admitted shapes, the bit-identical unfused primitive
sequence elsewhere (the kernels' own fallback discipline). The int8
chain rewrites onto the already-registered ``matmul_int8``/``mul_int8``
(quantize_kernels.py) and needs no new entry.

These ops are *compiler-internal*: builders never append them directly —
``analysis/optimizer.py``'s fusion passes do, with the refusal rules
(fetched/multi-consumer/grad-fed intermediates) enforced at rewrite time.
"""
from __future__ import annotations

from .registry import register_op


@register_op("fused_conv_bn_relu", num_outputs=3)
def fused_conv_bn_relu(x, weight, scale, bias, mean, var, *, stride=1,
                       padding=0, momentum=0.9, epsilon=1e-5, training=False,
                       data_format="NCHW"):
    """``relu(batch_norm(conv2d(x, weight)))`` as one registry op.

    Returns ``(y, new_running_mean, new_running_var)`` — the exact
    output structure of the ``batch_norm`` op (the optimizer keeps the
    original stat-output names and their ``__inplace__`` aliasing, so
    the executor's persistable write-back is unchanged). The conv must
    be bias-free, ungrouped, undilated — the fusion pass only rewrites
    chains that satisfy this.
    """
    from .pallas.conv_bn_relu import _fused

    return _fused(x, weight, scale, bias, mean, var, stride=stride,
                  padding=padding, training=bool(training),
                  momentum=float(momentum), eps=float(epsilon),
                  data_format=data_format)


@register_op("fused_layernorm_residual")
def fused_layernorm_residual(x, residual, scale, bias, *, epsilon=1e-5):
    """``LayerNorm(x + residual)`` over the last dim as one registry op.

    Same math as ``elementwise_add`` -> ``layer_norm`` with a trailing
    ``[H]`` scale/bias (the transformer residual idiom); the pallas
    kernel keeps one HBM round-trip instead of two.
    """
    from .pallas.layernorm_residual import _ln_res

    return _ln_res(x, residual, scale, bias, float(epsilon))
