"""Metric op kernels.

Reference parity: operators/metrics/ (auc_op.cc, precision_recall_op.cc;
accuracy_op.cc already exists in kernels.py). Streaming statistics are
returned as arrays the caller accumulates — matching the reference's
stat-tensor in/out design — so the ops stay pure and jittable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("auc", num_outputs=3)
def auc(predict, label, *, num_thresholds=4095, stat_pos=None, stat_neg=None,
        curve="ROC"):
    """auc_op.cc: bucketed ROC-AUC.

    predict [N, 2] (prob of classes 0/1) or [N] positive-class scores;
    label [N] in {0, 1}. Returns (auc_value, stat_pos', stat_neg') where the
    stats are per-bucket positive/negative counts (bucket = floor(p * T)).
    Pass the previous stats back in for streaming evaluation.
    """
    p = predict[:, 1] if predict.ndim == 2 else predict
    lbl = label.reshape(-1).astype(jnp.int32)
    t = int(num_thresholds)
    bucket = jnp.clip((p * t).astype(jnp.int32), 0, t)
    pos = jnp.zeros(t + 1, jnp.float64 if p.dtype == jnp.float64 else jnp.float32)
    pos = pos.at[bucket].add(lbl.astype(pos.dtype))
    neg = jnp.zeros_like(pos).at[bucket].add((1 - lbl).astype(pos.dtype))
    if stat_pos is not None:
        pos = pos + stat_pos
    if stat_neg is not None:
        neg = neg + stat_neg
    # integrate TPR over FPR with the trapezoid rule, descending threshold
    tp = jnp.cumsum(pos[::-1])
    fp = jnp.cumsum(neg[::-1])
    tot_pos = jnp.maximum(tp[-1], 1e-12)
    tot_neg = jnp.maximum(fp[-1], 1e-12)
    tpr = tp / tot_pos
    fpr = fp / tot_neg
    area = jnp.sum(
        (fpr[1:] - fpr[:-1]) * (tpr[1:] + tpr[:-1]) / 2.0
    ) + fpr[0] * tpr[0] / 2.0
    return area, pos, neg


@register_op("precision_recall", num_outputs=2)
def precision_recall(predict, label, *, num_classes):
    """precision_recall_op.cc: per-class and macro/micro P/R/F1.

    predict [N, C] scores (argmax = predicted class) or [N] class ids;
    label [N]. Returns:
      per_class [C, 3]  — precision, recall, F1 per class
      macro_micro [6]   — macro P/R/F1, micro P/R/F1
    """
    c = int(num_classes)
    pred = jnp.argmax(predict, axis=-1) if predict.ndim == 2 else predict
    pred = pred.astype(jnp.int32).reshape(-1)
    lbl = label.astype(jnp.int32).reshape(-1)
    f32 = jnp.float32

    onehot_p = jax.nn.one_hot(pred, c, dtype=f32)
    onehot_l = jax.nn.one_hot(lbl, c, dtype=f32)
    tp = jnp.sum(onehot_p * onehot_l, axis=0)
    fp = jnp.sum(onehot_p, axis=0) - tp
    fn = jnp.sum(onehot_l, axis=0) - tp

    def safe_div(a, b):
        return jnp.where(b > 0, a / jnp.maximum(b, 1e-12), 0.0)

    prec = safe_div(tp, tp + fp)
    rec = safe_div(tp, tp + fn)
    f1 = safe_div(2 * prec * rec, prec + rec)
    per_class = jnp.stack([prec, rec, f1], axis=1)

    macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
    micro_p = safe_div(tp.sum(), (tp + fp).sum())
    micro_r = safe_div(tp.sum(), (tp + fn).sum())
    micro_f = safe_div(2 * micro_p * micro_r, micro_p + micro_r)
    return per_class, jnp.concatenate(
        [macro, jnp.stack([micro_p, micro_r, micro_f])]
    )
