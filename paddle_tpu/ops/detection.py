"""Detection op family.

Reference parity: paddle/fluid/operators/detection/ (iou_similarity_op,
box_coder_op, box_clip_op, prior_box_op, yolo_box_op, roi_align_op,
multiclass_nms_op, bipartite_match_op). Boxes are [x1, y1, x2, y2].

TPU-native notes: everything except NMS is dense elementwise/gather math
that jits directly. NMS has data-dependent output size; ``nms``/
``multiclass_nms`` return a FIXED-size top-k list plus a validity count
(the accelerator-friendly contract — mask, don't shrink), exact host
semantics available eagerly via keep counts.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _area(boxes):
    return jnp.maximum(boxes[..., 2] - boxes[..., 0], 0) * jnp.maximum(
        boxes[..., 3] - boxes[..., 1], 0
    )


def _pairwise_iou(a, b):
    """a [N, 4], b [M, 4] -> [N, M]."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = _area(a)[:, None] + _area(b)[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register_op("iou_similarity")
def iou_similarity(x, y, *, box_normalized=True):
    """detection/iou_similarity_op.cc: pairwise IoU [N, M]."""
    return _pairwise_iou(x, y)


@register_op("bbox_overlaps")
def bbox_overlaps(x, y):
    return _pairwise_iou(x, y)


@register_op("box_clip")
def box_clip(boxes, im_info):
    """detection/box_clip_op.cc: clip to image (im_info [.., (h, w, ...)])."""
    h = im_info[..., 0:1] - 1
    w = im_info[..., 1:2] - 1
    x1 = jnp.clip(boxes[..., 0], 0, w[..., 0])
    y1 = jnp.clip(boxes[..., 1], 0, h[..., 0])
    x2 = jnp.clip(boxes[..., 2], 0, w[..., 0])
    y2 = jnp.clip(boxes[..., 3], 0, h[..., 0])
    return jnp.stack([x1, y1, x2, y2], axis=-1)


@register_op("box_coder")
def box_coder(prior_box, prior_box_var, target_box, *, code_type="encode_center_size",
              box_normalized=True):
    """detection/box_coder_op.cc: encode/decode boxes against priors.

    encode: target [N, 4] against priors [M, 4] -> [N, M, 4] deltas
    decode: deltas [N, M, 4] (or [N, 4] with M=N) -> boxes
    """
    pw = prior_box[:, 2] - prior_box[:, 0] + (0 if box_normalized else 1)
    ph = prior_box[:, 3] - prior_box[:, 1] + (0 if box_normalized else 1)
    pcx = prior_box[:, 0] + pw * 0.5
    pcy = prior_box[:, 1] + ph * 0.5
    var = prior_box_var if prior_box_var is not None else jnp.ones_like(prior_box)

    if code_type.lower().startswith("encode"):
        tw = target_box[:, 2] - target_box[:, 0] + (0 if box_normalized else 1)
        th = target_box[:, 3] - target_box[:, 1] + (0 if box_normalized else 1)
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10))
        dh = jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        return out / var[None, :, :]
    # decode
    d = target_box * (var[None, :, :] if target_box.ndim == 3 else var)
    if d.ndim == 2:
        d = d[:, None, :]
        squeeze = True
    else:
        squeeze = False
    cx = d[..., 0] * pw[None, :] + pcx[None, :]
    cy = d[..., 1] * ph[None, :] + pcy[None, :]
    w = jnp.exp(d[..., 2]) * pw[None, :]
    h = jnp.exp(d[..., 3]) * ph[None, :]
    off = 0 if box_normalized else 0.5
    out = jnp.stack(
        [cx - w * 0.5, cy - h * 0.5,
         cx + w * 0.5 - (0 if box_normalized else 1),
         cy + h * 0.5 - (0 if box_normalized else 1)], axis=-1
    )
    return out[:, 0, :] if squeeze else out


@register_op("prior_box", num_outputs=2)
def prior_box(input, image, *, min_sizes, max_sizes=(), aspect_ratios=(1.0,),
              variances=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              step_w=0.0, step_h=0.0, offset=0.5, min_max_aspect_ratios_order=False):
    """detection/prior_box_op.cc: SSD anchor boxes for one feature map.

    input [N, C, H, W] feature map, image [N, C, Him, Wim]. Returns
    (boxes [H, W, A, 4], variances [H, W, A, 4]).
    """
    h, w = input.shape[2], input.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    sw = float(step_w) or img_w / w
    sh = float(step_h) or img_h / h

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    whs = []
    for ms in min_sizes:
        ms = float(ms)
        for ar in ars:
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            mx = float(max_sizes[list(min_sizes).index(ms)])
            whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    whs = jnp.asarray(whs)                                    # [A, 2]
    a = whs.shape[0]

    cx = (jnp.arange(w) + float(offset)) * sw                 # [W]
    cy = (jnp.arange(h) + float(offset)) * sh                 # [H]
    cxg, cyg = jnp.meshgrid(cx, cy)                           # [H, W]
    cxg = cxg[:, :, None]
    cyg = cyg[:, :, None]
    bw = whs[None, None, :, 0] / 2.0
    bh = whs[None, None, :, 1] / 2.0
    boxes = jnp.stack(
        [(cxg - bw) / img_w, (cyg - bh) / img_h,
         (cxg + bw) / img_w, (cyg + bh) / img_h], axis=-1
    )                                                         # [H, W, A, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), boxes.shape)
    return boxes, var


@register_op("yolo_box", num_outputs=2)
def yolo_box(x, img_size, *, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """detection/yolo_box_op.cc: decode YOLOv3 head output.

    x [N, A*(5+C), H, W], img_size [N, 2] (h, w). Returns
    (boxes [N, H*W*A, 4], scores [N, H*W*A, C]).
    """
    n, _, h, w = x.shape
    a = len(anchors) // 2
    c = int(class_num)
    x = x.reshape(n, a, 5 + c, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    sxy = float(scale_x_y)
    bias = -0.5 * (sxy - 1.0)
    cx = (jax.nn.sigmoid(x[:, :, 0]) * sxy + bias + grid_x) / w    # [N,A,H,W]
    cy = (jax.nn.sigmoid(x[:, :, 1]) * sxy + bias + grid_y) / h
    anc = jnp.asarray(anchors, x.dtype).reshape(a, 2)
    input_h = float(downsample_ratio) * h
    input_w = float(downsample_ratio) * w
    bw = jnp.exp(x[:, :, 2]) * anc[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * anc[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]

    img_h = img_size[:, 0].reshape(n, 1, 1, 1).astype(x.dtype)
    img_w = img_size[:, 1].reshape(n, 1, 1, 1).astype(x.dtype)
    x1 = (cx - bw / 2) * img_w
    y1 = (cy - bh / 2) * img_h
    x2 = (cx + bw / 2) * img_w
    y2 = (cy + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)             # [N,A,H,W,4]
    keep = conf > conf_thresh
    boxes = boxes * keep[..., None].astype(x.dtype)
    probs = probs * keep[:, :, None].astype(x.dtype)
    boxes = boxes.transpose(0, 2, 3, 1, 4).reshape(n, h * w * a, 4)
    scores = probs.transpose(0, 3, 4, 2, 1).reshape(n, h * w * a, c)
    return boxes, scores


@register_op("roi_align")
def roi_align(x, rois, rois_num, *, pooled_height, pooled_width,
              spatial_scale=1.0, sampling_ratio=-1, aligned=False):
    """detection/roi_align_op.cc: bilinear ROI pooling.

    x [N, C, H, W]; rois [R, 4] in image coords; rois_num [N] rois per
    image (defines each roi's batch index). Output [R, C, ph, pw].
    """
    n, c, h, w = x.shape
    r = rois.shape[0]
    ph, pw = int(pooled_height), int(pooled_width)
    scale = float(spatial_scale)
    off = 0.5 if aligned else 0.0

    batch_idx = jnp.repeat(
        jnp.arange(rois_num.shape[0]), rois_num, total_repeat_length=r
    )

    x1 = rois[:, 0] * scale - off
    y1 = rois[:, 1] * scale - off
    x2 = rois[:, 2] * scale - off
    y2 = rois[:, 3] * scale - off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_w = rw / pw
    bin_h = rh / ph
    ns = int(sampling_ratio) if int(sampling_ratio) > 0 else 2

    # sample grid: [R, ph, ns] y coords x [R, pw, ns] x coords
    iy = (jnp.arange(ph)[None, :, None]
          + (jnp.arange(ns)[None, None, :] + 0.5) / ns)
    sy = y1[:, None, None] + iy * bin_h[:, None, None]       # [R, ph, ns]
    ix = (jnp.arange(pw)[None, :, None]
          + (jnp.arange(ns)[None, None, :] + 0.5) / ns)
    sx = x1[:, None, None] + ix * bin_w[:, None, None]       # [R, pw, ns]

    def bilinear(img, yy, xx):
        """img [C, H, W]; yy [ph*ns], xx [pw*ns] -> [C, ph*ns, pw*ns]"""
        y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        wy1 = jnp.clip(yy - y0, 0, 1)
        wx1 = jnp.clip(xx - x0, 0, 1)
        wy0, wx0 = 1 - wy1, 1 - wx1
        v00 = img[:, y0i][:, :, x0i]
        v01 = img[:, y0i][:, :, x1i]
        v10 = img[:, y1i][:, :, x0i]
        v11 = img[:, y1i][:, :, x1i]
        return (v00 * (wy0[:, None] * wx0[None, :])
                + v01 * (wy0[:, None] * wx1[None, :])
                + v10 * (wy1[:, None] * wx0[None, :])
                + v11 * (wy1[:, None] * wx1[None, :]))

    def per_roi(bi, yy, xx):
        img = x[bi]                                           # [C, H, W]
        vals = bilinear(img, yy.reshape(-1), xx.reshape(-1))  # [C, ph*ns, pw*ns]
        vals = vals.reshape(c, ph, ns, pw, ns)
        return vals.mean(axis=(2, 4))                         # [C, ph, pw]

    return jax.vmap(per_roi)(batch_idx, sy, sx)


@register_op("nms", num_outputs=2)
def nms(boxes, scores, *, iou_threshold=0.5, top_k=-1):
    """Greedy NMS with a FIXED output size: returns (keep_idx [K], num_kept)
    where K = top_k (or N). Suppressed slots hold -1 — the accelerator
    contract (mask, don't shrink); exact host semantics via num_kept.
    """
    n = boxes.shape[0]
    k = n if top_k in (-1, None) else min(int(top_k), n)
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    iou = _pairwise_iou(boxes_s, boxes_s)

    def body(i, keep):
        # box i survives iff no higher-scoring kept box overlaps it
        earlier = jnp.arange(n) < i
        sup = jnp.sum(
            jnp.where(earlier, (iou[i] > iou_threshold) & keep.astype(bool),
                      False)
        ) > 0
        return keep.at[i].set(jnp.where(sup, 0, 1))

    keep = lax.fori_loop(0, n, body, jnp.zeros(n, jnp.int32))
    # compact kept entries to the front, preserving score order
    rank = jnp.cumsum(keep) - 1
    out = jnp.full(k, -1, jnp.int32)
    valid = (keep.astype(bool)) & (rank < k)
    out = out.at[jnp.where(valid, rank, k)].set(
        jnp.where(valid, order, -1).astype(jnp.int32), mode="drop"
    )
    return out, jnp.minimum(jnp.sum(keep), k)


@register_op("multiclass_nms", num_outputs=2)
def multiclass_nms(bboxes, scores, *, score_threshold=0.05, nms_threshold=0.3,
                   keep_top_k=100, background_label=-1):
    """detection/multiclass_nms_op.cc with the fixed-size contract.

    bboxes [N, 4]; scores [C, N]. Returns (out [keep_top_k, 6], num_kept):
    rows are (class, score, x1, y1, x2, y2), padded rows are -1.
    """
    c, n = scores.shape
    k = int(keep_top_k)
    neg_inf = jnp.asarray(-jnp.inf, bboxes.dtype)
    all_rows, all_valid = [], []
    for cls in range(c):
        if cls == background_label:
            continue
        s_raw = scores[cls]
        passes = s_raw >= score_threshold
        # ordering key only — validity is the explicit mask, so legitimate
        # zero/negative scores above the threshold are kept (ADVICE r2)
        s_key = jnp.where(passes, s_raw, neg_inf)
        keep_idx, _ = nms(bboxes, s_key, iou_threshold=nms_threshold, top_k=n)
        gi = jnp.clip(keep_idx, 0, n - 1)
        valid = (keep_idx >= 0) & passes[gi]
        row = jnp.concatenate(
            [jnp.full((n, 1), cls, bboxes.dtype),
             s_raw[gi][:, None],
             bboxes[gi]], axis=1
        )
        all_rows.append(jnp.where(valid[:, None], row, -1.0))
        all_valid.append(valid)
    stacked = jnp.concatenate(all_rows, axis=0)
    valid = jnp.concatenate(all_valid, axis=0)
    order = jnp.argsort(-jnp.where(valid, stacked[:, 1], neg_inf))
    stacked = stacked[order][:k]
    valid = valid[order][:k]
    num = jnp.sum(valid)
    pad = k - stacked.shape[0]
    if pad > 0:
        stacked = jnp.concatenate(
            [stacked, jnp.full((pad, 6), -1.0, stacked.dtype)], axis=0
        )
    return stacked, num


# ---------------------------------------------------------------------------
# round-3 tail: anchors, matching/assignment, NMS variants, FPN routing,
# losses, proposal generation
# ---------------------------------------------------------------------------


@register_op("sigmoid_focal_loss")
def sigmoid_focal_loss(x, label, fg_num, *, gamma=2.0, alpha=0.25):
    """detection/sigmoid_focal_loss_op.cc: per-element focal loss over
    [N, C] logits; label [N] in {0..C} with 0 = background (classes are
    1-indexed as in the reference); normalized by fg_num."""
    n, c = x.shape
    fg = jnp.maximum(fg_num.astype(x.dtype).reshape(()), 1.0)
    cls = jnp.arange(1, c + 1)[None, :]
    t = (label.reshape(-1, 1) == cls).astype(x.dtype)  # one-hot, bg = zeros
    p = jax.nn.sigmoid(x)
    ce = -(t * jax.nn.log_sigmoid(x) + (1 - t) * jax.nn.log_sigmoid(-x))
    p_t = t * p + (1 - t) * (1 - p)
    a_t = t * alpha + (1 - t) * (1 - alpha)
    return a_t * ((1 - p_t) ** gamma) * ce / fg


@register_op("anchor_generator", num_outputs=2)
def anchor_generator(x, *, anchor_sizes, aspect_ratios, stride,
                     variances=(0.1, 0.1, 0.2, 0.2), offset=0.5):
    """detection/anchor_generator_op.cc: per-location anchors for an
    [N, C, H, W] feature map. Returns (anchors [H, W, A, 4],
    variances [H, W, A, 4])."""
    h, w = x.shape[2], x.shape[3]
    sx, sy = float(stride[0]), float(stride[1])
    cx = (jnp.arange(w) + offset) * sx
    cy = (jnp.arange(h) + offset) * sy
    ws, hs = [], []
    for r in aspect_ratios:
        for s in anchor_sizes:
            ws.append(s * float(np.sqrt(1.0 / r)))
            hs.append(s * float(np.sqrt(r)))
    ws = jnp.asarray(ws, x.dtype)
    hs = jnp.asarray(hs, x.dtype)
    grid_x = cx[None, :, None]
    grid_y = cy[:, None, None]
    x1 = grid_x - 0.5 * ws[None, None, :]
    y1 = grid_y - 0.5 * hs[None, None, :]
    x2 = grid_x + 0.5 * ws[None, None, :]
    y2 = grid_y + 0.5 * hs[None, None, :]
    x1, y1, x2, y2 = (
        jnp.broadcast_to(v, (h, w, ws.shape[0])) for v in (x1, y1, x2, y2)
    )
    anchors = jnp.stack([x1, y1, x2, y2], axis=-1)
    var = jnp.broadcast_to(
        jnp.asarray(variances, x.dtype), anchors.shape
    )
    return anchors, var


@register_op("density_prior_box", num_outputs=2)
def density_prior_box(x, image, *, densities, fixed_sizes, fixed_ratios,
                      variances=(0.1, 0.1, 0.2, 0.2), step=(0.0, 0.0),
                      offset=0.5, clip=False):
    """detection/density_prior_box_op.cc: densified SSD priors — each
    (density d, fixed size s) pair contributes d*d shifted boxes per
    ratio. Returns (boxes [H, W, P, 4], variances [H, W, P, 4]),
    normalized to [0, 1] image coords."""
    fh, fw = x.shape[2], x.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw = float(step[0]) or iw / fw
    sh = float(step[1]) or ih / fh
    boxes_per_loc = []
    for d, s in zip(densities, fixed_sizes):
        for r in fixed_ratios:
            bw = s * float(np.sqrt(r))
            bh = s / float(np.sqrt(r))
            shift = s / d
            for di in range(d):
                for dj in range(d):
                    ox = -s / 2.0 + shift / 2.0 + dj * shift
                    oy = -s / 2.0 + shift / 2.0 + di * shift
                    boxes_per_loc.append((ox, oy, bw, bh))
    p = len(boxes_per_loc)
    off = jnp.asarray(boxes_per_loc, x.dtype)  # [P, 4] (ox, oy, w, h)
    cx = (jnp.arange(fw, dtype=x.dtype) + offset) * sw
    cy = (jnp.arange(fh, dtype=x.dtype) + offset) * sh
    ccx = jnp.broadcast_to(cx[None, :, None], (fh, fw, p)) + off[None, None, :, 0]
    ccy = jnp.broadcast_to(cy[:, None, None], (fh, fw, p)) + off[None, None, :, 1]
    bw = jnp.broadcast_to(off[None, None, :, 2], (fh, fw, p))
    bh = jnp.broadcast_to(off[None, None, :, 3], (fh, fw, p))
    out = jnp.stack(
        [(ccx - bw / 2) / iw, (ccy - bh / 2) / ih,
         (ccx + bw / 2) / iw, (ccy + bh / 2) / ih], axis=-1,
    )
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, x.dtype), out.shape)
    return out, var


@register_op("polygon_box_transform")
def polygon_box_transform(x):
    """detection/polygon_box_transform_op.cc: EAST-style geometry map —
    channel 2k is offset-from-x, 2k+1 offset-from-y; input [N, 8, H, W]
    holds offsets, output holds absolute quad coords (x*4 - offset)."""
    n, c, h, w = x.shape
    xs = jnp.arange(w, dtype=x.dtype)[None, None, None, :] * 4.0
    ys = jnp.arange(h, dtype=x.dtype)[None, None, :, None] * 4.0
    is_x = (jnp.arange(c) % 2 == 0)[None, :, None, None]
    grid = jnp.where(is_x, xs, ys)
    return grid - x


@register_op("bipartite_match", num_outputs=2)
def bipartite_match(dist, *, match_type="bipartite", dist_threshold=0.5):
    """detection/bipartite_match_op.cc: greedy bipartite matching on a
    [N, M] similarity matrix — repeatedly take the globally largest
    entry whose row and column are both unmatched. Returns
    (match_indices [M] int32 with -1 = unmatched,
     match_dist [M]). match_type="per_prediction" additionally matches
    remaining columns to their best row when sim > dist_threshold."""
    n, m = dist.shape
    neg = jnp.asarray(-1.0, dist.dtype)

    def body(_, carry):
        col_match, col_dist, d = carry
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        ok = d[i, j] > 0
        col_match = col_match.at[j].set(
            jnp.where(ok, i.astype(jnp.int32), col_match[j])
        )
        col_dist = col_dist.at[j].set(jnp.where(ok, dist[i, j], col_dist[j]))
        d = jnp.where(ok, d.at[i, :].set(neg).at[:, j].set(neg), d)
        return col_match, col_dist, d

    init = (jnp.full(m, -1, jnp.int32), jnp.zeros(m, dist.dtype), dist)
    col_match, col_dist, _ = lax.fori_loop(0, min(n, m), body, init)
    if match_type == "per_prediction":
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        extra = (col_match < 0) & (best_val > dist_threshold)
        col_match = jnp.where(extra, best_row, col_match)
        col_dist = jnp.where(extra, best_val, col_dist)
    return col_match, col_dist


@register_op("target_assign", num_outputs=2)
def target_assign(x, match_indices, *, neg_value=0.0):
    """detection/target_assign_op.cc: gather per-column targets by match
    index. x [N, K], match_indices [M] -> (out [M, K], weights [M])."""
    mi = match_indices
    gi = jnp.clip(mi, 0, x.shape[0] - 1)
    out = x[gi]
    w = (mi >= 0).astype(x.dtype)
    out = jnp.where((mi >= 0)[:, None], out, neg_value)
    return out, w


@register_op("box_decoder_and_assign", num_outputs=2)
def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           *, box_clip=4.135166556742356):
    """detection/box_decoder_and_assign_op.cc: decode per-class deltas
    then pick each box's best-scoring class decode.

    prior_box [N,4]; target_box [N, C*4]; box_score [N, C].
    Returns (decoded [N, C*4], assigned [N, 4])."""
    n, c4 = target_box.shape
    c = c4 // 4
    pw = prior_box[:, 2] - prior_box[:, 0] + 1.0
    ph = prior_box[:, 3] - prior_box[:, 1] + 1.0
    pcx = prior_box[:, 0] + 0.5 * pw
    pcy = prior_box[:, 1] + 0.5 * ph
    t = target_box.reshape(n, c, 4)
    var = (prior_box_var if prior_box_var is not None
           else jnp.ones((n, 4), target_box.dtype))
    dx = t[..., 0] * var[:, None, 0]
    dy = t[..., 1] * var[:, None, 1]
    dw = jnp.clip(t[..., 2] * var[:, None, 2], -box_clip, box_clip)
    dh = jnp.clip(t[..., 3] * var[:, None, 3], -box_clip, box_clip)
    cx = dx * pw[:, None] + pcx[:, None]
    cy = dy * ph[:, None] + pcy[:, None]
    w = jnp.exp(dw) * pw[:, None]
    h = jnp.exp(dh) * ph[:, None]
    dec = jnp.stack(
        [cx - w / 2, cy - h / 2, cx + w / 2 - 1.0, cy + h / 2 - 1.0],
        axis=-1,
    )  # [N, C, 4]
    best = jnp.argmax(box_score, axis=1)
    assigned = jnp.take_along_axis(
        dec, best[:, None, None].repeat(4, axis=2), axis=1
    )[:, 0]
    return dec.reshape(n, c4), assigned


@register_op("matrix_nms", num_outputs=2)
def matrix_nms(bboxes, scores, *, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0):
    """detection/matrix_nms_op.cc: parallel soft-NMS — each box's score is
    decayed by its worst overlap with any higher-scoring same-class box
    (min over decay(iou_ij)/decay(max-overlap_j)). One matmul-shaped
    pass, no sequential suppression: the TPU-native NMS.

    bboxes [N,4]; scores [C,N]. Returns (out [keep_top_k, 6], num_kept).
    """
    c, n = scores.shape
    k = int(keep_top_k)
    rows, valid_all = [], []
    for cls in range(c):
        if cls == background_label:
            continue
        s = scores[cls]
        passes = s >= score_threshold
        order = jnp.argsort(-jnp.where(passes, s, -jnp.inf))
        b_s = bboxes[order]
        s_s = s[order]
        p_s = passes[order]
        iou = _pairwise_iou(b_s, b_s)
        upper = jnp.tril(iou, k=-1).T  # upper[i, j] = iou(i, j) for i < j
        # iou_max_i: suppressor i's own max overlap with ITS predecessors
        # (matrix_nms_op.cc: decay_ij = decay(iou_ij) / decay(iou_max_i))
        max_overlap = jnp.max(upper, axis=0)
        if use_gaussian:
            decay = jnp.exp(
                (jnp.square(max_overlap)[:, None] - jnp.square(upper))
                / gaussian_sigma
            )
        else:
            decay = (1.0 - upper) / jnp.maximum(1.0 - max_overlap[:, None],
                                                1e-10)
        decay = jnp.min(jnp.where(upper > 0, decay, 1.0), axis=0)
        new_s = s_s * decay
        ok = p_s & (new_s >= post_threshold)
        row = jnp.concatenate(
            [jnp.full((n, 1), cls, bboxes.dtype), new_s[:, None], b_s],
            axis=1,
        )
        rows.append(jnp.where(ok[:, None], row, -1.0))
        valid_all.append(ok)
    stacked = jnp.concatenate(rows, axis=0)
    valid = jnp.concatenate(valid_all, axis=0)
    order = jnp.argsort(-jnp.where(valid, stacked[:, 1], -jnp.inf))
    stacked = stacked[order][:k]
    valid = valid[order][:k]
    pad = k - stacked.shape[0]
    if pad > 0:
        stacked = jnp.concatenate(
            [stacked, jnp.full((pad, 6), -1.0, stacked.dtype)], axis=0
        )
    return stacked, jnp.sum(valid)


@register_op("locality_aware_nms", num_outputs=2)
def locality_aware_nms(bboxes, scores, *, score_threshold=0.05,
                       nms_threshold=0.3, keep_top_k=100):
    """detection/locality_aware_nms_op.cc (EAST): first weighted-merge
    overlapping neighbors (score-weighted coordinate average), then
    standard NMS. Single-class. Returns (out [keep_top_k, 6], num)."""
    n = bboxes.shape[0]
    s = scores.reshape(-1)
    passes = s >= score_threshold
    iou = _pairwise_iou(bboxes, bboxes)
    near = (iou > nms_threshold) & passes[None, :] & passes[:, None]
    wsum = jnp.sum(jnp.where(near, s[None, :], 0.0), axis=1)
    merged = jnp.einsum(
        "nm,md->nd", jnp.where(near, s[None, :], 0.0), bboxes
    ) / jnp.maximum(wsum, 1e-10)[:, None]
    merged = jnp.where(passes[:, None], merged, bboxes)
    keep_idx, _ = nms(
        merged, jnp.where(passes, s, -jnp.inf),
        iou_threshold=nms_threshold, top_k=n,
    )
    gi = jnp.clip(keep_idx, 0, n - 1)
    valid = (keep_idx >= 0) & passes[gi]
    k = int(keep_top_k)
    rows = jnp.concatenate(
        [jnp.zeros((n, 1), bboxes.dtype), s[gi][:, None], merged[gi]],
        axis=1,
    )
    rows = jnp.where(valid[:, None], rows, -1.0)[:k]
    valid = valid[:k]
    pad = k - rows.shape[0]
    if pad > 0:
        rows = jnp.concatenate(
            [rows, jnp.full((pad, 6), -1.0, rows.dtype)], axis=0
        )
    return rows, jnp.sum(valid)


@register_op("mine_hard_examples", num_outputs=2)
def mine_hard_examples(cls_loss, match_indices, *, neg_pos_ratio=3.0,
                       mining_type="max_negative", sample_size=None):
    """detection/mine_hard_examples_op.cc: pick the hardest negatives
    (highest loss among unmatched priors), capped at
    neg_pos_ratio * num_positives (or sample_size). Fixed-size output:
    returns (neg_mask [M] int32, num_neg) instead of a LoD index list."""
    m = match_indices.shape[0]
    is_pos = match_indices >= 0
    n_pos = jnp.sum(is_pos)
    cap = (jnp.asarray(int(sample_size), jnp.float32)
           if sample_size is not None
           else neg_pos_ratio * n_pos.astype(jnp.float32))
    neg_loss = jnp.where(is_pos, -jnp.inf, cls_loss.reshape(-1))
    order = jnp.argsort(-neg_loss)
    rank = jnp.zeros(m, jnp.int32).at[order].set(jnp.arange(m, dtype=jnp.int32))
    neg_mask = (~is_pos) & (rank.astype(jnp.float32) < cap) \
        & jnp.isfinite(neg_loss)
    return neg_mask.astype(jnp.int32), jnp.sum(neg_mask)


@register_op("generate_proposals", num_outputs=3)
def generate_proposals(scores, bbox_deltas, im_info, anchors, variances, *,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0):
    """detection/generate_proposals_op.cc for one image: objectness top-k
    → decode → clip to image → filter small → NMS. Fixed-size contract:
    (rois [post_nms_top_n, 4], roi_scores [post_nms_top_n], num_valid).

    scores [A] objectness; bbox_deltas [A, 4]; anchors/variances [A, 4];
    im_info (h, w, scale).
    """
    a = scores.shape[0]
    pre = min(int(pre_nms_top_n), a)
    post = int(post_nms_top_n)
    top_s, top_i = lax.top_k(scores, pre)
    anc = anchors[top_i]
    var = variances[top_i]
    d = bbox_deltas[top_i]
    # decode (box_coder decode_center_size with variances)
    aw = anc[:, 2] - anc[:, 0] + 1.0
    ah = anc[:, 3] - anc[:, 1] + 1.0
    acx = anc[:, 0] + 0.5 * aw
    acy = anc[:, 1] + 0.5 * ah
    cx = var[:, 0] * d[:, 0] * aw + acx
    cy = var[:, 1] * d[:, 1] * ah + acy
    w = jnp.exp(jnp.minimum(var[:, 2] * d[:, 2], 10.0)) * aw
    h = jnp.exp(jnp.minimum(var[:, 3] * d[:, 3], 10.0)) * ah
    boxes = jnp.stack(
        [cx - 0.5 * w, cy - 0.5 * h, cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0],
        axis=1,
    )
    ih, iw = im_info[0], im_info[1]
    boxes = jnp.stack([
        jnp.clip(boxes[:, 0], 0, iw - 1), jnp.clip(boxes[:, 1], 0, ih - 1),
        jnp.clip(boxes[:, 2], 0, iw - 1), jnp.clip(boxes[:, 3], 0, ih - 1),
    ], axis=1)
    ms = min_size * im_info[2]
    keep_size = ((boxes[:, 2] - boxes[:, 0] + 1.0) >= ms) \
        & ((boxes[:, 3] - boxes[:, 1] + 1.0) >= ms)
    s_masked = jnp.where(keep_size, top_s, -jnp.inf)
    keep_idx, _ = nms(boxes, s_masked, iou_threshold=nms_thresh, top_k=post)
    gi = jnp.clip(keep_idx, 0, pre - 1)
    valid = (keep_idx >= 0) & keep_size[gi]
    rois = jnp.where(valid[:, None], boxes[gi], 0.0)
    rs = jnp.where(valid, top_s[gi], 0.0)
    return rois, rs, jnp.sum(valid)


@register_op("distribute_fpn_proposals", num_outputs=2)
def distribute_fpn_proposals(rois, *, min_level=2, max_level=5,
                             refer_level=4, refer_scale=224):
    """detection/distribute_fpn_proposals_op.cc: route each RoI to an FPN
    level by its scale. Fixed-size contract: returns
    (level_idx [R] int32 absolute level, restore_rank [R] int32) — the
    caller masks per level (instead of the reference's variable-size
    per-level LoD outputs)."""
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = jnp.sqrt(jnp.maximum(w * h, 1e-10))
    lvl = jnp.floor(
        jnp.log2(scale / refer_scale + 1e-10)
    ).astype(jnp.int32) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level)
    order = jnp.argsort(lvl, stable=True)
    restore = jnp.zeros_like(order).at[order].set(
        jnp.arange(rois.shape[0], dtype=order.dtype)
    )
    return lvl, restore.astype(jnp.int32)


@register_op("collect_fpn_proposals", num_outputs=2)
def collect_fpn_proposals(multi_rois, multi_scores, *, post_nms_top_n=1000):
    """detection/collect_fpn_proposals_op.cc: concat per-level proposals
    and keep the global top-k by score. multi_rois [L, R, 4] stacked
    (pad with zero-score rows); multi_scores [L, R].
    Returns (rois [post_nms_top_n, 4], scores [post_nms_top_n])."""
    rois = multi_rois.reshape(-1, 4)
    scores = multi_scores.reshape(-1)
    k = min(int(post_nms_top_n), scores.shape[0])
    top_s, top_i = lax.top_k(scores, k)
    return rois[top_i], top_s


@register_op("retinanet_detection_output", num_outputs=2)
def retinanet_detection_output(bboxes, scores, anchors, im_info, *,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3):
    """detection/retinanet_detection_output_op.cc for one image: decode
    per-anchor deltas, then multiclass NMS. bboxes [A, 4] deltas;
    scores [A, C] sigmoid scores; anchors [A, 4]."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    cx = bboxes[:, 0] * aw + acx
    cy = bboxes[:, 1] * ah + acy
    w = jnp.exp(jnp.minimum(bboxes[:, 2], 10.0)) * aw
    h = jnp.exp(jnp.minimum(bboxes[:, 3], 10.0)) * ah
    dec = jnp.stack(
        [cx - 0.5 * w, cy - 0.5 * h, cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0],
        axis=1,
    )
    ih, iw = im_info[0], im_info[1]
    dec = jnp.stack([
        jnp.clip(dec[:, 0], 0, iw - 1), jnp.clip(dec[:, 1], 0, ih - 1),
        jnp.clip(dec[:, 2], 0, iw - 1), jnp.clip(dec[:, 3], 0, ih - 1),
    ], axis=1)
    return multiclass_nms(
        dec, scores.T, score_threshold=score_threshold,
        nms_threshold=nms_threshold, keep_top_k=keep_top_k,
        background_label=-1,
    )


@register_op("yolov3_loss")
def yolov3_loss(x, gt_box, gt_label, *, anchors, anchor_mask, class_num,
                ignore_thresh=0.7, downsample_ratio=32,
                use_label_smooth=False):
    """detection/yolov3_loss_op.cc: single-scale YOLOv3 training loss.

    x [N, A*(5+C), H, W] raw head output; gt_box [N, B, 4] normalized
    (cx, cy, w, h); gt_label [N, B] int (negative = padding slot).
    Differentiable scalar loss (objectness ignore mask per
    ignore_thresh, as the reference computes it).
    """
    n, _, h, w = x.shape
    a = len(anchor_mask)
    c = int(class_num)
    an_all = jnp.asarray(anchors, x.dtype).reshape(-1, 2)  # [A_all, 2]
    an = an_all[jnp.asarray(anchor_mask)]                  # [A, 2]
    stride = float(downsample_ratio)
    in_w, in_h = w * stride, h * stride

    x = x.reshape(n, a, 5 + c, h, w)
    tx, ty = x[:, :, 0], x[:, :, 1]
    tw, th = x[:, :, 2], x[:, :, 3]
    tobj = x[:, :, 4]
    tcls = x[:, :, 5:]

    gx = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    px = (jax.nn.sigmoid(tx) + gx) / w
    py = (jax.nn.sigmoid(ty) + gy) / h
    pw = jnp.exp(jnp.clip(tw, -10, 10)) * an[None, :, 0, None, None] / in_w
    ph = jnp.exp(jnp.clip(th, -10, 10)) * an[None, :, 1, None, None] / in_h
    pred = jnp.stack(
        [px - pw / 2, py - ph / 2, px + pw / 2, py + ph / 2], axis=-1
    )  # [N, A, H, W, 4]

    gt_valid = gt_label >= 0
    gxyxy = jnp.stack(
        [gt_box[..., 0] - gt_box[..., 2] / 2,
         gt_box[..., 1] - gt_box[..., 3] / 2,
         gt_box[..., 0] + gt_box[..., 2] / 2,
         gt_box[..., 1] + gt_box[..., 3] / 2], axis=-1,
    )  # [N, B, 4]

    def per_image(pred_i, gt_i, gtv_i):
        iou = _pairwise_iou(pred_i.reshape(-1, 4), gt_i)  # [AHW, B]
        best = jnp.max(jnp.where(gtv_i[None, :], iou, 0.0), axis=1)
        return best.reshape(a, h, w)

    best_iou = jax.vmap(per_image)(pred, gxyxy, gt_valid)
    ignore = best_iou > ignore_thresh

    # responsibility: each gt is owned by the best-matching anchor shape
    # at its center cell (shape-only IoU over ALL anchors, then mapped
    # into this scale's mask)
    gw = gt_box[..., 2] * in_w
    gh = gt_box[..., 3] * in_h
    inter = jnp.minimum(gw[..., None], an_all[None, None, :, 0]) * \
        jnp.minimum(gh[..., None], an_all[None, None, :, 1])
    union = gw[..., None] * gh[..., None] + \
        an_all[None, None, :, 0] * an_all[None, None, :, 1] - inter
    best_an = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # [N,B]
    mask_arr = jnp.asarray(anchor_mask)
    local_a = jnp.argmax(best_an[..., None] == mask_arr[None, None, :],
                         axis=-1)
    owned = jnp.any(best_an[..., None] == mask_arr[None, None, :], axis=-1) \
        & gt_valid

    gi = jnp.clip((gt_box[..., 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[..., 1] * h).astype(jnp.int32), 0, h - 1)

    b = gt_box.shape[1]
    n_idx = jnp.repeat(jnp.arange(n)[:, None], b, axis=1)

    def scatter(vals, default):
        out = jnp.full((n, a, h, w), default, x.dtype)
        return out.at[n_idx, local_a, gj, gi].set(
            jnp.where(owned, vals, out[n_idx, local_a, gj, gi]),
            mode="drop",
        )

    obj_t = scatter(jnp.ones_like(gw), 0.0)
    scale_t = scatter(2.0 - gt_box[..., 2] * gt_box[..., 3], 0.0)
    tx_t = scatter(gt_box[..., 0] * w - gi.astype(x.dtype), 0.0)
    ty_t = scatter(gt_box[..., 1] * h - gj.astype(x.dtype), 0.0)
    tw_t = scatter(
        jnp.log(jnp.maximum(gw / an[local_a][..., 0], 1e-10)), 0.0
    )
    th_t = scatter(
        jnp.log(jnp.maximum(gh / an[local_a][..., 1], 1e-10)), 0.0
    )

    def bce(logit, target):
        return -(target * jax.nn.log_sigmoid(logit)
                 + (1 - target) * jax.nn.log_sigmoid(-logit))

    pos = obj_t
    loss_xy = pos * scale_t * (bce(tx, tx_t) + bce(ty, ty_t))
    loss_wh = pos * scale_t * 0.5 * (
        jnp.square(tw - tw_t) + jnp.square(th - th_t)
    )
    noobj = (1.0 - pos) * (1.0 - ignore.astype(x.dtype))
    loss_obj = pos * bce(tobj, jnp.ones_like(tobj)) \
        + noobj * bce(tobj, jnp.zeros_like(tobj))
    smooth = 1.0 / c if use_label_smooth else 0.0
    cls_t = scatter(gt_label.astype(x.dtype), -1.0)
    cls_onehot = jnp.clip(
        (cls_t[:, :, None] == jnp.arange(c)[None, None, :, None, None])
        .astype(x.dtype), smooth, 1.0 - smooth if use_label_smooth else 1.0,
    )
    loss_cls = pos[:, :, None] * bce(tcls, cls_onehot)
    per_img = (loss_xy.sum(axis=(1, 2, 3)) + loss_wh.sum(axis=(1, 2, 3))
               + loss_obj.sum(axis=(1, 2, 3))
               + loss_cls.sum(axis=(1, 2, 3, 4)))
    return per_img


@register_op("rpn_target_assign", num_outputs=4)
def rpn_target_assign(anchors, gt_boxes, *, key, is_crowd=None,
                      rpn_batch_size_per_im=256, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7, rpn_negative_overlap=0.3,
                      use_random=True):
    """detection/rpn_target_assign_op.cc for one image. Fixed-size
    contract: returns (labels [A] int32 in {-1 ignore, 0 neg, 1 pos},
    matched_gt [A] int32, fg_num, bg_num) instead of LoD index lists.

    Positives: best anchor per gt + anchors with IoU > positive_overlap;
    negatives: IoU < negative_overlap; then subsampled to the reference's
    batch-size/fg-fraction budget (random when use_random, else
    top-ranked).
    """
    a = anchors.shape[0]
    iou = _pairwise_iou(anchors, gt_boxes)  # [A, G]
    best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
    best_iou = jnp.max(iou, axis=1)
    # anchors that are the argmax for some gt are positive regardless
    best_per_gt = jnp.max(iou, axis=0)
    is_best = jnp.any(
        (iou >= best_per_gt[None, :] - 1e-7) & (best_per_gt[None, :] > 0),
        axis=1,
    )
    pos = is_best | (best_iou >= rpn_positive_overlap)
    neg = (~pos) & (best_iou < rpn_negative_overlap)

    budget = int(rpn_batch_size_per_im)
    fg_cap = int(budget * rpn_fg_fraction)
    rk = jax.random.uniform(key, (a,)) if use_random else -best_iou

    def subsample(mask, cap):
        r = jnp.where(mask, rk, jnp.inf)
        order = jnp.argsort(r)
        rank = jnp.zeros(a, jnp.int32).at[order].set(
            jnp.arange(a, dtype=jnp.int32)
        )
        return mask & (rank < cap)

    pos_s = subsample(pos, fg_cap)
    n_fg = jnp.sum(pos_s)
    neg_s = subsample(neg, budget - n_fg)
    labels = jnp.where(pos_s, 1, jnp.where(neg_s, 0, -1)).astype(jnp.int32)
    return labels, best_gt, n_fg, jnp.sum(neg_s)
