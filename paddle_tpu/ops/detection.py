"""Detection op family.

Reference parity: paddle/fluid/operators/detection/ (iou_similarity_op,
box_coder_op, box_clip_op, prior_box_op, yolo_box_op, roi_align_op,
multiclass_nms_op, bipartite_match_op). Boxes are [x1, y1, x2, y2].

TPU-native notes: everything except NMS is dense elementwise/gather math
that jits directly. NMS has data-dependent output size; ``nms``/
``multiclass_nms`` return a FIXED-size top-k list plus a validity count
(the accelerator-friendly contract — mask, don't shrink), exact host
semantics available eagerly via keep counts.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _area(boxes):
    return jnp.maximum(boxes[..., 2] - boxes[..., 0], 0) * jnp.maximum(
        boxes[..., 3] - boxes[..., 1], 0
    )


def _pairwise_iou(a, b):
    """a [N, 4], b [M, 4] -> [N, M]."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = _area(a)[:, None] + _area(b)[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register_op("iou_similarity")
def iou_similarity(x, y, *, box_normalized=True):
    """detection/iou_similarity_op.cc: pairwise IoU [N, M]."""
    return _pairwise_iou(x, y)


@register_op("bbox_overlaps")
def bbox_overlaps(x, y):
    return _pairwise_iou(x, y)


@register_op("box_clip")
def box_clip(boxes, im_info):
    """detection/box_clip_op.cc: clip to image (im_info [.., (h, w, ...)])."""
    h = im_info[..., 0:1] - 1
    w = im_info[..., 1:2] - 1
    x1 = jnp.clip(boxes[..., 0], 0, w[..., 0])
    y1 = jnp.clip(boxes[..., 1], 0, h[..., 0])
    x2 = jnp.clip(boxes[..., 2], 0, w[..., 0])
    y2 = jnp.clip(boxes[..., 3], 0, h[..., 0])
    return jnp.stack([x1, y1, x2, y2], axis=-1)


@register_op("box_coder")
def box_coder(prior_box, prior_box_var, target_box, *, code_type="encode_center_size",
              box_normalized=True):
    """detection/box_coder_op.cc: encode/decode boxes against priors.

    encode: target [N, 4] against priors [M, 4] -> [N, M, 4] deltas
    decode: deltas [N, M, 4] (or [N, 4] with M=N) -> boxes
    """
    pw = prior_box[:, 2] - prior_box[:, 0] + (0 if box_normalized else 1)
    ph = prior_box[:, 3] - prior_box[:, 1] + (0 if box_normalized else 1)
    pcx = prior_box[:, 0] + pw * 0.5
    pcy = prior_box[:, 1] + ph * 0.5
    var = prior_box_var if prior_box_var is not None else jnp.ones_like(prior_box)

    if code_type.lower().startswith("encode"):
        tw = target_box[:, 2] - target_box[:, 0] + (0 if box_normalized else 1)
        th = target_box[:, 3] - target_box[:, 1] + (0 if box_normalized else 1)
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10))
        dh = jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        return out / var[None, :, :]
    # decode
    d = target_box * (var[None, :, :] if target_box.ndim == 3 else var)
    if d.ndim == 2:
        d = d[:, None, :]
        squeeze = True
    else:
        squeeze = False
    cx = d[..., 0] * pw[None, :] + pcx[None, :]
    cy = d[..., 1] * ph[None, :] + pcy[None, :]
    w = jnp.exp(d[..., 2]) * pw[None, :]
    h = jnp.exp(d[..., 3]) * ph[None, :]
    off = 0 if box_normalized else 0.5
    out = jnp.stack(
        [cx - w * 0.5, cy - h * 0.5,
         cx + w * 0.5 - (0 if box_normalized else 1),
         cy + h * 0.5 - (0 if box_normalized else 1)], axis=-1
    )
    return out[:, 0, :] if squeeze else out


@register_op("prior_box", num_outputs=2)
def prior_box(input, image, *, min_sizes, max_sizes=(), aspect_ratios=(1.0,),
              variances=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              step_w=0.0, step_h=0.0, offset=0.5, min_max_aspect_ratios_order=False):
    """detection/prior_box_op.cc: SSD anchor boxes for one feature map.

    input [N, C, H, W] feature map, image [N, C, Him, Wim]. Returns
    (boxes [H, W, A, 4], variances [H, W, A, 4]).
    """
    h, w = input.shape[2], input.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    sw = float(step_w) or img_w / w
    sh = float(step_h) or img_h / h

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    whs = []
    for ms in min_sizes:
        ms = float(ms)
        for ar in ars:
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            mx = float(max_sizes[list(min_sizes).index(ms)])
            whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    whs = jnp.asarray(whs)                                    # [A, 2]
    a = whs.shape[0]

    cx = (jnp.arange(w) + float(offset)) * sw                 # [W]
    cy = (jnp.arange(h) + float(offset)) * sh                 # [H]
    cxg, cyg = jnp.meshgrid(cx, cy)                           # [H, W]
    cxg = cxg[:, :, None]
    cyg = cyg[:, :, None]
    bw = whs[None, None, :, 0] / 2.0
    bh = whs[None, None, :, 1] / 2.0
    boxes = jnp.stack(
        [(cxg - bw) / img_w, (cyg - bh) / img_h,
         (cxg + bw) / img_w, (cyg + bh) / img_h], axis=-1
    )                                                         # [H, W, A, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), boxes.shape)
    return boxes, var


@register_op("yolo_box", num_outputs=2)
def yolo_box(x, img_size, *, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """detection/yolo_box_op.cc: decode YOLOv3 head output.

    x [N, A*(5+C), H, W], img_size [N, 2] (h, w). Returns
    (boxes [N, H*W*A, 4], scores [N, H*W*A, C]).
    """
    n, _, h, w = x.shape
    a = len(anchors) // 2
    c = int(class_num)
    x = x.reshape(n, a, 5 + c, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    sxy = float(scale_x_y)
    bias = -0.5 * (sxy - 1.0)
    cx = (jax.nn.sigmoid(x[:, :, 0]) * sxy + bias + grid_x) / w    # [N,A,H,W]
    cy = (jax.nn.sigmoid(x[:, :, 1]) * sxy + bias + grid_y) / h
    anc = jnp.asarray(anchors, x.dtype).reshape(a, 2)
    input_h = float(downsample_ratio) * h
    input_w = float(downsample_ratio) * w
    bw = jnp.exp(x[:, :, 2]) * anc[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * anc[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]

    img_h = img_size[:, 0].reshape(n, 1, 1, 1).astype(x.dtype)
    img_w = img_size[:, 1].reshape(n, 1, 1, 1).astype(x.dtype)
    x1 = (cx - bw / 2) * img_w
    y1 = (cy - bh / 2) * img_h
    x2 = (cx + bw / 2) * img_w
    y2 = (cy + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)             # [N,A,H,W,4]
    keep = conf > conf_thresh
    boxes = boxes * keep[..., None].astype(x.dtype)
    probs = probs * keep[:, :, None].astype(x.dtype)
    boxes = boxes.transpose(0, 2, 3, 1, 4).reshape(n, h * w * a, 4)
    scores = probs.transpose(0, 3, 4, 2, 1).reshape(n, h * w * a, c)
    return boxes, scores


@register_op("roi_align")
def roi_align(x, rois, rois_num, *, pooled_height, pooled_width,
              spatial_scale=1.0, sampling_ratio=-1, aligned=False):
    """detection/roi_align_op.cc: bilinear ROI pooling.

    x [N, C, H, W]; rois [R, 4] in image coords; rois_num [N] rois per
    image (defines each roi's batch index). Output [R, C, ph, pw].
    """
    n, c, h, w = x.shape
    r = rois.shape[0]
    ph, pw = int(pooled_height), int(pooled_width)
    scale = float(spatial_scale)
    off = 0.5 if aligned else 0.0

    batch_idx = jnp.repeat(
        jnp.arange(rois_num.shape[0]), rois_num, total_repeat_length=r
    )

    x1 = rois[:, 0] * scale - off
    y1 = rois[:, 1] * scale - off
    x2 = rois[:, 2] * scale - off
    y2 = rois[:, 3] * scale - off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_w = rw / pw
    bin_h = rh / ph
    ns = int(sampling_ratio) if int(sampling_ratio) > 0 else 2

    # sample grid: [R, ph, ns] y coords x [R, pw, ns] x coords
    iy = (jnp.arange(ph)[None, :, None]
          + (jnp.arange(ns)[None, None, :] + 0.5) / ns)
    sy = y1[:, None, None] + iy * bin_h[:, None, None]       # [R, ph, ns]
    ix = (jnp.arange(pw)[None, :, None]
          + (jnp.arange(ns)[None, None, :] + 0.5) / ns)
    sx = x1[:, None, None] + ix * bin_w[:, None, None]       # [R, pw, ns]

    def bilinear(img, yy, xx):
        """img [C, H, W]; yy [ph*ns], xx [pw*ns] -> [C, ph*ns, pw*ns]"""
        y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        wy1 = jnp.clip(yy - y0, 0, 1)
        wx1 = jnp.clip(xx - x0, 0, 1)
        wy0, wx0 = 1 - wy1, 1 - wx1
        v00 = img[:, y0i][:, :, x0i]
        v01 = img[:, y0i][:, :, x1i]
        v10 = img[:, y1i][:, :, x0i]
        v11 = img[:, y1i][:, :, x1i]
        return (v00 * (wy0[:, None] * wx0[None, :])
                + v01 * (wy0[:, None] * wx1[None, :])
                + v10 * (wy1[:, None] * wx0[None, :])
                + v11 * (wy1[:, None] * wx1[None, :]))

    def per_roi(bi, yy, xx):
        img = x[bi]                                           # [C, H, W]
        vals = bilinear(img, yy.reshape(-1), xx.reshape(-1))  # [C, ph*ns, pw*ns]
        vals = vals.reshape(c, ph, ns, pw, ns)
        return vals.mean(axis=(2, 4))                         # [C, ph, pw]

    return jax.vmap(per_roi)(batch_idx, sy, sx)


@register_op("nms", num_outputs=2)
def nms(boxes, scores, *, iou_threshold=0.5, top_k=-1):
    """Greedy NMS with a FIXED output size: returns (keep_idx [K], num_kept)
    where K = top_k (or N). Suppressed slots hold -1 — the accelerator
    contract (mask, don't shrink); exact host semantics via num_kept.
    """
    n = boxes.shape[0]
    k = n if top_k in (-1, None) else min(int(top_k), n)
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    iou = _pairwise_iou(boxes_s, boxes_s)

    def body(i, keep):
        # box i survives iff no higher-scoring kept box overlaps it
        earlier = jnp.arange(n) < i
        sup = jnp.sum(
            jnp.where(earlier, (iou[i] > iou_threshold) & keep.astype(bool),
                      False)
        ) > 0
        return keep.at[i].set(jnp.where(sup, 0, 1))

    keep = lax.fori_loop(0, n, body, jnp.zeros(n, jnp.int32))
    # compact kept entries to the front, preserving score order
    rank = jnp.cumsum(keep) - 1
    out = jnp.full(k, -1, jnp.int32)
    valid = (keep.astype(bool)) & (rank < k)
    out = out.at[jnp.where(valid, rank, k)].set(
        jnp.where(valid, order, -1).astype(jnp.int32), mode="drop"
    )
    return out, jnp.minimum(jnp.sum(keep), k)


@register_op("multiclass_nms", num_outputs=2)
def multiclass_nms(bboxes, scores, *, score_threshold=0.05, nms_threshold=0.3,
                   keep_top_k=100, background_label=-1):
    """detection/multiclass_nms_op.cc with the fixed-size contract.

    bboxes [N, 4]; scores [C, N]. Returns (out [keep_top_k, 6], num_kept):
    rows are (class, score, x1, y1, x2, y2), padded rows are -1.
    """
    c, n = scores.shape
    k = int(keep_top_k)
    neg_inf = jnp.asarray(-jnp.inf, bboxes.dtype)
    all_rows, all_valid = [], []
    for cls in range(c):
        if cls == background_label:
            continue
        s_raw = scores[cls]
        passes = s_raw >= score_threshold
        # ordering key only — validity is the explicit mask, so legitimate
        # zero/negative scores above the threshold are kept (ADVICE r2)
        s_key = jnp.where(passes, s_raw, neg_inf)
        keep_idx, _ = nms(bboxes, s_key, iou_threshold=nms_threshold, top_k=n)
        gi = jnp.clip(keep_idx, 0, n - 1)
        valid = (keep_idx >= 0) & passes[gi]
        row = jnp.concatenate(
            [jnp.full((n, 1), cls, bboxes.dtype),
             s_raw[gi][:, None],
             bboxes[gi]], axis=1
        )
        all_rows.append(jnp.where(valid[:, None], row, -1.0))
        all_valid.append(valid)
    stacked = jnp.concatenate(all_rows, axis=0)
    valid = jnp.concatenate(all_valid, axis=0)
    order = jnp.argsort(-jnp.where(valid, stacked[:, 1], neg_inf))
    stacked = stacked[order][:k]
    valid = valid[order][:k]
    num = jnp.sum(valid)
    pad = k - stacked.shape[0]
    if pad > 0:
        stacked = jnp.concatenate(
            [stacked, jnp.full((pad, 6), -1.0, stacked.dtype)], axis=0
        )
    return stacked, num
