"""Eager op API.

Reference parity: the generated `core.ops.*` fast functions
(paddle/fluid/pybind/op_function_generator.cc:204) + the python
paddle.tensor/* wrappers. Each wrapper coerces inputs to Tensor, pulls a
PRNG key for stochastic ops, and dispatches through the autograd tracer
(framework/autograd.py apply_op), which records the vjp tape node.
"""
from __future__ import annotations

import builtins

import numpy as np

import jax.numpy as jnp

from ..framework import random as _random
from ..framework.autograd import apply_op, no_grad  # noqa: F401
from ..framework.dtype import convert_dtype, get_default_dtype
from ..framework.tensor import Tensor, to_tensor
from . import kernels as _k  # registers all kernels  # noqa: F401
from . import beam_search as _bs  # noqa: F401
from . import detection as _det  # noqa: F401
from . import linalg_kernels as _la  # noqa: F401
from . import math_extra as _mx  # noqa: F401
from . import metrics_kernels as _mk  # noqa: F401
from . import nn_extra as _nx  # noqa: F401
from . import quantize_kernels as _qk  # noqa: F401
from . import compat as _compat  # noqa: F401  (reference op-type aliases)
from . import fused_ops as _fo  # noqa: F401  (IR-optimizer fusion targets)
from . import niche as _niche  # noqa: F401  (registry tail: tree_conv etc.)
from . import optimizer_kernels as _ok  # noqa: F401
from . import sequence as _seq  # noqa: F401
from .registry import all_ops, get_op, has_op, kernel  # noqa: F401


def _t(x, dtype=None):
    if isinstance(x, Tensor):
        return x
    return to_tensor(x, dtype=dtype)


def _run(name, *tensors, **attrs):
    # Mode-aware dispatch (paddle 2.0 unified API): under enable_static(),
    # ops append OpDescs to the default program instead of executing.
    from ..static.program import Variable, in_static_mode

    if in_static_mode() and (
        builtins.any(isinstance(t, Variable) for t in tensors) or not tensors
    ):
        from ..static.op_append import append_static_op

        return append_static_op(name, tensors, attrs)
    return apply_op(name, kernel(name), tensors, attrs)


# -- binary math -------------------------------------------------------------


def _binary(name):
    def fn(x, y, name_=None):
        x = _t(x)
        y = y if isinstance(y, Tensor) else _t(y, dtype=x.dtype if x.dtype.kind == "f" else None)
        return _run(name, x, y)

    fn.__name__ = name
    return fn


add = _binary("elementwise_add")
subtract = _binary("elementwise_sub")
multiply = _binary("elementwise_mul")
divide = _binary("elementwise_div")
elementwise_pow = _binary("elementwise_pow")
remainder = _binary("elementwise_mod")
mod = remainder
floor_divide = _binary("elementwise_floordiv")
maximum = _binary("elementwise_max")
minimum = _binary("elementwise_min")
atan2 = _binary("atan2")
equal = _binary("equal")
not_equal = _binary("not_equal")
less_than = _binary("less_than")
less_equal = _binary("less_equal")
greater_than = _binary("greater_than")
greater_equal = _binary("greater_equal")
logical_and = _binary("logical_and")
logical_or = _binary("logical_or")
logical_xor = _binary("logical_xor")
bitwise_and = _binary("bitwise_and")
bitwise_or = _binary("bitwise_or")
bitwise_xor = _binary("bitwise_xor")


def logical_not(x):
    return _run("logical_not", _t(x))


def bitwise_not(x):
    return _run("bitwise_not", _t(x))


# -- unary math --------------------------------------------------------------


def _unary(name):
    def fn(x, name_=None):
        return _run(name, _t(x))

    fn.__name__ = name
    return fn


abs = _unary("abs")
exp = _unary("exp")
expm1 = _unary("expm1")
log = _unary("log")
log2 = _unary("log2")
log10 = _unary("log10")
log1p = _unary("log1p")
sqrt = _unary("sqrt")
rsqrt = _unary("rsqrt")
square = _unary("square")
sin = _unary("sin")
cos = _unary("cos")
tan = _unary("tan")
asin = _unary("asin")
acos = _unary("acos")
atan = _unary("atan")
sinh = _unary("sinh")
cosh = _unary("cosh")
asinh = _unary("asinh")
acosh = _unary("acosh")
atanh = _unary("atanh")
tanh = _unary("tanh")
floor = _unary("floor")
ceil = _unary("ceil")
round = _unary("round")
sign = _unary("sign")
reciprocal = _unary("reciprocal")
erf = _unary("erf")
erfinv = _unary("erfinv")
digamma = _unary("digamma")
lgamma = _unary("lgamma")
sigmoid = _unary("sigmoid")
log_sigmoid = _unary("logsigmoid")
isnan = _unary("isnan")
isinf = _unary("isinf")
isfinite = _unary("isfinite")
trunc = _unary("trunc")


def neg(x):
    return scale(_t(x), scale=-1.0)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    out = _run("scale", _t(x), scale=float(scale), bias=float(bias), bias_after_scale=bias_after_scale)
    if act:
        out = globals()[act](out)
    return out


def clip(x, min=None, max=None):
    min = float(min) if min is not None and not isinstance(min, Tensor) else min
    max = float(max) if max is not None and not isinstance(max, Tensor) else max
    if isinstance(min, Tensor):
        min = min.item()
    if isinstance(max, Tensor):
        max = max.item()
    return _run("clip", _t(x), min=min, max=max)


def pow(x, y):
    if isinstance(y, (int, float)):
        return _run("pow", _t(x), factor=float(y))
    return elementwise_pow(x, y)


# -- matrix ------------------------------------------------------------------


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _run("matmul", _t(x), _t(y), transpose_x=transpose_x, transpose_y=transpose_y)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    return _run("mul", _t(x), _t(y), x_num_col_dims=x_num_col_dims, y_num_col_dims=y_num_col_dims)


def bmm(x, y):
    return _run("bmm", _t(x), _t(y))


def dot(x, y):
    return _run("dot", _t(x), _t(y))


def addmm(input, x, y, beta=1.0, alpha=1.0):
    return _run("addmm", _t(input), _t(x), _t(y), beta=beta, alpha=alpha)


def cross(x, y, axis=-1):
    return _run("cross", _t(x), _t(y), axis=axis)


def cholesky(x, upper=False):
    return _run("cholesky", _t(x), upper=upper)


def matrix_power(x, n):
    return _run("matrix_power", _t(x), n=n)


def inverse(x):
    return _run("inverse", _t(x))


def einsum(equation, *operands):
    return _run("einsum", *[_t(o) for o in operands], equation=equation)


def t(x):
    x = _t(x)
    if x.ndim < 2:
        return x
    return transpose(x, [1, 0])


# -- reductions --------------------------------------------------------------


def sum(x, axis=None, dtype=None, keepdim=False):
    out = _run("reduce_sum", _t(x), dim=axis, keep_dim=keepdim)
    if dtype is not None:
        out = cast(out, dtype)
    return out


def mean(x, axis=None, keepdim=False):
    return _run("reduce_mean", _t(x), dim=axis, keep_dim=keepdim)


def max(x, axis=None, keepdim=False):
    return _run("reduce_max", _t(x), dim=axis, keep_dim=keepdim)


def min(x, axis=None, keepdim=False):
    return _run("reduce_min", _t(x), dim=axis, keep_dim=keepdim)


def prod(x, axis=None, keepdim=False):
    return _run("reduce_prod", _t(x), dim=axis, keep_dim=keepdim)


def any(x, axis=None, keepdim=False):
    return _run("reduce_any", _t(x), dim=axis, keep_dim=keepdim)


def all(x, axis=None, keepdim=False):
    return _run("reduce_all", _t(x), dim=axis, keep_dim=keepdim)


def logsumexp(x, axis=None, keepdim=False):
    return _run("logsumexp", _t(x), axis=axis, keepdim=keepdim)


def argmax(x, axis=None, keepdim=False, dtype="int64"):
    return _run("arg_max", _t(x), axis=axis, keepdims=keepdim, dtype=str(convert_dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    return _run("arg_min", _t(x), axis=axis, keepdims=keepdim, dtype=str(convert_dtype(dtype)))


def p_norm(x, p=2, axis=None, keepdim=False, epsilon=1e-12):
    return _run("p_norm", _t(x), porder=float(p), axis=axis, keepdim=keepdim, epsilon=epsilon)


norm = p_norm


def cumsum(x, axis=None):
    return _run("cumsum", _t(x), axis=axis)


def cumprod(x, dim=None):
    return _run("cumprod", _t(x), dim=dim)


# -- manipulation ------------------------------------------------------------


def cast(x, dtype):
    return _run("cast", _t(x), dtype=str(convert_dtype(dtype)))


def reshape(x, shape):
    return _run("reshape", _t(x), shape=tuple(shape))


def transpose(x, perm):
    return _run("transpose", _t(x), perm=tuple(perm))


def flatten(x, start_axis=0, stop_axis=-1):
    return _run("flatten", _t(x), start_axis=start_axis, stop_axis=stop_axis)


def squeeze(x, axis=None):
    return _run("squeeze", _t(x), axes=axis)


def unsqueeze(x, axis):
    return _run("unsqueeze", _t(x), axes=axis)


def concat(xs, axis=0):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _run("concat", *[_t(x) for x in xs], axis=axis)


def split(x, num_or_sections, axis=0):
    outs = _run("split", _t(x), num_or_sections=num_or_sections if isinstance(num_or_sections, int) else tuple(num_or_sections), axis=axis)
    return list(outs)


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


def stack(xs, axis=0):
    return _run("stack", *[_t(x) for x in xs], axis=axis)


def unstack(x, axis=0, num=None):
    return list(_run("unstack", _t(x), axis=axis, num=num))


def unbind(x, axis=0):
    return list(_run("unbind", _t(x), axis=axis))


def slice(x, axes, starts, ends):
    return _run("slice", _t(x), axes=tuple(axes), starts=tuple(starts), ends=tuple(ends))


def strided_slice(x, axes, starts, ends, strides):
    return _run("strided_slice", _t(x), axes=tuple(axes), starts=tuple(starts), ends=tuple(ends), strides=tuple(strides))


def getitem(x, idx):
    # Tensor indices route to gather/gather_nd to stay static-shape friendly
    if isinstance(idx, Tensor):
        if idx.dtype == jnp.bool_:
            raise NotImplementedError(
                "boolean mask indexing produces dynamic shapes; use paddle_tpu.where/masked_select"
            )
        return index_select(x, idx.flatten(), axis=0) if idx.ndim == 1 else gather(x, idx, axis=0)
    if isinstance(idx, tuple):
        idx = tuple(i._array if isinstance(i, Tensor) else i for i in idx)
    return _run("getitem", _t(x), idx=idx)


def gather(x, index, axis=0):
    return _run("gather", _t(x), _t(index), axis=axis)


def gather_nd(x, index):
    return _run("gather_nd", _t(x), _t(index))


def scatter(x, index, updates, overwrite=True):
    return _run("scatter", _t(x), _t(index), _t(updates), overwrite=overwrite)


def scatter_nd_add(x, index, updates):
    return _run("scatter_nd_add", _t(x), _t(index), _t(updates))


def index_select(x, index, axis=0):
    return _run("index_select", _t(x), _t(index), axis=axis)


def index_sample(x, index):
    return _run("index_sample", _t(x), _t(index))


def take_along_axis(x, index, axis):
    return _run("take_along_axis", _t(x), _t(index), axis=axis)


def masked_select(x, mask):
    # dynamic-shape op: executes on host values (not jittable) — paddle parity
    from ..static.program import in_static_mode

    if in_static_mode():
        from ..errors import UnimplementedError

        raise UnimplementedError(
            "operator 'masked_select' has a data-dependent output shape and "
            "cannot appear in a static program; use where/multiply masking "
            "instead"
        )
    arr = np.asarray(_t(x)._array)[np.asarray(_t(mask)._array)]
    return to_tensor(arr)


def masked_fill(x, mask, value):
    return _run("masked_fill", _t(x), _t(mask), value=float(value))


def tile(x, repeat_times):
    return _run("tile", _t(x), repeat_times=tuple(repeat_times))


def expand(x, shape):
    return _run("expand", _t(x), shape=tuple(shape))


def expand_as(x, y):
    return _run("broadcast_to", _t(x), shape=tuple(_t(y).shape))


def broadcast_to(x, shape):
    return _run("broadcast_to", _t(x), shape=tuple(shape))


def where(cond, x=None, y=None):
    if x is None and y is None:
        idx = np.argwhere(np.asarray(_t(cond)._array))
        return to_tensor(idx.astype(np.int64))
    return _run("where", _t(cond), _t(x), _t(y))


def pad(x, paddings, mode="constant", value=0.0):
    return _run("pad", _t(x), paddings=tuple(paddings), mode=mode, value=float(value))


def roll(x, shifts, axis=None):
    return _run("roll", _t(x), shifts=shifts, axis=axis)


def flip(x, axis):
    return _run("flip", _t(x), axis=axis)


def tril(x, diagonal=0):
    return _run("tril", _t(x), diagonal=diagonal)


def triu(x, diagonal=0):
    return _run("triu", _t(x), diagonal=diagonal)


def diag(x, offset=0, padding_value=0.0):
    return _run("diag", _t(x), offset=offset, padding_value=padding_value)


def assign(x, output=None):
    out = _run("assign", _t(x))
    if output is not None:
        output.set_value(out)
        return output
    return out


def one_hot(x, num_classes):
    return _run("one_hot", _t(x), num_classes=num_classes)


def topk(x, k, axis=-1, largest=True, sorted=True):
    vals, idx = _run("top_k", _t(x), k=k, axis=axis, largest=largest, sorted=sorted)
    return vals, idx


def argsort(x, axis=-1, descending=False):
    _, idx = _run("argsort", _t(x), axis=axis, descending=descending)
    return idx


def sort(x, axis=-1, descending=False):
    return _run("sort", _t(x), axis=axis, descending=descending)


def kthvalue(x, k, axis=-1, keepdim=False):
    return _run("kthvalue", _t(x), k=k, axis=axis, keepdim=keepdim)


def meshgrid(*xs):
    return list(_run("meshgrid", *[_t(x) for x in xs]))


def repeat_interleave(x, repeats, axis=None):
    return _run("repeat_interleave", _t(x), repeats=repeats, axis=axis)


def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    return _run("shard_index", _t(x), index_num=index_num, nshards=nshards, shard_id=shard_id, ignore_value=ignore_value)


def numel(x):
    return to_tensor(np.int64(_t(x).size))


def shape(x):
    return to_tensor(np.array(_t(x).shape, dtype=np.int32))


# -- activations -------------------------------------------------------------

relu = _unary("relu")
selu = _unary("selu")
softsign = _unary("softsign")
tanh_shrink = _unary("tanh_shrink")
swish = _unary("swish")
silu = _unary("swish")
mish = _unary("mish")


def relu6(x, threshold=6.0):
    return _run("relu6", _t(x), threshold=threshold)


def leaky_relu(x, negative_slope=0.01):
    return _run("leaky_relu", _t(x), alpha=negative_slope)


def elu(x, alpha=1.0):
    return _run("elu", _t(x), alpha=alpha)


def celu(x, alpha=1.0):
    return _run("celu", _t(x), alpha=alpha)


def gelu(x, approximate=False):
    return _run("gelu", _t(x), approximate=approximate)


def hardsigmoid(x, slope=0.1666667, offset=0.5):
    return _run("hard_sigmoid", _t(x), slope=slope, offset=offset)


def hardswish(x):
    return _run("hard_swish", _t(x))


def hardtanh(x, min=-1.0, max=1.0):
    return _run("hard_tanh", _t(x), min=min, max=max)


def hardshrink(x, threshold=0.5):
    return _run("hard_shrink", _t(x), threshold=threshold)


def softshrink(x, threshold=0.5):
    return _run("softshrink", _t(x), lambda_=threshold)


def softplus(x, beta=1.0, threshold=20.0):
    return _run("softplus", _t(x), beta=beta, threshold=threshold)


def prelu(x, weight):
    return _run("prelu", _t(x), _t(weight))


def softmax(x, axis=-1):
    return _run("softmax", _t(x), axis=axis)


def log_softmax(x, axis=-1):
    return _run("log_softmax", _t(x), axis=axis)


def maxout(x, groups, axis=1):
    return _run("maxout", _t(x), groups=groups, axis=axis)


# -- nn ops ------------------------------------------------------------------


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW"):
    out = _run("conv2d", _t(x), _t(weight), stride=stride, padding=padding,
               dilation=dilation, groups=groups, data_format=data_format)
    if bias is not None:
        caxis = 1 if data_format == "NCHW" else out.ndim - 1
        shape = [1] * out.ndim
        shape[caxis] = -1
        out = add(out, reshape(_t(bias), shape))
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    out = _run("conv1d", _t(x), _t(weight), stride=stride, padding=padding, dilation=dilation, groups=groups)
    if bias is not None:
        out = add(out, reshape(_t(bias), [1, -1, 1]))
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, data_format="NCHW"):
    out = _run("conv2d_transpose", _t(x), _t(weight), stride=stride, padding=padding,
               output_padding=output_padding, dilation=dilation, groups=groups, data_format=data_format)
    if bias is not None:
        out = add(out, reshape(_t(bias), [1, -1, 1, 1]))
    return out


def linear(x, weight, bias=None):
    if bias is None:
        return _run("linear", _t(x), _t(weight))
    return _run("linear", _t(x), _t(weight), _t(bias))


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW"):
    return _run("pool2d", _t(x), kernel_size=kernel_size, stride=stride, padding=padding,
                pooling_type="max", ceil_mode=ceil_mode, data_format=data_format)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, data_format="NCHW"):
    return _run("pool2d", _t(x), kernel_size=kernel_size, stride=stride, padding=padding,
                pooling_type="avg", ceil_mode=ceil_mode, exclusive=exclusive, data_format=data_format)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return _run("adaptive_pool2d", _t(x), output_size=output_size, pooling_type="avg",
                data_format=data_format)


def adaptive_max_pool2d(x, output_size, data_format="NCHW"):
    return _run("adaptive_pool2d", _t(x), output_size=output_size, pooling_type="max",
                data_format=data_format)


def batch_norm(x, running_mean, running_var, weight, bias, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW"):
    from ..static.program import Variable, in_static_mode

    if in_static_mode() and isinstance(x, Variable):
        # static: alias the stat outputs back onto the running-stat vars so
        # the executor's persistable write-back updates them in the scope
        from ..static.op_append import append_static_op

        alias = {1: running_mean.name, 2: running_var.name} if training else None
        y, _, _ = append_static_op(
            "batch_norm",
            [x, weight, bias, running_mean, running_var],
            dict(momentum=momentum, epsilon=epsilon, training=training,
                 data_format=data_format),
            alias_outputs=alias,
        )
        return y
    y, new_mean, new_var = _run(
        "batch_norm", _t(x), _t(weight), _t(bias), _t(running_mean), _t(running_var),
        momentum=momentum, epsilon=epsilon, training=training, data_format=data_format,
    )
    if training:
        with no_grad():
            running_mean.set_value(new_mean.detach())
            running_var.set_value(new_var.detach())
    return y


def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5):
    if normalized_shape is not None:
        n = len(normalized_shape) if isinstance(normalized_shape, (list, tuple)) else 1
        begin_norm_axis = -n
    else:
        begin_norm_axis = -1
    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        if weight is None:
            raise ValueError("bias without weight unsupported; pass both")
        args.append(_t(bias))
    return _run("layer_norm", *args, epsilon=epsilon, begin_norm_axis=begin_norm_axis)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5):
    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return _run("group_norm", *args, groups=num_groups, epsilon=epsilon)


def instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return _run("instance_norm", *args, epsilon=epsilon)


def embedding(x, weight, padding_idx=None, sparse=False):
    return _run("lookup_table", _t(weight), _t(x), padding_idx=-1 if padding_idx is None else padding_idx)


def dropout(x, p=0.5, training=True, mode="upscale_in_train"):
    if not training or p == 0.0:
        return _t(x)
    key = _random.split_key()
    return _run("dropout", _t(x), p=p, training=training, mode=mode, key=key)


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW"):
    if size is not None and not isinstance(size, (list, tuple)):
        size = (int(size), int(size))
    return _run("interpolate", _t(x), size=tuple(size) if size else None,
                scale_factor=scale_factor, mode=mode, align_corners=align_corners, data_format=data_format)


def pixel_shuffle(x, upscale_factor):
    return _run("pixel_shuffle", _t(x), upscale_factor=upscale_factor)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    return _run("unfold", _t(x), kernel_sizes=kernel_sizes, strides=strides, paddings=paddings, dilations=dilations)


# -- losses ------------------------------------------------------------------


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1, ignore_index=-100):
    return _run("softmax_with_cross_entropy", _t(logits), _t(label),
                soft_label=soft_label, axis=axis, ignore_index=ignore_index)


def cross_entropy(input, label, weight=None, soft_label=False, axis=-1,
                  ignore_index=-100, reduction="mean", use_softmax=True):
    tensors = [_t(input), _t(label)]
    attrs = dict(soft_label=soft_label, axis=axis, ignore_index=ignore_index,
                 reduction=reduction, use_softmax=use_softmax)
    if weight is not None:
        attrs["weight"] = _t(weight)._array
    return _run("cross_entropy", *tensors, **attrs)


def mse_loss(input, label, reduction="mean"):
    return _run("mse_loss", _t(input), _t(label), reduction=reduction)


def l1_loss(input, label, reduction="mean"):
    return _run("l1_loss", _t(input), _t(label), reduction=reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    return _run("smooth_l1_loss", _t(input), _t(label), reduction=reduction, delta=delta)


def binary_cross_entropy(input, label, reduction="mean"):
    return _run("bce_loss", _t(input), _t(label), reduction=reduction)


def binary_cross_entropy_with_logits(logits, label, reduction="mean", pos_weight=None):
    attrs = dict(reduction=reduction)
    if pos_weight is not None:
        attrs["pos_weight"] = _t(pos_weight)._array
    return _run("bce_with_logits", _t(logits), _t(label), **attrs)


def nll_loss(input, label, reduction="mean", ignore_index=-100):
    return _run("nll_loss", _t(input), _t(label), reduction=reduction, ignore_index=ignore_index)


def kl_div(input, label, reduction="mean"):
    return _run("kl_div", _t(input), _t(label), reduction=reduction)


def log_loss(input, label, epsilon=1e-4):
    return _run("log_loss", _t(input), _t(label), epsilon=epsilon)


def square_error_cost(input, label):
    return _run("square_error_cost", _t(input), _t(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    return _run("margin_ranking_loss", _t(input), _t(other), _t(label), margin=margin, reduction=reduction)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return _run("cosine_similarity", _t(x1), _t(x2), axis=axis, eps=eps)


def accuracy(input, label, k=1):
    _, idx = topk(_t(input), k)
    return _run("accuracy", idx, _t(label))


# -- creation ----------------------------------------------------------------


def zeros(shape, dtype=None):
    return to_tensor(jnp.zeros(tuple(shape), convert_dtype(dtype)))


def ones(shape, dtype=None):
    return to_tensor(jnp.ones(tuple(shape), convert_dtype(dtype)))


def full(shape, fill_value, dtype=None):
    if dtype is None and isinstance(fill_value, bool):
        dtype = "bool"
    elif dtype is None and isinstance(fill_value, int):
        dtype = "int64"
    return to_tensor(jnp.full(tuple(shape), fill_value, convert_dtype(dtype)))


def zeros_like(x, dtype=None):
    x = _t(x)
    return to_tensor(jnp.zeros(x._array.shape, convert_dtype(dtype) if dtype else x._array.dtype))


def ones_like(x, dtype=None):
    x = _t(x)
    return to_tensor(jnp.ones(x._array.shape, convert_dtype(dtype) if dtype else x._array.dtype))


def full_like(x, fill_value, dtype=None):
    x = _t(x)
    return to_tensor(jnp.full(x._array.shape, fill_value, convert_dtype(dtype) if dtype else x._array.dtype))


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = "int64" if _is_int(start) and _is_int(end) and _is_int(step) else "float32"
    return to_tensor(jnp.arange(start, end, step, dtype=convert_dtype(dtype)))


def _is_int(v):
    return isinstance(v, (int, np.integer))


def linspace(start, stop, num, dtype=None):
    return to_tensor(jnp.linspace(start, stop, num, dtype=convert_dtype(dtype or "float32")))


def eye(num_rows, num_columns=None, dtype=None):
    return to_tensor(jnp.eye(num_rows, num_columns, dtype=convert_dtype(dtype)))


def diag_embed(x, offset=0):
    arr = _t(x)._array
    n = arr.shape[-1] + (offset if offset >= 0 else -offset)
    out = jnp.zeros(arr.shape[:-1] + (n, n), arr.dtype)
    idx = jnp.arange(arr.shape[-1])
    if offset >= 0:
        out = out.at[..., idx, idx + offset].set(arr)
    else:
        out = out.at[..., idx - offset, idx].set(arr)
    return to_tensor(out)


# -- RNG ---------------------------------------------------------------------


def uniform(shape, dtype=None, min=-1.0, max=1.0):
    return _run("uniform_random", shape=tuple(shape), min=float(min), max=float(max),
                dtype=str(convert_dtype(dtype)), key=_random.split_key())


def rand(shape, dtype=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype=None):
    return _run("gaussian_random", shape=tuple(shape), mean=0.0, std=1.0,
                dtype=str(convert_dtype(dtype)), key=_random.split_key())


def normal(mean=0.0, std=1.0, shape=None):
    return _run("gaussian_random", shape=tuple(shape), mean=float(mean), std=float(std),
                dtype="float32", key=_random.split_key())


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return _run("randint", low=int(low), high=int(high), shape=tuple(shape),
                dtype=str(convert_dtype(dtype)), key=_random.split_key())


def randperm(n, dtype="int64"):
    return _run("randperm", n=n, dtype=str(convert_dtype(dtype)), key=_random.split_key())


def bernoulli(x):
    return _run("bernoulli", _t(x), key=_random.split_key())


def multinomial(x, num_samples=1, replacement=False):
    return _run("multinomial", _t(x), num_samples=num_samples, replacement=replacement,
                key=_random.split_key())


# -- sequence (ragged) family ------------------------------------------------
# Dense padded [B, T, ...] + lengths [B] replaces LoD (see ops/sequence.py).


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    return _run("sequence_mask", _t(lengths), maxlen=maxlen, out_dtype=str(dtype))


def sequence_pad(x, lengths, maxlen=None, pad_value=0.0):
    return _run("sequence_pad", _t(x), _t(lengths), maxlen=maxlen,
                pad_value=float(pad_value))


def sequence_unpad(x, lengths):
    return _run("sequence_unpad", _t(x), _t(lengths))


def sequence_pool(x, lengths, pooltype="SUM"):
    return _run("sequence_pool", _t(x), _t(lengths), pooltype=pooltype)


def segment_pool(x, segment_ids, num_segments, pooltype="SUM"):
    return _run("segment_pool", _t(x), _t(segment_ids),
                num_segments=int(num_segments), pooltype=pooltype)


def sequence_softmax(x, lengths):
    return _run("sequence_softmax", _t(x), _t(lengths))


def sequence_reverse(x, lengths):
    return _run("sequence_reverse", _t(x), _t(lengths))


def sequence_slice(x, offset, length, maxlen=None):
    return _run("sequence_slice", _t(x), _t(offset), _t(length), maxlen=maxlen)


def sequence_concat(x, xlen, y, ylen):
    return _run("sequence_concat", _t(x), _t(xlen), _t(y), _t(ylen))


def sequence_expand(x, rep):
    return _run("sequence_expand", _t(x), _t(rep))


def sequence_enumerate(x, win_size, pad_value=0):
    return _run("sequence_enumerate", _t(x), win_size=int(win_size),
                pad_value=pad_value)


def sequence_erase(x, tokens=()):
    return _run("sequence_erase", _t(x), tokens=tuple(tokens))


def sequence_conv(x, lengths, weight, context_length, context_start=None):
    return _run("sequence_conv", _t(x), _t(lengths), _t(weight),
                context_length=int(context_length), context_start=context_start)


def sequence_first_step(x, lengths):
    return _run("sequence_first_step", _t(x), _t(lengths))


def sequence_last_step(x, lengths):
    return _run("sequence_last_step", _t(x), _t(lengths))


# -- beam search -------------------------------------------------------------


def beam_search_step(log_probs, beam_scores, beam_size, end_id=None,
                     first_step=False):
    return _run("beam_search_step", _t(log_probs), _t(beam_scores),
                beam_size=int(beam_size), end_id=end_id, first_step=first_step)


def beam_search_decode(parents, tokens, final_scores, end_id=None):
    return _run("beam_search_decode", _t(parents), _t(tokens), _t(final_scores),
                end_id=end_id)


# -- metrics -----------------------------------------------------------------


def auc(predict, label, num_thresholds=4095, stat_pos=None, stat_neg=None,
        curve="ROC"):
    from .registry import kernel as _kernel
    # stats are optional arrays -> pass via attrs to keep arity fixed
    return _run("auc", _t(predict), _t(label), num_thresholds=num_thresholds,
                stat_pos=None if stat_pos is None else _t(stat_pos)._array,
                stat_neg=None if stat_neg is None else _t(stat_neg)._array,
                curve=curve)


def precision_recall(predict, label, num_classes):
    return _run("precision_recall", _t(predict), _t(label),
                num_classes=int(num_classes))


# -- detection ---------------------------------------------------------------


def iou_similarity(x, y, box_normalized=True):
    return _run("iou_similarity", _t(x), _t(y), box_normalized=box_normalized)


def bbox_overlaps(x, y):
    return _run("bbox_overlaps", _t(x), _t(y))


def box_clip(boxes, im_info):
    return _run("box_clip", _t(boxes), _t(im_info))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True):
    pb = _t(prior_box)
    # prior_box_var=None is part of the reference API (ones variance)
    var = (_t(prior_box_var) if prior_box_var is not None
           else ones(pb.shape, str(pb.dtype)))
    return _run("box_coder", pb, var, _t(target_box),
                code_type=code_type, box_normalized=box_normalized)


def prior_box(input, image, min_sizes, max_sizes=(), aspect_ratios=(1.0,),
              variances=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5):
    return _run("prior_box", _t(input), _t(image), min_sizes=tuple(min_sizes),
                max_sizes=tuple(max_sizes), aspect_ratios=tuple(aspect_ratios),
                variances=tuple(variances), flip=flip, clip=clip,
                step_w=steps[0], step_h=steps[1], offset=offset)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    return _run("yolo_box", _t(x), _t(img_size), anchors=tuple(anchors),
                class_num=int(class_num), conf_thresh=conf_thresh,
                downsample_ratio=downsample_ratio, clip_bbox=clip_bbox,
                scale_x_y=scale_x_y)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=False):
    if not isinstance(output_size, (tuple, list)):
        output_size = (output_size, output_size)
    return _run("roi_align", _t(x), _t(boxes), _t(boxes_num),
                pooled_height=output_size[0], pooled_width=output_size[1],
                spatial_scale=spatial_scale, sampling_ratio=sampling_ratio,
                aligned=aligned)


def nms(boxes, scores, iou_threshold=0.5, top_k=-1):
    return _run("nms", _t(boxes), _t(scores), iou_threshold=iou_threshold,
                top_k=top_k)


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_threshold=0.3,
                   keep_top_k=100, background_label=-1):
    return _run("multiclass_nms", _t(bboxes), _t(scores),
                score_threshold=score_threshold, nms_threshold=nms_threshold,
                keep_top_k=keep_top_k, background_label=background_label)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    return _run("sigmoid_focal_loss", _t(x), _t(label), _t(fg_num),
                gamma=gamma, alpha=alpha)


def anchor_generator(x, anchor_sizes, aspect_ratios, stride,
                     variances=(0.1, 0.1, 0.2, 0.2), offset=0.5):
    return _run("anchor_generator", _t(x), anchor_sizes=tuple(anchor_sizes),
                aspect_ratios=tuple(aspect_ratios), stride=tuple(stride),
                variances=tuple(variances), offset=offset)


def density_prior_box(x, image, densities, fixed_sizes, fixed_ratios,
                      variances=(0.1, 0.1, 0.2, 0.2), step=(0.0, 0.0),
                      offset=0.5, clip=False):
    return _run("density_prior_box", _t(x), _t(image),
                densities=tuple(densities), fixed_sizes=tuple(fixed_sizes),
                fixed_ratios=tuple(fixed_ratios), variances=tuple(variances),
                step=tuple(step), offset=offset, clip=clip)


def polygon_box_transform(x):
    return _run("polygon_box_transform", _t(x))


def bipartite_match(dist, match_type="bipartite", dist_threshold=0.5):
    return _run("bipartite_match", _t(dist), match_type=match_type,
                dist_threshold=dist_threshold)


def target_assign(x, match_indices, neg_value=0.0):
    return _run("target_assign", _t(x), _t(match_indices),
                neg_value=neg_value)


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=4.135166556742356):
    return _run("box_decoder_and_assign", _t(prior_box),
                _t(prior_box_var) if prior_box_var is not None else None,
                _t(target_box), _t(box_score), box_clip=box_clip)


def matrix_nms(bboxes, scores, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0):
    return _run("matrix_nms", _t(bboxes), _t(scores),
                score_threshold=score_threshold,
                post_threshold=post_threshold, nms_top_k=nms_top_k,
                keep_top_k=keep_top_k, use_gaussian=use_gaussian,
                gaussian_sigma=gaussian_sigma,
                background_label=background_label)


def locality_aware_nms(bboxes, scores, score_threshold=0.05,
                       nms_threshold=0.3, keep_top_k=100):
    return _run("locality_aware_nms", _t(bboxes), _t(scores),
                score_threshold=score_threshold,
                nms_threshold=nms_threshold, keep_top_k=keep_top_k)


def mine_hard_examples(cls_loss, match_indices, neg_pos_ratio=3.0,
                       mining_type="max_negative", sample_size=None):
    return _run("mine_hard_examples", _t(cls_loss), _t(match_indices),
                neg_pos_ratio=neg_pos_ratio, mining_type=mining_type,
                sample_size=sample_size)


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0):
    return _run("generate_proposals", _t(scores), _t(bbox_deltas),
                _t(im_info), _t(anchors), _t(variances),
                pre_nms_top_n=pre_nms_top_n, post_nms_top_n=post_nms_top_n,
                nms_thresh=nms_thresh, min_size=min_size, eta=eta)


def distribute_fpn_proposals(rois, min_level=2, max_level=5, refer_level=4,
                             refer_scale=224):
    return _run("distribute_fpn_proposals", _t(rois), min_level=min_level,
                max_level=max_level, refer_level=refer_level,
                refer_scale=refer_scale)


def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n=1000):
    return _run("collect_fpn_proposals", _t(multi_rois), _t(multi_scores),
                post_nms_top_n=post_nms_top_n)


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3):
    return _run("retinanet_detection_output", _t(bboxes), _t(scores),
                _t(anchors), _t(im_info), score_threshold=score_threshold,
                nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                nms_threshold=nms_threshold)


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh=0.7, downsample_ratio=32,
                use_label_smooth=False):
    return _run("yolov3_loss", _t(x), _t(gt_box), _t(gt_label),
                anchors=tuple(anchors), anchor_mask=tuple(anchor_mask),
                class_num=class_num, ignore_thresh=ignore_thresh,
                downsample_ratio=downsample_ratio,
                use_label_smooth=use_label_smooth)


def rpn_target_assign(anchors, gt_boxes, rpn_batch_size_per_im=256,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    return _run("rpn_target_assign", _t(anchors), _t(gt_boxes),
                key=_random.split_key(),
                rpn_batch_size_per_im=rpn_batch_size_per_im,
                rpn_fg_fraction=rpn_fg_fraction,
                rpn_positive_overlap=rpn_positive_overlap,
                rpn_negative_overlap=rpn_negative_overlap,
                use_random=use_random)


# -- 3D conv/pool, deformable, data_norm, roi pools, shuffles ----------------


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    out = _run("conv3d", _t(x), _t(weight), stride=stride, padding=padding,
               dilation=dilation, groups=groups, data_format=data_format)
    if bias is not None:
        out = add(out, reshape(_t(bias), [1, -1, 1, 1, 1]))
    return out


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW"):
    out = _run("conv3d_transpose", _t(x), _t(weight), stride=stride,
               padding=padding, output_padding=output_padding,
               dilation=dilation, groups=groups, data_format=data_format)
    if bias is not None:
        out = add(out, reshape(_t(bias), [1, -1, 1, 1, 1]))
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NCDHW"):
    return _run("pool3d", _t(x), kernel_size=kernel_size, stride=stride,
                padding=padding, pooling_type="max", ceil_mode=ceil_mode,
                data_format=data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCDHW"):
    return _run("pool3d", _t(x), kernel_size=kernel_size, stride=stride,
                padding=padding, pooling_type="avg", ceil_mode=ceil_mode,
                exclusive=exclusive, data_format=data_format)


def deformable_conv(x, offset, mask, weight, bias=None, stride=1, padding=0,
                    dilation=1, deformable_groups=1, groups=1,
                    im2col_step=1):
    out = _run("deformable_conv", _t(x), _t(offset),
               _t(mask) if mask is not None else None, _t(weight),
               stride=stride, padding=padding, dilation=dilation,
               deformable_groups=deformable_groups, groups=groups,
               im2col_step=im2col_step)
    if bias is not None:
        out = add(out, reshape(_t(bias), [1, -1, 1, 1]))
    return out


def data_norm(x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4):
    return _run("data_norm", _t(x), _t(batch_size), _t(batch_sum),
                _t(batch_square_sum), epsilon=epsilon)


def roi_pool(x, rois, batch_indices=None, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    return _run("roi_pool", _t(x), _t(rois),
                batch_indices=None if batch_indices is None
                else _t(batch_indices)._array,
                pooled_height=pooled_height, pooled_width=pooled_width,
                spatial_scale=spatial_scale)


def psroi_pool(x, rois, output_channels, pooled_height, pooled_width,
               spatial_scale=1.0, batch_indices=None):
    return _run("psroi_pool", _t(x), _t(rois),
                batch_indices=None if batch_indices is None
                else _t(batch_indices)._array,
                output_channels=output_channels,
                pooled_height=pooled_height, pooled_width=pooled_width,
                spatial_scale=spatial_scale)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    return _run("pixel_unshuffle", _t(x), downscale_factor=downscale_factor,
                data_format=data_format)


def channel_shuffle(x, groups, data_format="NCHW"):
    return _run("channel_shuffle", _t(x), groups=groups,
                data_format=data_format)


# -- 2.0 tensor-API tail (python/paddle/tensor/ coverage) --------------------

floor_mod = mod


def increment(x, value=1.0):
    """fluid increment op: x + value (in the 2.0 API, returns new)."""
    return add(_t(x), to_tensor(value))


def multiplex(inputs, index):
    """operators/multiplex_op.cc: out[i] = inputs[index[i]][i]."""
    stacked = stack([_t(t) for t in inputs], axis=0)
    idx = reshape(_t(index), [-1])
    arr = stacked._array[
        idx._array.astype("int32"), jnp.arange(idx._array.shape[0])
    ]
    return to_tensor(arr)


def stanh(x, scale_a=0.67, scale_b=1.7159):
    """operators/activation_op.cc stanh: b * tanh(a * x)."""
    return scale(tanh(scale(_t(x), scale_a)), scale_b)


def inner(x, y):
    a, b = _t(x), _t(y)
    return to_tensor(jnp.inner(a._array, b._array))


def outer(x, y):
    a, b = _t(x), _t(y)
    return to_tensor(jnp.outer(a._array, b._array))


def rank(x):
    """paddle.rank: the number of dimensions (attribute.py)."""
    return to_tensor(np.asarray(len(_t(x).shape), np.int32))


def is_complex(x):
    return jnp.issubdtype(_t(x)._array.dtype, jnp.complexfloating)


def is_integer(x):
    return jnp.issubdtype(_t(x)._array.dtype, jnp.integer)


def is_empty(x):
    return to_tensor(np.asarray(_t(x)._array.size == 0))


def empty(shape, dtype=None):
    """paddle.empty — uninitialized memory doesn't exist under XLA's
    value semantics; zeros have identical cost post-fusion."""
    return zeros(shape, dtype)


def empty_like(x, dtype=None):
    return zeros_like(_t(x), dtype)


def diagflat(x, offset=0):
    return to_tensor(jnp.diagflat(_t(x)._array, k=offset))


def clone(x):
    t = _t(x)
    return to_tensor(jnp.copy(t._array))


def dist(x, y, p=2):
    """paddle.dist: p-norm of (x - y)."""
    d = subtract(_t(x), _t(y))
    arr = d._array.reshape(-1)
    if p == float("inf"):
        return to_tensor(jnp.max(jnp.abs(arr)))
    if p == 0:
        return to_tensor(jnp.sum(arr != 0).astype(arr.dtype))
    return to_tensor(jnp.sum(jnp.abs(arr) ** p) ** (1.0 / p))


def mv(x, vec):
    return matmul(_t(x), _t(vec))


def poisson(x):
    return to_tensor(
        jax.random.poisson(_random.split_key(), _t(x)._array)
        .astype(_t(x)._array.dtype)
    )


def standard_normal(shape, dtype=None):
    return randn(shape, dtype)


def reverse(x, axis):
    return flip(_t(x), axis)


def scatter_nd(index, updates, shape):
    z = zeros(list(shape), str(_t(updates)._array.dtype))
    return scatter_nd_add(z, _t(index), _t(updates))


def put_along_axis(x, indices, values, axis, reduce="assign"):
    t, idx = _t(x), _t(indices)
    v = _t(values) if not isinstance(values, (int, float)) else None
    varr = (v._array if v is not None
            else jnp.full(idx._array.shape, values, t._array.dtype))
    varr = jnp.broadcast_to(varr, idx._array.shape).astype(t._array.dtype)
    if reduce == "assign":
        out = jnp.put_along_axis(
            t._array, idx._array, varr, axis=axis, inplace=False
        )
    elif reduce == "add":
        out = t._array
        dims = list(range(out.ndim))
        idxs = jnp.meshgrid(
            *[jnp.arange(s) for s in idx._array.shape], indexing="ij"
        )
        idxs[axis] = idx._array
        out = out.at[tuple(idxs)].add(varr)
    else:
        raise ValueError(f"unsupported reduce mode {reduce!r}")
    return to_tensor(out)


# -- linalg ------------------------------------------------------------------


def det(x):
    return _run("det", _t(x))


def slogdet(x):
    return _run("slogdet", _t(x))


def matrix_rank(x, tol=None, hermitian=False):
    return _run("matrix_rank", _t(x), tol=tol, hermitian=hermitian)


def solve(a, b):
    return _run("solve", _t(a), _t(b))


def triangular_solve(a, b, upper=True, transpose=False, unitriangular=False):
    return _run("triangular_solve", _t(a), _t(b), upper=upper,
                transpose=transpose, unitriangular=unitriangular)


def cholesky_solve(b, l, upper=False):
    return _run("cholesky_solve", _t(b), _t(l), upper=upper)


def lstsq(a, b, rcond=None):
    return _run("lstsq", _t(a), _t(b), rcond=rcond)


def svd(x, full_matrices=False):
    return _run("svd", _t(x), full_matrices=full_matrices)


def qr(x, mode="reduced"):
    return _run("qr", _t(x), mode=mode)


def lu(x):
    return _run("lu", _t(x))


def eig(x):
    return _run("eig", _t(x))


def eigh(x, UPLO="L"):
    return _run("eigh", _t(x), UPLO=UPLO)


def eigvalsh(x, UPLO="L"):
    return _run("eigvalsh", _t(x), UPLO=UPLO)


def pinv(x, rcond=1e-15, hermitian=False):
    return _run("pinv", _t(x), rcond=rcond, hermitian=hermitian)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    return _run("matrix_norm", _t(x), p=p, axis=tuple(axis), keepdim=keepdim)


def trace(x, offset=0, axis1=0, axis2=1):
    return _run("trace", _t(x), offset=offset, axis1=axis1, axis2=axis2)


def kron(x, y):
    return _run("kron", _t(x), _t(y))


def cov(x, rowvar=True, ddof=True):
    return _run("cov", _t(x), rowvar=rowvar, ddof=ddof)


def corrcoef(x, rowvar=True):
    return _run("corrcoef", _t(x), rowvar=rowvar)


def householder_product(x, tau):
    return _run("householder_product", _t(x), _t(tau))


def multi_dot(arrays):
    return _run("multi_dot", *[_t(a) for a in arrays])


# -- statistics / search extras ----------------------------------------------


def std(x, axis=None, unbiased=True, keepdim=False):
    return _run("std", _t(x), axis=axis, unbiased=unbiased, keepdim=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False):
    return _run("var", _t(x), axis=axis, unbiased=unbiased, keepdim=keepdim)


def median(x, axis=None, keepdim=False):
    return _run("median", _t(x), axis=axis, keepdim=keepdim)


def nanmedian(x, axis=None, keepdim=False):
    return _run("nanmedian", _t(x), axis=axis, keepdim=keepdim)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return _run("quantile", _t(x), q=q, axis=axis, keepdim=keepdim,
                interpolation=interpolation)


def mode(x, axis=-1, keepdim=False):
    return _run("mode", _t(x), axis=axis, keepdim=keepdim)


def histogram(x, bins=100, min=0, max=0, weight=None, density=False):
    return _run("histogram", _t(x), bins=bins, min=min, max=max,
                weight=None if weight is None else _t(weight)._array,
                density=density)


def bincount(x, weights=None, minlength=0, length=None):
    return _run("bincount", _t(x),
                weights=None if weights is None else _t(weights)._array,
                minlength=minlength, length=length)


def nansum(x, axis=None, keepdim=False):
    return _run("nansum", _t(x), axis=axis, keepdim=keepdim)


def nanmean(x, axis=None, keepdim=False):
    return _run("nanmean", _t(x), axis=axis, keepdim=keepdim)


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    return _run("searchsorted", _t(sorted_sequence), _t(values),
                out_int32=out_int32, right=right)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None):
    out = _run("unique", _t(x), return_index=return_index,
               return_inverse=return_inverse, return_counts=return_counts,
               axis=axis)
    vals, index, inverse, counts = out
    res = [vals]
    if return_index:
        res.append(index)
    if return_inverse:
        res.append(inverse)
    if return_counts:
        res.append(counts)
    return res[0] if len(res) == 1 else tuple(res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    out = _run("unique_consecutive", _t(x), return_inverse=return_inverse,
               return_counts=return_counts, axis=axis)
    vals, inverse, counts = out
    res = [vals]
    if return_inverse:
        res.append(inverse)
    if return_counts:
        res.append(counts)
    return res[0] if len(res) == 1 else tuple(res)


def nonzero(x, as_tuple=False):
    return _run("nonzero", _t(x), as_tuple=as_tuple)


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return _run("allclose", _t(x), _t(y), rtol=rtol, atol=atol,
                equal_nan=equal_nan)


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return _run("isclose", _t(x), _t(y), rtol=rtol, atol=atol,
                equal_nan=equal_nan)


def equal_all(x, y):
    return _run("equal_all", _t(x), _t(y))


# -- pointwise extras --------------------------------------------------------


def lerp(x, y, weight):
    return _run("lerp", _t(x), _t(y), _t(weight))


def logit(x, eps=None):
    return _run("logit", _t(x), eps=eps)


def logaddexp(x, y):
    return _run("logaddexp", _t(x), _t(y))


def heaviside(x, y):
    return _run("heaviside", _t(x), _t(y))


def frac(x):
    return _run("frac", _t(x))


def gcd(x, y):
    return _run("gcd", _t(x), _t(y))


def lcm(x, y):
    return _run("lcm", _t(x), _t(y))


def rad2deg(x):
    return _run("rad2deg", _t(x))


def deg2rad(x):
    return _run("deg2rad", _t(x))


def diff(x, n=1, axis=-1):
    return _run("diff", _t(x), n=n, axis=axis)


def amax(x, axis=None, keepdim=False):
    return _run("amax", _t(x), axis=axis, keepdim=keepdim)


def amin(x, axis=None, keepdim=False):
    return _run("amin", _t(x), axis=axis, keepdim=keepdim)


def angle(x):
    return _run("angle", _t(x))


def conj(x):
    return _run("conj", _t(x))


def real(x):
    return _run("real", _t(x))


def imag(x):
    return _run("imag", _t(x))


def as_complex(x):
    return _run("as_complex", _t(x))


def as_real(x):
    return _run("as_real", _t(x))


def nextafter(x, y):
    return _run("nextafter", _t(x), _t(y))


def ldexp(x, y):
    return _run("ldexp", _t(x), _t(y))


def copysign(x, y):
    return _run("copysign", _t(x), _t(y))


def hypot(x, y):
    return _run("hypot", _t(x), _t(y))


def i0(x):
    return _run("i0", _t(x))


def sinc(x):
    return _run("sinc", _t(x))


def signbit(x):
    return _run("signbit", _t(x))


def label_smooth(label, prior_dist=None, epsilon=0.1):
    return _run("label_smooth", _t(label), epsilon=epsilon,
                prior_dist=None if prior_dist is None else _t(prior_dist)._array)


def glu(x, axis=-1):
    return _run("glu", _t(x), axis=axis)


def rot90(x, k=1, axes=(0, 1)):
    return _run("rot90", _t(x), k=k, axes=tuple(axes))


def pad3d(x, paddings, mode="constant", value=0.0, data_format="NCDHW"):
    return _run("pad3d", _t(x), paddings=tuple(paddings), mode=mode,
                value=value, data_format=data_format)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    return _run("grid_sample", _t(x), _t(grid), mode=mode,
                padding_mode=padding_mode, align_corners=align_corners)


def affine_grid(theta, out_shape, align_corners=True):
    return _run("affine_grid", _t(theta), out_shape=tuple(out_shape),
                align_corners=align_corners)
