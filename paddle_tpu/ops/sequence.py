"""Sequence (ragged) op family — the LoD replacement.

Reference parity: paddle/fluid/operators/sequence_ops/ (sequence_pad, pool,
expand, softmax, concat, reverse, slice, conv, mask, enumerate, erase,
first/last step) over LoDTensor offsets (framework/lod_tensor.h:241).

TPU-native ragged design (SURVEY.md §5/§7): XLA wants static shapes, so a
ragged batch is represented ONE of two ways instead of LoD offsets:

1. **padded + lengths** — dense ``[B, T, ...]`` plus ``lengths [B]`` (the
   representation every op here consumes/produces). Masking against
   ``lengths`` replaces offset arithmetic, and everything jits.
2. **flat + segment_ids** — ``[N, ...]`` values with a ``segment_ids [N]``
   row map, for pooling over variable rows (``segment_pool``), backed by
   ``jax.ops.segment_*`` which lower to efficient sorted-scatter on TPU.

Conversions between the reference's flat-LoD world and this one:
``sequence_pad`` (flat+lengths -> padded), ``sequence_unpad`` (padded ->
flat; output size is data-dependent so it is eager-only, like
masked_select). Ops whose output *shape* depends on data (expand, erase)
are eager-only and documented as such; everything else traces.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

_NEG_INF = -1e9


def _time_mask(lengths, maxlen, dtype=jnp.bool_):
    """[B, T] validity mask from lengths."""
    t = jnp.arange(maxlen)
    return (t[None, :] < lengths[:, None]).astype(dtype)


def _eager_only_maxlen(name, lengths):
    if isinstance(lengths, jax.core.Tracer):
        raise NotImplementedError(
            f"{name} with maxlen=None derives the output length from the "
            "data; pass a static maxlen= under jit/tracing, or call it "
            "eagerly"
        )


@register_op("sequence_mask")
def sequence_mask(lengths, *, maxlen=None, out_dtype="int64"):
    """operators/sequence_ops/sequence_mask_op.cc."""
    if maxlen is None:
        _eager_only_maxlen("sequence_mask", lengths)
        maxlen = int(lengths.max())
    else:
        maxlen = int(maxlen)
    return _time_mask(lengths, maxlen, jnp.dtype(out_dtype))


@register_op("sequence_pad", num_outputs=2)
def sequence_pad(x, lengths, *, maxlen=None, pad_value=0.0):
    """Flat [N, ...] + lengths [B] -> padded [B, maxlen, ...] + lengths.

    sequence_pad_op.cc consumes LoD offsets; offsets here are cumsum of
    lengths. Gather indices are clipped so the op stays jittable.
    """
    b = lengths.shape[0]
    if maxlen is None:
        _eager_only_maxlen("sequence_pad", lengths)
        maxlen = int(lengths.max())
    else:
        maxlen = int(maxlen)
    offsets = jnp.concatenate([jnp.zeros(1, lengths.dtype),
                               jnp.cumsum(lengths)[:-1]])
    idx = offsets[:, None] + jnp.arange(maxlen)[None, :]      # [B, T]
    idx = jnp.clip(idx, 0, x.shape[0] - 1)
    out = x[idx]                                              # [B, T, ...]
    mask = _time_mask(lengths, maxlen)
    mask = mask.reshape(mask.shape + (1,) * (out.ndim - 2))
    out = jnp.where(mask, out, jnp.asarray(pad_value, out.dtype))
    return out, lengths


@register_op("sequence_unpad", eager_only=True)
def sequence_unpad(x, lengths):
    """Padded [B, T, ...] -> flat [N, ...]. Output length is data-dependent
    (sum of lengths) — eager-only, mirroring masked_select's contract."""
    if isinstance(x, jax.core.Tracer):
        raise NotImplementedError(
            "sequence_unpad output shape depends on lengths; call it "
            "eagerly or keep the padded+lengths representation under jit"
        )
    xs = np.asarray(x)
    ls = np.asarray(lengths)
    return jnp.asarray(
        np.concatenate([xs[i, : ls[i]] for i in range(ls.shape[0])], axis=0)
    )


@register_op("sequence_pool")
def sequence_pool(x, lengths, *, pooltype="SUM"):
    """sequence_pool_op.cc over padded [B, T, ...] + lengths.

    SUM/AVERAGE/SQRT/MAX/MIN/FIRST/LAST; SQRT divides by sqrt(len) (the
    reference's scaling for attention-style pooling).
    """
    t = x.shape[1]
    mask = _time_mask(lengths, t)
    mask_e = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    pool = pooltype.upper()
    if pool in ("SUM", "AVERAGE", "SQRT"):
        s = jnp.sum(jnp.where(mask_e, x, 0), axis=1)
        if pool == "SUM":
            return s
        denom = jnp.maximum(lengths, 1).astype(s.dtype)
        denom = denom.reshape((-1,) + (1,) * (s.ndim - 1))
        return s / (denom if pool == "AVERAGE" else jnp.sqrt(denom))
    if pool == "MAX":
        return jnp.max(jnp.where(mask_e, x, -jnp.inf), axis=1)
    if pool == "MIN":
        return jnp.min(jnp.where(mask_e, x, jnp.inf), axis=1)
    if pool == "FIRST":
        return x[:, 0]
    if pool == "LAST":
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    raise ValueError(f"unknown pooltype {pooltype}")


@register_op("segment_pool")
def segment_pool(x, segment_ids, *, num_segments, pooltype="SUM"):
    """Flat+segment-ids pooling (the second ragged representation); lowers
    to jax.ops.segment_* (sorted scatter — MXU/VPU friendly on TPU)."""
    pool = pooltype.upper()
    if pool == "SUM":
        return jax.ops.segment_sum(x, segment_ids, num_segments)
    if pool == "AVERAGE":
        s = jax.ops.segment_sum(x, segment_ids, num_segments)
        cnt = jax.ops.segment_sum(
            jnp.ones(x.shape[0], x.dtype), segment_ids, num_segments
        )
        return s / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (s.ndim - 1))
    if pool == "MAX":
        return jax.ops.segment_max(x, segment_ids, num_segments)
    if pool == "MIN":
        return jax.ops.segment_min(x, segment_ids, num_segments)
    raise ValueError(f"unknown pooltype {pooltype}")


@register_op("sequence_softmax")
def sequence_softmax(x, lengths):
    """Masked softmax over the time axis (sequence_softmax_op.cc)."""
    mask = _time_mask(lengths, x.shape[1])
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    scores = jnp.where(mask, x, _NEG_INF)
    w = jax.nn.softmax(scores.astype(jnp.float32), axis=1)
    return jnp.where(mask, w, 0.0).astype(x.dtype)


@register_op("sequence_reverse")
def sequence_reverse(x, lengths):
    """Reverse each valid prefix, keep padding in place
    (sequence_reverse_op.h)."""
    t = x.shape[1]
    ar = jnp.arange(t)
    # index of the element to pull: len-1-t inside the prefix, identity after
    src = jnp.where(
        ar[None, :] < lengths[:, None], lengths[:, None] - 1 - ar[None, :],
        ar[None, :],
    )
    src = jnp.clip(src, 0, t - 1)
    return jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1
    )


@register_op("sequence_slice")
def sequence_slice(x, offset, length, *, maxlen=None):
    """Per-sequence slice: out[b, t] = x[b, offset[b]+t] for t < length[b]
    (sequence_slice_op.h), padded with zeros to a static maxlen."""
    t = x.shape[1]
    maxlen = int(maxlen) if maxlen is not None else t
    ar = jnp.arange(maxlen)
    src = offset.reshape(-1, 1) + ar[None, :]
    valid = ar[None, :] < length.reshape(-1, 1)
    src = jnp.clip(src, 0, t - 1)
    out = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1
    )
    vm = valid.reshape(valid.shape + (1,) * (x.ndim - 2))
    return jnp.where(vm, out, 0)


@register_op("sequence_concat", num_outputs=2)
def sequence_concat(x, xlen, y, ylen):
    """Concatenate two padded ragged batches along time
    (sequence_concat_op.cc): out[b] = x[b][:xlen] ++ y[b][:ylen]."""
    t_out = x.shape[1] + y.shape[1]
    ar = jnp.arange(t_out)
    in_x = ar[None, :] < xlen[:, None]
    y_idx = ar[None, :] - xlen[:, None]
    x_src = jnp.clip(ar[None, :] + jnp.zeros_like(xlen[:, None]), 0,
                     x.shape[1] - 1)
    y_src = jnp.clip(y_idx, 0, y.shape[1] - 1)

    def take(v, src):
        return jnp.take_along_axis(
            v, src.reshape(src.shape + (1,) * (v.ndim - 2)), axis=1
        )

    out = jnp.where(
        in_x.reshape(in_x.shape + (1,) * (x.ndim - 2)),
        take(x, x_src), take(y, y_src),
    )
    lengths = xlen + ylen
    mask = _time_mask(lengths, t_out)
    out = jnp.where(mask.reshape(mask.shape + (1,) * (x.ndim - 2)), out, 0)
    return out, lengths


@register_op("sequence_expand", eager_only=True)
def sequence_expand(x, rep):
    """Repeat row b of x rep[b] times (sequence_expand_op.cc). Output row
    count is data-dependent — eager-only."""
    if isinstance(x, jax.core.Tracer) or isinstance(rep, jax.core.Tracer):
        raise NotImplementedError(
            "sequence_expand output shape depends on rep; eager-only — "
            "under jit use repeat_interleave with a static total"
        )
    return jnp.asarray(np.repeat(np.asarray(x), np.asarray(rep), axis=0))


@register_op("sequence_enumerate")
def sequence_enumerate(x, *, win_size, pad_value=0):
    """All win_size windows per position (sequence_enumerate_op.cc):
    [N] -> [N, win], padding past the end."""
    n = x.shape[0]
    idx = jnp.arange(n)[:, None] + jnp.arange(int(win_size))[None, :]
    valid = idx < n
    idx = jnp.clip(idx, 0, n - 1)
    return jnp.where(valid, x[idx], jnp.asarray(pad_value, x.dtype))


@register_op("sequence_erase", eager_only=True)
def sequence_erase(x, *, tokens=()):
    """Remove listed tokens (sequence_erase_op.cc). Output size is
    data-dependent — eager-only."""
    if isinstance(x, jax.core.Tracer):
        raise NotImplementedError(
            "sequence_erase output shape depends on data; eager-only — "
            "under jit mask instead of erasing"
        )
    xs = np.asarray(x)
    keep = ~np.isin(xs, np.asarray(list(tokens), dtype=xs.dtype))
    return jnp.asarray(xs[keep])


@register_op("sequence_conv")
def sequence_conv(x, lengths, weight, *, context_length, context_start=None):
    """Context-window convolution over time (sequence_conv_op.cc): for each
    step, concat [t+start, t+start+context_length) features (zeros outside
    the valid range) and project with weight [ctx*D, M]."""
    b, t, d = x.shape
    start = -((context_length - 1) // 2) if context_start is None else int(
        context_start
    )
    mask = _time_mask(lengths, t)
    xm = x * mask[:, :, None].astype(x.dtype)  # zero past each length
    cols = []
    for k in range(int(context_length)):
        shift = start + k
        pos = jnp.arange(t) + shift
        idx = jnp.clip(pos, 0, t - 1)
        in_range = ((pos >= 0) & (pos < t))[None, :]
        col = xm[:, idx] * in_range[:, :, None].astype(x.dtype)
        cols.append(col)
    ctx = jnp.concatenate(cols, axis=-1)          # [B, T, ctx*D]
    out = jnp.einsum("btc,cm->btm", ctx, weight)
    return out * mask[:, :, None].astype(out.dtype)


@register_op("sequence_first_step")
def sequence_first_step(x, lengths):
    return sequence_pool(x, lengths, pooltype="FIRST")


@register_op("sequence_last_step")
def sequence_last_step(x, lengths):
    return sequence_pool(x, lengths, pooltype="LAST")
