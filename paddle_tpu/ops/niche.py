"""The last registry tail: five niche reference ops.

Reference parity (the 100%-coverage set of tools/check_op_coverage.py):
- bilateral_slice  — operators/bilateral_slice_op.cc (HDRNet grid slice)
- rank_attention   — operators/rank_attention_op.cc (+ rank_attention.cu.h
  expand/gemm scheme)
- var_conv_2d      — operators/var_conv_2d_op.cc (per-sample-size conv)
- tree_conv        — operators/tree_conv_op.cc + math/tree2col.cc (TBCNN
  continuous binary tree patches)
- pyramid_hash     — operators/pyramid_hash_op.cc (n-gram hash embedding)

TPU notes: bilateral_slice / rank_attention / var_conv_2d are pure jnp
(jit-friendly — gathers + dots on static shapes). tree_conv's patch
construction is data-dependent graph traversal (the reference runs it on
CPU, tree2col.cc); the traversal runs host-side on concrete edge sets and
only the final patch x filter contraction is jnp — under a trace the op
raises with that explanation. pyramid_hash replaces XXH32 with a
vectorized FNV-1a over token windows (no xxhash in-image; same
bucket-spreading role, recorded divergence).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .registry import register_op

__all__ = [
    "bilateral_slice", "rank_attention", "var_conv_2d", "tree_conv",
    "pyramid_hash",
]


@register_op("bilateral_slice")
def bilateral_slice(x, grid, guide, *, has_offset=True):
    """HDRNet bilateral-grid apply (bilateral_slice_op.cc).

    x      [N, Ci, H, W]   input image
    grid   [N, Cg, D, Gh, Gw]  affine-coeff grid; Cg = Co*(Ci+1) with
                           offset, Co*Ci without
    guide  [N, H, W] in [0, 1]  per-pixel grid depth
    out    [N, Co, H, W]
    Trilinear-samples the grid at (gx, gy, guide) and applies the sampled
    per-pixel affine transform.
    """
    n, ci, h, w = x.shape
    _, cg, d, gh, gw = grid.shape
    co = cg // (ci + 1) if has_offset else cg // ci

    # sample positions in grid space (align like the reference kernel:
    # gx = (x+0.5)*gw/W - 0.5)
    gx = (jnp.arange(w) + 0.5) * gw / w - 0.5
    gy = (jnp.arange(h) + 0.5) * gh / h - 0.5
    gz = guide * d - 0.5  # [N, H, W]

    fx = jnp.clip(jnp.floor(gx), 0, gw - 2).astype(jnp.int32)  # [W]
    fy = jnp.clip(jnp.floor(gy), 0, gh - 2).astype(jnp.int32)  # [H]
    fz = jnp.clip(jnp.floor(gz), 0, d - 2).astype(jnp.int32)   # [N,H,W]
    wx = jnp.clip(gx - fx, 0.0, 1.0)
    wy = jnp.clip(gy - fy, 0.0, 1.0)
    wz = jnp.clip(gz - fz, 0.0, 1.0)

    # 8-corner trilinear gather via advanced indexing
    out_acc = 0.0
    nn = jnp.arange(n)[:, None, None]
    for dz in (0, 1):
        for dy in (0, 1):
            for dx in (0, 1):
                zz = fz + dz                                  # [N,H,W]
                yy = jnp.broadcast_to(
                    (fy + dy)[None, :, None], (n, h, w))
                xx = jnp.broadcast_to(
                    (fx + dx)[None, None, :], (n, h, w))
                g = grid[nn, :, zz, yy, xx]                   # [N,H,W,Cg]
                wgt = (
                    (wz if dz else (1 - wz))
                    * (wy if dy else (1 - wy))[None, :, None]
                    * (wx if dx else (1 - wx))[None, None, :]
                )
                out_acc = out_acc + g * wgt[..., None]
    coeff = out_acc  # [N, H, W, Cg]

    xs = jnp.moveaxis(x, 1, -1)  # [N,H,W,Ci]
    per_in = ci + 1 if has_offset else ci
    coeff = coeff.reshape(n, h, w, co, per_in)
    out = jnp.einsum("nhwoc,nhwc->nhwo", coeff[..., :ci], xs)
    if has_offset:
        out = out + coeff[..., ci]
    return jnp.moveaxis(out, -1, 1)


@register_op("rank_attention", num_outputs=3)
def rank_attention(x, rank_offset, rank_param, *, max_rank=3,
                   rank_param_shape=None):
    """rank_attention_op.cc: per-instance parameter selection by rank
    pairs + matmul (the expand-input/expand-param/batched-gemm scheme of
    rank_attention.cu.h, as one einsum).

    x           [ins, fea]
    rank_offset int [ins, 1+2*max_rank]: col0 = own rank (1-based; <=0
                invalid); col(2k+1) = k-th other's rank; col(2k+2) = that
                instance's row in x
    rank_param  [n_ranks*max_rank*fea, para_col]
    returns (out [ins, para_col], input_help, ins_rank)
    """
    ins, fea = x.shape
    para_col = rank_param.shape[1]
    lower = rank_offset[:, 0] - 1                       # [ins]
    ks = jnp.arange(max_rank)
    faster = rank_offset[:, 2 * ks + 1] - 1             # [ins, K]
    index = rank_offset[:, 2 * ks + 2]                  # [ins, K]
    valid = (lower[:, None] >= 0) & (faster >= 0)       # [ins, K]

    # expanded input: slot k = x[index_k] (zeros when invalid)
    xin = x[jnp.clip(index, 0, ins - 1)]                # [ins, K, fea]
    xin = jnp.where(valid[..., None], xin, 0.0)

    # expanded param: block (lower*max_rank + faster) of shape [fea, col]
    blocks = rank_param.reshape(-1, fea, para_col)      # [n_blocks, fea, col]
    bidx = jnp.clip(lower[:, None] * max_rank + faster, 0,
                    blocks.shape[0] - 1)                # [ins, K]
    par = jnp.where(valid[..., None, None], blocks[bidx], 0.0)

    out = jnp.einsum("ikf,ikfc->ic", xin, par)
    ins_rank = jnp.where(
        rank_offset[:, 0] > 0, rank_offset[:, 0], -1
    ).astype(x.dtype)[:, None]
    return out, xin.reshape(ins, max_rank * fea), ins_rank


@register_op("var_conv_2d")
def var_conv_2d(x, w, rows, cols, *, output_channel, input_channel,
                kernel_h, kernel_w, stride_h=1, stride_w=1):
    """var_conv_2d_op.cc: conv over per-sample-sized images.

    The reference consumes a LoD-packed batch with per-sample (row, col)
    lods; the XLA form takes the PADDED batch x [N, Cin, H, W] plus
    per-sample extents rows/cols [N] and masks both input and output so
    positions beyond each sample's true size are exactly zero — same
    math, static shapes.
    """
    from . import kernels as _k

    n, cin, hmax, wmax = x.shape
    rows = jnp.asarray(rows).astype(jnp.int32)
    cols = jnp.asarray(cols).astype(jnp.int32)
    hh = jnp.arange(hmax)[None, :]
    ww = jnp.arange(wmax)[None, :]
    in_mask = ((hh < rows[:, None])[:, None, :, None]
               & (ww < cols[:, None])[:, None, None, :])
    xm = jnp.where(in_mask, x, 0.0)
    weight = w.reshape(output_channel, input_channel, kernel_h, kernel_w)
    out = _k.conv2d(
        xm, weight, stride=(stride_h, stride_w),
        padding=(kernel_h // 2, kernel_w // 2),
    )
    oh = (rows + stride_h - 1) // stride_h
    ow = (cols + stride_w - 1) // stride_w
    ho = jnp.arange(out.shape[2])[None, :]
    wo = jnp.arange(out.shape[3])[None, :]
    out_mask = ((ho < oh[:, None])[:, None, :, None]
                & (wo < ow[:, None])[:, None, None, :])
    return jnp.where(out_mask, out, 0.0)


def _tree_patches(edges, n_nodes, max_depth):
    """tree2col.cc construct_tree + construct_patch on the host: for each
    root, DFS to max_depth collecting (node, eta_t/l/r) coefficients of
    the continuous binary tree."""
    adj = [[] for _ in range(n_nodes + 1)]
    for a, b in edges:
        a, b = int(a), int(b)
        if a <= 0 or b <= 0:
            continue
        adj[a].append(b)  # parent -> child, 1-based (tree2col.cc:60)

    def eta(index, pclen, depth, fd):
        et = (fd - depth) / fd
        el = (1.0 - et) * (0.5 if pclen == 1
                           else (index - 1.0) / (pclen - 1.0))
        er = (1.0 - et) * (1.0 - (0.5 if pclen == 1
                                  else (index - 1.0) / (pclen - 1.0)))
        return et, el, er

    patches = []
    for root in range(1, n_nodes + 1):
        patch = []
        stack = [(root, 1, 1, 0)]
        visited = {root}
        patch.append((root, 1, 1, 0))
        while stack:
            node, idx, pclen, depth = stack[-1]
            advanced = False
            children = adj[node]
            for i, v in enumerate(children):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, i, len(children), depth + 1))
                    patch.append((v, i + 1, len(children), depth + 1))
                    advanced = True
            if not advanced:
                stack.pop()
        if patch:
            rows = []
            fd = float(max_depth)
            for node, idx, pclen, depth in patch:
                et, el, er = eta(idx, pclen, depth, fd)
                rows.append((node - 1, el, er, et))  # tree2col order l,r,t
            patches.append(rows)
    return patches


@register_op("tree_conv")
def tree_conv(nodes_vector, edge_set, filter, *, max_depth=2):
    """tree_conv_op.cc (TBCNN): per-tree patches → filter contraction.

    nodes_vector [N, n, fea]; edge_set int [N, e, 2] (1-based parent,
    child; zero rows = padding); filter [fea, 3, out_c, num_filters] or
    [fea, 3, out_c]; out [N, patches, out_c(, num_filters)].

    The patch construction is data-dependent tree traversal — host-side
    on concrete arrays (the reference computes it on CPU too,
    math/tree2col.cc); inside jit this op raises.
    """
    if isinstance(nodes_vector, jax.core.Tracer) or isinstance(
        edge_set, jax.core.Tracer
    ):
        raise NotImplementedError(
            "tree_conv patch construction is data-dependent tree "
            "traversal; run it eagerly (the reference's kernel is "
            "CPU-only as well, math/tree2col.cc)"
        )
    nv = np.asarray(nodes_vector)
    es = np.asarray(edge_set)
    filt = jnp.asarray(filter)
    squeeze = filt.ndim == 3
    if squeeze:
        filt = filt[..., None]
    fea = nv.shape[2]
    outs = []
    max_patches = 0
    per_batch = []
    for b in range(nv.shape[0]):
        patches = _tree_patches(es[b], nv.shape[1], max_depth)
        # patch matrix [n_patches, fea, 3] with (l, r, t) coefficient sums
        pm = np.zeros((max(1, len(patches)), fea, 3), np.float32)
        for pi, rows in enumerate(patches):
            for node_id, el, er, et in rows:
                pm[pi] += nv[b, node_id][:, None] * np.asarray(
                    [el, er, et], np.float32
                )
        per_batch.append(pm)
        max_patches = max(max_patches, pm.shape[0])
    for pm in per_batch:
        if pm.shape[0] < max_patches:
            pm = np.concatenate([
                pm, np.zeros((max_patches - pm.shape[0], fea, 3),
                             np.float32)
            ])
        outs.append(pm)
    patch = jnp.asarray(np.stack(outs))  # [N, P, fea, 3]
    out = jnp.einsum("npft,ftcm->npcm", patch, filt)
    return out[..., 0] if squeeze else out


def _fnv1a(tokens, seed):
    """Vectorized FNV-1a over int32 token windows [..., L] → uint32.
    Stands in for the reference's XXH32 (pyramid_hash_op.cc:229)."""
    h = jnp.uint32(2166136261) ^ jnp.uint32(seed)
    prime = jnp.uint32(16777619)
    toks = tokens.astype(jnp.uint32)
    for k in range(tokens.shape[-1]):
        for shift in (0, 8, 16, 24):  # byte-wise like the reference hash
            byte = (toks[..., k] >> shift) & jnp.uint32(0xFF)
            h = (h ^ byte) * prime
    return h


@register_op("pyramid_hash", num_outputs=2)
def pyramid_hash(x, w, *, num_emb, space_len, pyramid_layer, rand_len,
                 white_list_len=0, black_list_len=0, seed=0,
                 drop_out_percent=0.0, is_training=0, use_filter=False,
                 lr=0.0, key=None):
    """pyramid_hash_op.cc: n-gram hash embeddings summed over pyramid
    levels.

    x [N, L] int token ids (0 = pad); w [space_len + rand_len, 1] the
    hash-embedding parameter space. For each n-gram length 2..
    pyramid_layer and window, num_emb/rand_len hash buckets are drawn
    (FNV-1a here vs the reference's XXH32) and rand_len-wide fragments of
    w concatenated → [num_emb] per window, summed per sequence.
    Returns (out [N, num_emb], drop_pos [N, 1] — kept for surface parity,
    all-ones without dropout).
    """
    n, L = x.shape
    n_frag = num_emb // rand_len
    acc = jnp.zeros((n, num_emb), jnp.float32)
    w_flat = w.reshape(-1)
    for gram in range(2, pyramid_layer + 1):
        if gram > L:
            break
        for start in range(L - gram + 1):
            window = x[:, start:start + gram]          # [N, gram]
            valid = jnp.all(window > 0, axis=1)        # pads break grams
            frags = []
            for j in range(n_frag):
                pos = _fnv1a(window, seed + j) % jnp.uint32(space_len)
                idx = pos[:, None].astype(jnp.int32) + jnp.arange(rand_len)
                frags.append(w_flat[idx])              # [N, rand_len]
            emb = jnp.concatenate(frags, axis=1)       # [N, num_emb]
            acc = acc + jnp.where(valid[:, None], emb, 0.0)
    drop_pos = jnp.ones((n, 1), jnp.int32)
    return acc, drop_pos
