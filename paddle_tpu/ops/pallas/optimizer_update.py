"""Fused momentum / weight-decay optimizer update (TPU pallas kernel).

The Momentum update is the textbook memory-bound chain: read param,
grad, velocity; write param, velocity — with L2 weight decay it lowers
to four elementwise HBM passes when left to op-by-op dispatch. On TPU
the whole update runs as ONE pallas kernel: a single VMEM pass computes

    g' = grad + wd * param
    v' = mu * v + g'
    p' = p - lr * (g' + mu * v')      (nesterov)
        | p - lr * v'                  (plain)

with ``input_output_aliases`` so param and velocity update in place
(zero extra HBM allocation — the same discipline as the executor's
buffer donation). Off-TPU (and for shapes/dtypes the kernel does not
admit) a jnp fallback computes the IDENTICAL expression in the same
order, so the fused path is bit-compatible everywhere and
``FLAGS_use_fused_optimizer`` is numerically free to leave on.

Design per /opt/skills/guides/pallas_guide.md: operands flatten to
``[R, 128]`` lane-major tiles (sublane padding per dtype), the grid
walks row blocks, and ``lr`` (a traced scalar — the LR schedule feeds a
fresh value every step without recompiling) rides in SMEM as ``[1, 1]``.
Padding rows compute garbage that is never written back (masked block
writes), which is safe because the update is purely elementwise.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..._internal_tuning import register_schedule, resolve_schedule
from ._platform import on_tpu_platform

__all__ = ["fused_momentum_update"]

_LANES = 128
# minimum sublane multiple per dtype (pallas_guide.md tiling table)
_SUBLANES = {"float32": 8, "bfloat16": 16}
_BLOCK_R = 2048  # default rows per program: ≤ 2048×128 f4 = 1 MB / operand


def _schedule_block_rows(rows, dtype) -> int:
    """Row-block size through the autotuner; the default point is the
    historical ``min(rows, 2048)`` — byte-identical when untuned."""
    params = resolve_schedule("optimizer_update", rows=int(rows),
                              dtype=str(dtype))
    return max(1, min(int(params["block_r"]), rows))


def _tuning_bench(info):
    import numpy as np

    rows = int(info["rows"])
    dtype = str(info.get("dtype", "float32"))
    n = rows * _LANES
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.randn(n).astype("f4")).astype(dtype)
    g = jnp.asarray(rng.randn(n).astype("f4")).astype(dtype)
    v = jnp.asarray(rng.randn(n).astype("f4")).astype(dtype)
    interpret = not on_tpu_platform()

    def builder(params):
        block_r = max(1, min(int(params["block_r"]), rows))
        fn = jax.jit(lambda p, g, v, lr: _pallas_update(
            p, g, v, lr, 0.9, 1e-4, False, interpret=interpret,
            block_r=block_r))
        lr = jnp.float32(0.1)

        def run():
            jax.block_until_ready(fn(p, g, v, lr))

        return run

    return builder


def _bucket(info):
    # raw-row tune() keys and padded-[R,128] resolve() keys must
    # collapse into one bucket: clamp rows to the sublane floor first
    from ...tuning.schedule import aligned_bucket

    return aligned_bucket({
        "rows": lambda i: _SUBLANES.get(str(i.get("dtype", "float32")),
                                        8),
    })(info)


register_schedule(
    name="optimizer_update",
    version=1,
    params={"block_r": (256, 512, 1024, 2048, 4096, 8192)},
    default=lambda info: {"block_r": min(int(info["rows"]), _BLOCK_R)},
    bucket=_bucket,
    # 5 live [block_r, 128] operand blocks (3 in + 2 out) must stay far
    # under the ~16 MB VMEM budget, bf16 sublane multiple respected
    supported=lambda info, c: (
        c["block_r"] >= _SUBLANES.get(info.get("dtype", "float32"), 8)
        and 5 * c["block_r"] * _LANES * 4 <= (1 << 23)),
    bench=_tuning_bench,
)


def _jnp_update(param, grad, velocity, lr, mu, wd, nesterov):
    """Reference/fallback path: the exact expression the kernel fuses,
    in the same operation order (bit-identical off-TPU)."""
    g = grad + wd * param if wd else grad
    v = mu * velocity + g
    if nesterov:
        new_p = param - lr * (g + mu * v)
    else:
        new_p = param - lr * v
    return new_p, v


def _kernel(lr_ref, p_ref, g_ref, v_ref, p_out, v_out, *, mu, wd,
            nesterov):
    lr = lr_ref[0, 0]
    p = p_ref[:]
    g = g_ref[:]
    if wd:
        g = g + wd * p
    v = mu * v_ref[:] + g
    v_out[:] = v
    if nesterov:
        p_out[:] = p - lr * (g + mu * v)
    else:
        p_out[:] = p - lr * v


def _supported(param, grad, velocity) -> bool:
    if str(param.dtype) not in _SUBLANES:
        return False
    return (param.shape == grad.shape == velocity.shape
            and param.size >= _LANES)


def _pallas_update(param, grad, velocity, lr, mu, wd, nesterov,
                   interpret=False, block_r=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape, dtype, n = param.shape, param.dtype, param.size
    sub = _SUBLANES[str(dtype)]
    tile = sub * _LANES
    padded = ((n + tile - 1) // tile) * tile
    rows = padded // _LANES

    def flat(a):
        a = a.reshape(-1)
        if padded != n:
            a = jnp.pad(a, (0, padded - n))
        return a.reshape(rows, _LANES)

    pf, gf, vf = flat(param), flat(grad), flat(velocity)
    if block_r is None:
        block_r = _schedule_block_rows(rows, dtype)
    grid = (pl.cdiv(rows, block_r),)
    row_spec = pl.BlockSpec((block_r, _LANES), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    lr_arr = jnp.asarray(lr, jnp.float32).reshape(1, 1)

    def kernel(lr_ref, p_ref, g_ref, v_ref, p_out, v_out):
        return _kernel(lr_ref, p_ref, g_ref, v_ref, p_out, v_out,
                       mu=mu, wd=wd, nesterov=nesterov)

    new_p, new_v = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            row_spec, row_spec, row_spec,
        ],
        out_specs=[row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANES), dtype),
            jax.ShapeDtypeStruct((rows, _LANES), dtype),
        ],
        # param/velocity update IN PLACE (XLA aliases the dead inputs)
        input_output_aliases={1: 0, 3: 1},
        interpret=interpret,
    )(lr_arr, pf, gf, vf)
    unflat = lambda a: a.reshape(-1)[:n].reshape(shape)
    return unflat(new_p), unflat(new_v)


def fused_momentum_update(param, grad, velocity, lr, momentum=0.9,
                          weight_decay=0.0, use_nesterov=False):
    """One fused momentum(+L2 decay) parameter update.

    Returns ``(new_param, new_velocity)``. Dispatches to the pallas
    kernel on TPU for admitted shapes/dtypes; elsewhere the jnp fallback
    computes the identical expression (same order, same dtypes). Safe
    inside a jitted train step (``lr`` may be a traced scalar).
    """
    param = jnp.asarray(param)
    grad = jnp.asarray(grad, param.dtype)
    velocity = jnp.asarray(velocity, param.dtype)
    mu = float(momentum)
    wd = float(weight_decay)
    nesterov = bool(use_nesterov)
    if on_tpu_platform() and _supported(param, grad, velocity):
        return _pallas_update(param, grad, velocity, lr, mu, wd, nesterov)
    return _jnp_update(param, grad, velocity, lr, mu, wd, nesterov)
