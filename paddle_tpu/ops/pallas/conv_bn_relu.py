"""Fused conv2d + batch_norm + relu (TPU pallas kernels, fwd + bwd).

The ResNet hot path is the ``conv -> bn -> relu`` triple (three per
bottleneck block, ~50 per forward): op-by-op that is an HBM round trip
for the conv output, two more for the statistics and the normalized
activation, and one for the relu. Here the conv contraction runs as a
tiled MXU matmul whose epilogue applies the BN affine + relu in the
same VMEM pass:

- the conv lowers to matmul form ONCE outside the kernels (1x1
  stride-1 convs reshape directly; KxK convs go through
  ``lax.conv_general_dilated_patches`` — the classical im2col, whose
  VJP gives the dx scatter for free), then
- **eval**: ONE kernel computes ``relu((patches @ w) * scale + shift)``
  per [TM, TN] tile — the pre-activation never exists in HBM. scale /
  shift fold gamma/beta with the running statistics.
- **training**: kernel 1 computes the matmul AND per-tile partial
  channel sums in the same pass; kernel 2 reduces the CENTERED
  sum-of-squares (two-pass variance — the one-pass E[x^2]-mean^2 form
  catastrophically cancels for large-mean channels, see
  ``_centered_sumsq_kernel``); kernel 3 is one elementwise
  normalize+relu pass.
- **backward (training)**: kernel B1 recomputes the relu gate from the
  saved conv output and emits per-tile partials of ``sum(dy)`` and
  ``sum(dy * co)`` (one pass); kernel B2 applies the folded BN
  backward ``d_co = k1*dy - k3*co - b0`` elementwise. The matmul
  gradients finish through ``jnp.dot`` (MXU via XLA) and the patch
  VJP — the same "kernels do the fused pointwise work, jnp finishes
  the reductions" discipline as layernorm_residual's dw/db.

Off-TPU (and for unadmitted shapes) the fallback calls the IDENTICAL
registered op kernels (``conv2d`` -> ``batch_norm`` -> relu) in the
same order, so ``FLAGS_use_fused_conv_bn`` never changes numerics off
the pallas path — the same flag discipline as the PR-10 kernels.

Tile geometry (TM, TN) resolves through the kernel autotuner
(``tuning.resolve("conv_bn_relu", ...)``) with the historical 256/256
as the byte-identical default point.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..._internal_tuning import register_schedule, resolve_schedule
from ._platform import on_tpu_platform

__all__ = ["conv_bn_relu"]

_LANES = 128
_SUBLANES = {"float32": 8, "bfloat16": 16}
_TILE = 256  # default M/N tile (the schedule space's default point)


# -- schedule space -----------------------------------------------------------


def _schedule_tiles(mp, kp, cp, dtype) -> tuple:
    params = resolve_schedule("conv_bn_relu", m=int(mp), k=int(kp),
                              c=int(cp), dtype=str(dtype))
    return (max(8, min(int(params["tile_m"]), mp)),
            max(_LANES, min(int(params["tile_n"]), cp)))


def _bucket(info):
    # raw-shape tune() keys and padded-dim resolve() keys must collapse
    # into one bucket: clamp dims to their tile floors first
    from ...tuning.schedule import aligned_bucket

    return aligned_bucket({
        "m": lambda i: _SUBLANES.get(str(i.get("dtype", "float32")), 8),
        "k": _LANES, "c": _LANES,
    })(info)


def _conv_vmem_ok(info, c) -> bool:
    # full-K stripes resident per program: [tile_m, K] + [K, tile_n]
    # operand blocks (2B at the bf16 floor) + f32 [tile_m, tile_n]
    # accumulator/output; ~12 MB admission line under the 16 MB core
    k = int(info["k"])
    bytes_ = 2 * (c["tile_m"] * k + k * c["tile_n"]) \
        + 4 * c["tile_m"] * c["tile_n"]
    return (c["tile_m"] % 8 == 0 and c["tile_n"] % _LANES == 0
            and bytes_ <= 12 * (1 << 20))


def _tuning_bench(info):
    import numpy as np

    m, k, c = int(info["m"]), int(info["k"]), int(info["c"])
    dtype = str(info.get("dtype", "float32"))
    rng = np.random.RandomState(0)
    p2 = jnp.asarray(rng.randn(m, k).astype("f4")).astype(dtype)
    w2 = jnp.asarray(rng.randn(k, c).astype("f4")).astype(dtype)
    scale = jnp.asarray(rng.rand(c).astype("f4") + 0.5)
    shift = jnp.asarray(rng.randn(c).astype("f4"))
    interpret = not on_tpu_platform()

    def builder(params):
        tiles = (max(8, min(int(params["tile_m"]), m)),
                 max(_LANES, min(int(params["tile_n"]), c)))
        fn = jax.jit(lambda p2, w2, s, b: _mm_affine_relu(
            p2, w2, s, b, interpret=interpret, tiles=tiles))

        def run():
            jax.block_until_ready(fn(p2, w2, scale, shift))

        return run

    return builder


register_schedule(
    name="conv_bn_relu",
    version=1,
    params={"tile_m": (64, 128, 256, 512),
            "tile_n": (128, 256, 512)},
    # tile floors keep the default point valid for RAW shapes too (the
    # dispatch path always passes padded dims, where the max() is a
    # no-op — byte-identity of the default holds either way)
    default=lambda info: {"tile_m": max(8, min(int(info["m"]), _TILE)),
                          "tile_n": max(_LANES, min(int(info["c"]),
                                                    _TILE))},
    supported=_conv_vmem_ok,
    bench=_tuning_bench,
    bucket=_bucket,
)


# -- reference / fallback -----------------------------------------------------


def _reference(x, w, gamma, beta, mean, var, *, stride, padding, training,
               momentum, eps, data_format):
    """EXACTLY the unfused op sequence: the registered conv2d kernel ->
    the registered batch_norm kernel -> relu, same primitives, same
    order — enabling the flag off-TPU is numerically free."""
    from ..kernels import batch_norm as _bn
    from ..kernels import conv2d as _conv

    co = _conv(x, w, stride=stride, padding=padding, dilation=1, groups=1,
               data_format=data_format)
    y, new_mean, new_var = _bn(co, gamma, beta, mean, var,
                               momentum=momentum, epsilon=eps,
                               training=training, data_format=data_format)
    return jax.nn.relu(y), new_mean, new_var


# -- conv -> matmul lowering --------------------------------------------------


def _norm_padding(padding):
    """Normalize int / (ph, pw) / 4-list padding to [(t, b), (l, r)];
    None for forms the fused path does not admit (SAME/VALID strings,
    per-edge pair-of-pairs fall back)."""
    if isinstance(padding, str):
        return None
    if isinstance(padding, (list, tuple)):
        if len(padding) == 2 and all(
                isinstance(p, (list, tuple)) for p in padding):
            return [tuple(padding[0]), tuple(padding[1])]
        if len(padding) == 2:
            return [(padding[0], padding[0]), (padding[1], padding[1])]
        if len(padding) == 4:
            return [(padding[0], padding[1]), (padding[2], padding[3])]
        return None
    return [(int(padding), int(padding))] * 2


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v), int(v))


def _as_matmul(x, w, stride, pad, data_format):
    """Lower the conv to ``patches2d [M, K] @ w2 [K, Cout]``.

    Returns (patches2d, w2, (n, oh, ow)). The patch features are
    ordered (cin, kh, kw) — exactly the OIHW weight's trailing-axes
    flattening, verified by the interpret parity tests.
    """
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, cin, h, wd = x.shape
    cout, _, kh, kw = w.shape
    sh, sw = _pair(stride)
    oh = (h + pad[0][0] + pad[0][1] - kh) // sh + 1
    ow = (wd + pad[1][0] + pad[1][1] - kw) // sw + 1
    if (kh, kw) == (1, 1) and (sh, sw) == (1, 1) \
            and pad == [(0, 0), (0, 0)]:
        # pointwise conv (2 of 3 convs per bottleneck block): the
        # "patches" ARE the input, channels-last
        p2 = jnp.moveaxis(x, 1, -1).reshape(n * h * wd, cin)
    else:
        p = lax.conv_general_dilated_patches(
            x, (kh, kw), (sh, sw), pad)           # [N, Cin*KH*KW, OH, OW]
        p2 = jnp.moveaxis(p, 1, -1).reshape(n * oh * ow, cin * kh * kw)
    w2 = w.reshape(cout, cin * kh * kw).T          # [K, Cout], (i, kh, kw)
    return p2, w2, (n, oh, ow)


def _pad_mat(a, rows, cols):
    r, c = a.shape
    if (r, c) == (rows, cols):
        return a
    return jnp.pad(a, ((0, rows - r), (0, cols - c)))


def _pad_vec(v, cols):
    return v if v.shape[0] == cols else jnp.pad(v, (0, cols - v.shape[0]))


def _padded_dims(m, k, c, dtype):
    sub = _SUBLANES.get(str(dtype), 8)
    mp = ((m + sub - 1) // sub) * sub
    kp = ((k + _LANES - 1) // _LANES) * _LANES
    cp = ((c + _LANES - 1) // _LANES) * _LANES
    return mp, kp, cp


# -- forward kernels ----------------------------------------------------------


def _mm_affine_relu_kernel(x_ref, w_ref, s_ref, b_ref, y_ref, *, dt):
    # conv output cast to the carrier dtype FIRST (what the unfused conv
    # hands batch_norm), then the f32 affine + relu — one VMEM pass
    acc = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
    co = acc.astype(dt).astype(jnp.float32)
    y = co * s_ref[0] + b_ref[0]
    y_ref[:] = jnp.maximum(y, 0.0).astype(dt)


def _mm_stats_kernel(x_ref, w_ref, co_ref, ps_ref, *, dt, nrows, tile_m):
    """Matmul + channel-sum partials. A ragged last row-tile reads
    out-of-bounds rows (undefined content — NaN in interpret mode);
    stores clamp them away but the REDUCTION must mask them, same as
    the layernorm bwd row-validity mask. Zero-padded patch rows below
    ``nrows`` contribute 0 on their own."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    acc = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
    co = acc.astype(dt)
    co_ref[:] = co
    cf = co.astype(jnp.float32)
    row = i * tile_m + lax.broadcasted_iota(jnp.int32, cf.shape, 0)
    ps_ref[0] = jnp.sum(jnp.where(row < nrows, cf, 0.0), axis=0)


def _centered_sumsq_kernel(co_ref, mean_ref, pss_ref, *, nrows, tile_m):
    """Per-tile partial of sum((co - mean)^2): the CENTERED second
    statistics pass. E[x^2] - mean^2 would be one pass cheaper but
    catastrophically cancels for large-mean channels (f32 carries ~7
    digits; a channel at mean 100, std 0.1 loses the variance
    entirely) — the two-pass form matches the unfused batch_norm
    kernel's jnp.var numerics class. Padded rows are masked (zero co
    minus a nonzero mean would otherwise contribute mean^2 each)."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    cf = co_ref[:].astype(jnp.float32)
    d = cf - mean_ref[0]
    row = i * tile_m + lax.broadcasted_iota(jnp.int32, cf.shape, 0)
    d = jnp.where(row < nrows, d, 0.0)
    pss_ref[0] = jnp.sum(d * d, axis=0)


def _bn_relu_kernel(co_ref, s_ref, b_ref, y_ref, *, dt):
    cf = co_ref[:].astype(jnp.float32)
    y = cf * s_ref[0] + b_ref[0]
    y_ref[:] = jnp.maximum(y, 0.0).astype(dt)


def _specs(pl, pltpu, tile_m, tile_n, kp):
    row = pl.BlockSpec((tile_m, kp), lambda i, j: (i, 0),
                       memory_space=pltpu.VMEM)
    col = pl.BlockSpec((kp, tile_n), lambda i, j: (0, j),
                       memory_space=pltpu.VMEM)
    out = pl.BlockSpec((tile_m, tile_n), lambda i, j: (i, j),
                       memory_space=pltpu.VMEM)
    vec = pl.BlockSpec((1, tile_n), lambda i, j: (0, j),
                       memory_space=pltpu.VMEM)
    part = pl.BlockSpec((1, tile_n), lambda i, j: (i, j),
                        memory_space=pltpu.VMEM)
    return row, col, out, vec, part


def _mm_affine_relu(p2, w2, scale, shift, interpret=False, tiles=None):
    """Eval-mode fused pass: ``relu((p2 @ w2) * scale + shift)``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = p2.shape
    c = w2.shape[1]
    dt = p2.dtype
    mp, kp, cp = _padded_dims(m, k, c, dt)
    tile_m, tile_n = tiles if tiles is not None else _schedule_tiles(
        mp, kp, cp, dt)
    xp = _pad_mat(p2, mp, kp)
    wp = _pad_mat(w2, kp, cp)
    sp = _pad_vec(scale.astype(jnp.float32), cp).reshape(1, cp)
    bp = _pad_vec(shift.astype(jnp.float32), cp).reshape(1, cp)
    row, col, out, vec, _ = _specs(pl, pltpu, tile_m, tile_n, kp)
    y = pl.pallas_call(
        functools.partial(_mm_affine_relu_kernel, dt=dt),
        grid=(pl.cdiv(mp, tile_m), pl.cdiv(cp, tile_n)),
        in_specs=[row, col, vec, vec],
        out_specs=out,
        out_shape=jax.ShapeDtypeStruct((mp, cp), dt),
        interpret=interpret,
    )(xp, wp, sp, bp)
    return y[:m, :c]


def _mm_stats(p2, w2, interpret=False, tiles=None):
    """Training pass 1: conv matmul + per-tile channel-sum partials in
    the same VMEM pass. Returns (co, sum) with ``co`` left PADDED
    [Mp, Cp] — the statistics and normalize passes and the backward
    kernels consume it aligned, so keeping the padding avoids a
    slice-then-repad HBM round trip of the largest intermediate (padded
    rows/cols are zero and contribute nothing to any partial)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = p2.shape
    c = w2.shape[1]
    dt = p2.dtype
    mp, kp, cp = _padded_dims(m, k, c, dt)
    tile_m, tile_n = tiles if tiles is not None else _schedule_tiles(
        mp, kp, cp, dt)
    xp = _pad_mat(p2, mp, kp)
    wp = _pad_mat(w2, kp, cp)
    row, col, out, _, part = _specs(pl, pltpu, tile_m, tile_n, kp)
    gm = pl.cdiv(mp, tile_m)
    co, ps = pl.pallas_call(
        functools.partial(_mm_stats_kernel, dt=dt, nrows=m,
                          tile_m=tile_m),
        grid=(gm, pl.cdiv(cp, tile_n)),
        in_specs=[row, col],
        out_specs=[out, part],
        out_shape=[
            jax.ShapeDtypeStruct((mp, cp), dt),
            jax.ShapeDtypeStruct((gm, cp), jnp.float32),
        ],
        interpret=interpret,
    )(xp, wp)
    return co, ps.sum(axis=0)[:c]


def _centered_sumsq(co_p, mean, nrows, interpret=False, tiles=None):
    """Training pass 2: per-channel sum((co - mean)^2) over the PADDED
    conv output (rows >= nrows masked in-kernel)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    mp, cp = co_p.shape
    c = mean.shape[0]
    tile_m, tile_n = tiles if tiles is not None else _schedule_tiles(
        mp, _LANES, cp, co_p.dtype)
    meanp = _pad_vec(mean.astype(jnp.float32), cp).reshape(1, cp)
    tile = pl.BlockSpec((tile_m, tile_n), lambda i, j: (i, j),
                        memory_space=pltpu.VMEM)
    vec = pl.BlockSpec((1, tile_n), lambda i, j: (0, j),
                       memory_space=pltpu.VMEM)
    part = pl.BlockSpec((1, tile_n), lambda i, j: (i, j),
                        memory_space=pltpu.VMEM)
    gm = pl.cdiv(mp, tile_m)
    pss = pl.pallas_call(
        functools.partial(_centered_sumsq_kernel, nrows=nrows,
                          tile_m=tile_m),
        grid=(gm, pl.cdiv(cp, tile_n)),
        in_specs=[tile, vec],
        out_specs=part,
        out_shape=jax.ShapeDtypeStruct((gm, cp), jnp.float32),
        interpret=interpret,
    )(co_p, meanp)
    return pss.sum(axis=0)[:c]


def _bn_relu(co, scale, shift, interpret=False, tiles=None):
    """Training pass 2: one elementwise normalize+relu pass."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, c = co.shape
    dt = co.dtype
    mp, _, cp = _padded_dims(m, 1, c, dt)
    tile_m, tile_n = tiles if tiles is not None else _schedule_tiles(
        mp, _LANES, cp, dt)
    cop = _pad_mat(co, mp, cp)
    sp = _pad_vec(scale.astype(jnp.float32), cp).reshape(1, cp)
    bp = _pad_vec(shift.astype(jnp.float32), cp).reshape(1, cp)
    tile = pl.BlockSpec((tile_m, tile_n), lambda i, j: (i, j),
                        memory_space=pltpu.VMEM)
    vec = pl.BlockSpec((1, tile_n), lambda i, j: (0, j),
                       memory_space=pltpu.VMEM)
    y = pl.pallas_call(
        functools.partial(_bn_relu_kernel, dt=dt),
        grid=(pl.cdiv(mp, tile_m), pl.cdiv(cp, tile_n)),
        in_specs=[tile, vec, vec],
        out_specs=tile,
        out_shape=jax.ShapeDtypeStruct((mp, cp), dt),
        interpret=interpret,
    )(cop, sp, bp)
    return y[:m, :c]


# -- backward kernels (training) ----------------------------------------------


def _bn_bwd_partials_kernel(co_ref, g_ref, s_ref, b_ref, pdy_ref,
                            pdyc_ref, *, nrows, tile_m):
    """Per-tile partials of sum(dy_relu) and sum(dy_relu * co): the relu
    gate recomputes from the saved conv output (pre = co*scale + shift),
    the flash-attention recompute discipline. Ragged-tail rows are
    masked out of the reductions (see _mm_stats_kernel)."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    cf = co_ref[:].astype(jnp.float32)
    pre = cf * s_ref[0] + b_ref[0]
    dyr = jnp.where(pre > 0, g_ref[:].astype(jnp.float32), 0.0)
    row = i * tile_m + lax.broadcasted_iota(jnp.int32, cf.shape, 0)
    valid = row < nrows
    dyr = jnp.where(valid, dyr, 0.0)
    pdy_ref[0] = jnp.sum(dyr, axis=0)
    # cf must be masked too: 0 * (out-of-bounds NaN) is still NaN
    pdyc_ref[0] = jnp.sum(dyr * jnp.where(valid, cf, 0.0), axis=0)


def _bn_bwd_dco_kernel(co_ref, g_ref, s_ref, b_ref, k3_ref, b0_ref,
                       dco_ref):
    """Folded BN backward, elementwise: d_co = k1*dy_relu - k3*co - b0
    (k1 = scale = gamma*rstd; k3/b0 fold the batch-statistic terms)."""
    cf = co_ref[:].astype(jnp.float32)
    pre = cf * s_ref[0] + b_ref[0]
    dyr = jnp.where(pre > 0, g_ref[:].astype(jnp.float32), 0.0)
    dco_ref[:] = s_ref[0] * dyr - k3_ref[0] * cf - b0_ref[0]


def _bn_bwd_partials(co, g2, scale, shift, interpret=False, tiles=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, c = co.shape
    dt = co.dtype
    mp, _, cp = _padded_dims(m, 1, c, dt)
    tile_m, tile_n = tiles if tiles is not None else _schedule_tiles(
        mp, _LANES, cp, dt)
    cop = _pad_mat(co, mp, cp)
    gp = _pad_mat(g2, mp, cp)  # zero-padded rows/cols -> exact partials
    sp = _pad_vec(scale, cp).reshape(1, cp)
    bp = _pad_vec(shift, cp).reshape(1, cp)
    tile = pl.BlockSpec((tile_m, tile_n), lambda i, j: (i, j),
                        memory_space=pltpu.VMEM)
    vec = pl.BlockSpec((1, tile_n), lambda i, j: (0, j),
                       memory_space=pltpu.VMEM)
    part = pl.BlockSpec((1, tile_n), lambda i, j: (i, j),
                        memory_space=pltpu.VMEM)
    gm = pl.cdiv(mp, tile_m)
    pdy, pdyc = pl.pallas_call(
        functools.partial(_bn_bwd_partials_kernel, nrows=m,
                          tile_m=tile_m),
        grid=(gm, pl.cdiv(cp, tile_n)),
        in_specs=[tile, tile, vec, vec],
        out_specs=[part, part],
        out_shape=[
            jax.ShapeDtypeStruct((gm, cp), jnp.float32),
            jax.ShapeDtypeStruct((gm, cp), jnp.float32),
        ],
        interpret=interpret,
    )(cop, gp, sp, bp)
    return pdy.sum(axis=0)[:c], pdyc.sum(axis=0)[:c]


def _bn_bwd_dco(co, g2, scale, shift, k3, b0, interpret=False, tiles=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, c = co.shape
    dt = co.dtype
    mp, _, cp = _padded_dims(m, 1, c, dt)
    tile_m, tile_n = tiles if tiles is not None else _schedule_tiles(
        mp, _LANES, cp, dt)
    cop = _pad_mat(co, mp, cp)
    gp = _pad_mat(g2, mp, cp)
    vecs = [
        _pad_vec(v, cp).reshape(1, cp) for v in (scale, shift, k3, b0)
    ]
    tile = pl.BlockSpec((tile_m, tile_n), lambda i, j: (i, j),
                        memory_space=pltpu.VMEM)
    vec = pl.BlockSpec((1, tile_n), lambda i, j: (0, j),
                       memory_space=pltpu.VMEM)
    dco = pl.pallas_call(
        _bn_bwd_dco_kernel,
        grid=(pl.cdiv(mp, tile_m), pl.cdiv(cp, tile_n)),
        in_specs=[tile, tile, vec, vec, vec, vec],
        out_specs=tile,
        out_shape=jax.ShapeDtypeStruct((mp, cp), jnp.float32),
        interpret=interpret,
    )(cop, gp, *vecs)
    return dco[:m, :c]


# -- custom-vjp cores ---------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _train_core(p2, w2, gamma, beta, eps, interpret):
    y2, _, mean, var = _train_fwd_impl(p2, w2, gamma, beta, eps, interpret)
    return y2, mean, var


def _train_fwd_impl(p2, w2, gamma, beta, eps, interpret):
    m, c = p2.shape[0], w2.shape[1]
    co_p, s = _mm_stats(p2, w2, interpret=interpret)  # co PADDED
    mean = s / m
    # centered two-pass variance (biased, like jnp.var) — see
    # _centered_sumsq_kernel for why E[x^2]-mean^2 is not an option
    var = _centered_sumsq(co_p, mean, m, interpret=interpret) / m
    rstd = lax.rsqrt(var + eps)
    scale = gamma * rstd
    shift = beta - mean * scale
    # co_p is already tile-aligned: the normalize pass pads nothing
    y2 = _bn_relu(co_p, scale, shift, interpret=interpret)[:m, :c]
    return y2, co_p, mean, var


def _train_core_fwd(p2, w2, gamma, beta, eps, interpret):
    y2, co, mean, var = _train_fwd_impl(p2, w2, gamma, beta, eps,
                                        interpret)
    return (y2, mean, var), (p2, w2, gamma, beta, co, mean, var)


def _train_core_bwd(eps, interpret, saved, cots):
    p2, w2, gamma, beta, co_p, mean, var = saved  # co_p PADDED [Mp, Cp]
    g, _, _ = cots  # the batch-stat outputs feed only the DETACHED
    #                 running-stat blend: their cotangents are zero
    m, c = p2.shape[0], w2.shape[1]
    mp, cp = co_p.shape
    gp = _pad_mat(g, mp, cp)  # zero pad rows/cols -> exact partials
    rstd = lax.rsqrt(var + eps)
    scale = gamma * rstd
    shift = beta - mean * scale
    sum_dy, sum_dyc = _bn_bwd_partials(co_p, gp, scale, shift,
                                       interpret=interpret)
    sum_dy, sum_dyc = sum_dy[:c], sum_dyc[:c]
    dbeta = sum_dy
    dgamma = (sum_dyc - mean * sum_dy) * rstd
    c1 = sum_dy / m
    c2 = dgamma / m                               # = mean(dy * xhat)
    k3 = scale * c2 * rstd
    b0 = scale * c1 - k3 * mean
    dco = _bn_bwd_dco(co_p, gp, scale, shift, k3, b0,
                      interpret=interpret)[:m, :c]
    # matmul grads: MXU dots through XLA (dco sliced back to the real
    # extent; p2's padded rows were zero, so nothing was ever lost)
    dp2 = jnp.dot(dco, w2.astype(jnp.float32).T).astype(p2.dtype)
    dw2 = jnp.dot(p2.astype(jnp.float32).T, dco).astype(w2.dtype)
    return dp2, dw2, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype)


_train_core.defvjp(_train_core_fwd, _train_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _eval_core(p2, w2, gamma, beta, mean, var, eps, interpret):
    rstd = lax.rsqrt(var + eps)
    scale = gamma * rstd
    shift = beta - mean * scale
    return _mm_affine_relu(p2, w2, scale, shift, interpret=interpret)


def _eval_expr(p2, w2, gamma, beta, mean, var, eps, dt):
    """The eval-mode math as plain jnp (the backward recompute)."""
    acc = jnp.dot(p2, w2, preferred_element_type=jnp.float32)
    co = acc.astype(dt).astype(jnp.float32)
    rstd = lax.rsqrt(var + eps)
    y = (co - mean) * rstd * gamma + beta
    return jnp.maximum(y, 0.0).astype(dt)


def _eval_core_fwd(p2, w2, gamma, beta, mean, var, eps, interpret):
    y = _eval_core(p2, w2, gamma, beta, mean, var, eps, interpret)
    return y, (p2, w2, gamma, beta, mean, var)


def _eval_core_bwd(eps, interpret, saved, g):
    # inference backward is off the training hot path: exact grads via
    # the jnp recompute (one extra matmul, the recompute discipline)
    p2, w2, gamma, beta, mean, var = saved
    _, vjp = jax.vjp(
        lambda *a: _eval_expr(*a, eps, p2.dtype),
        p2, w2, gamma, beta, mean, var)
    return vjp(g)


_eval_core.defvjp(_eval_core_fwd, _eval_core_bwd)


# -- dispatch -----------------------------------------------------------------


def _supported(x, w, stride, padding, data_format, dilation, groups):
    if not on_tpu_platform():
        return False
    if str(x.dtype) not in _SUBLANES or x.dtype != w.dtype:
        return False
    if groups != 1 or _pair(dilation) != (1, 1):
        return False
    if x.ndim != 4 or w.ndim != 4:
        return False
    if _norm_padding(padding) is None:
        return False
    if data_format not in ("NCHW", "NHWC"):
        return False
    cout = w.shape[0]
    # tiny convs are not worth two pallas dispatches
    return x.shape[0] * cout >= 8 * _LANES // 2


def _fused(x, w, gamma, beta, mean, var, *, stride, padding, training,
           momentum, eps, data_format, interpret=False, force=False):
    if not force and not _supported(x, w, stride, padding, data_format,
                                    1, 1):
        return _reference(x, w, gamma, beta, mean, var, stride=stride,
                          padding=padding, training=training,
                          momentum=momentum, eps=eps,
                          data_format=data_format)
    pad = _norm_padding(padding)
    p2, w2, (n, oh, ow) = _as_matmul(x, w, stride, pad, data_format)
    cout = w.shape[0]
    gf = gamma.astype(jnp.float32)
    bf = beta.astype(jnp.float32)
    if training:
        y2, bmean, bvar = _train_core(p2, w2, gf, bf, float(eps),
                                      bool(interpret))
        # the same running-stat blend as the batch_norm op kernel
        new_mean = momentum * mean + (1 - momentum) * bmean.astype(
            mean.dtype)
        new_var = momentum * var + (1 - momentum) * bvar.astype(var.dtype)
    else:
        y2 = _eval_core(p2, w2, gf, bf, mean.astype(jnp.float32),
                        var.astype(jnp.float32), float(eps),
                        bool(interpret))
        new_mean, new_var = mean, var
    y = y2.reshape(n, oh, ow, cout)
    if data_format == "NCHW":
        y = jnp.moveaxis(y, -1, 1)
    return y, new_mean, new_var


def conv_bn_relu(x, weight, gamma, beta, running_mean, running_var, *,
                 stride=1, padding=0, epsilon=1e-5, momentum=0.9,
                 training=False, data_format="NCHW"):
    """Fused ``relu(batch_norm(conv2d(x, weight)))``.

    Returns ``(y, new_running_mean, new_running_var)`` with the exact
    batch_norm running-stat semantics (``running = momentum*running +
    (1-momentum)*batch``; unchanged in eval mode). Accepts Tensors
    (autograd-tracked through the op tape) or raw arrays; pallas on TPU
    for admitted shapes, the identical unfused op sequence elsewhere.
    The conv must be bias-free, ungrouped, undilated (the vision-path
    triple this fusion targets).
    """
    from ...framework.tensor import Tensor

    attrs = dict(stride=stride, padding=padding, training=bool(training),
                 momentum=float(momentum), eps=float(epsilon),
                 data_format=data_format)
    args = (x, weight, gamma, beta, running_mean, running_var)
    if any(isinstance(t, Tensor) for t in args):
        from ...framework.autograd import apply_op

        tensors = [
            t if isinstance(t, Tensor) else Tensor._from_array(jnp.asarray(t))
            for t in args
        ]
        return apply_op(
            "fused_conv_bn_relu",
            lambda x, w, g, b, m, v: _fused(x, w, g, b, m, v, **attrs),
            tensors, {})
    return _fused(*(jnp.asarray(a) for a in args), **attrs)
