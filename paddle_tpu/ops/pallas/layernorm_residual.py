"""Fused residual-add + LayerNorm (TPU pallas kernel, fwd + bwd).

The post-norm transformer's hottest pointwise chain is

    y = LayerNorm(x + residual)

— on the op-by-op path that is an HBM round trip for the add, another
for the statistics, and a third for the affine output. The pallas
kernel does it in ONE VMEM pass per row tile: compute ``a = x + res``,
the f32 mean/rstd, and ``xhat * w + b`` without ever materializing the
sum in HBM. The backward is a second kernel over the same tiles using
the saved per-row ``(mean, rstd)``: it recomputes ``a`` from the saved
inputs (cheaper than saving ``xhat`` — the flash-attention recompute
discipline), emits ``d_input`` (= dx = dresidual) plus per-tile partial
``dw``/``db`` sums that one tiny jnp reduction finishes.

Off-TPU (and for unadmitted shapes) the jnp fallback computes the
IDENTICAL primitive sequence the ``layer_norm`` op kernel uses (f32
statistics, output cast back to the input dtype), so enabling
``FLAGS_use_fused_layernorm`` never changes f32 numerics — only where
the fusion happens (Mosaic vs XLA). The kernels express the residual
add in the INPUT dtype (same expression as the unfused path) so both
compile to the same arithmetic; for bf16 inputs agreement is to 1 ulp
rather than bit-exact, because XLA itself keeps or drops the bf16
rounding of fused intermediates depending on fusion decisions — on
both paths equally (a jitted bf16+bf16 add already computes in f32
without intermediate rounding on XLA:CPU).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..._internal_tuning import register_schedule, resolve_schedule
from ._platform import on_tpu_platform

__all__ = ["layernorm_residual"]

_LANES = 128
_BLOCK_R = 256  # max rows per program
_MAX_H = 16384  # _supported bound: block_r floors at 8 rows ≤ 2 MB f32


def _block_rows(rows, h):
    """Rows per program, scaled so one f32 row block stays ≤ ~2 MB —
    the bwd kernel keeps a handful of blocks live, so an unscaled
    (256, H) tile blows the ~16 MB VMEM budget once H > 2048. This is
    the schedule space's DEFAULT point: untuned resolution returns
    exactly this geometry."""
    cap = max(8, min(_BLOCK_R, (1 << 21) // (4 * h)))
    return min(cap, rows)


def _schedule_block_rows(rows, h, dtype) -> int:
    """Row-block size through the autotuner: tuned winner for this
    (device_kind, shape-bucket, dtype) when cached, else the
    byte-identical :func:`_block_rows` default."""
    params = resolve_schedule("layernorm_residual", rows=int(rows),
                              h=int(h), dtype=str(dtype))
    return max(1, min(int(params["block_r"]), rows))


def _tuning_bench(info):
    """Measurement builder for the tuner: one jitted fwd pass at the
    candidate's row block (interpret off-TPU, so the CPU smoke can
    drive the full search pipeline)."""
    import numpy as np

    rows, h = int(info["rows"]), int(info["h"])
    dtype = str(info.get("dtype", "float32"))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(rows, h).astype("f4")).astype(dtype)
    r = jnp.asarray(rng.randn(rows, h).astype("f4")).astype(dtype)
    w = jnp.asarray(rng.randn(h).astype("f4"))
    b = jnp.asarray(rng.randn(h).astype("f4"))
    interpret = not on_tpu_platform()

    def builder(params):
        block_r = max(1, min(int(params["block_r"]), rows))
        fn = jax.jit(lambda x, r, w, b: _pallas_fwd(
            x, r, w, b, 1e-5, interpret=interpret, block_r=block_r))

        def run():
            jax.block_until_ready(fn(x, r, w, b))

        return run

    return builder


register_schedule(
    name="layernorm_residual",
    version=1,
    params={"block_r": (8, 16, 32, 64, 128, 256, 512)},
    default=lambda info: {"block_r": _block_rows(info["rows"], info["h"])},
    # one row block must stay within the searchable VMEM headroom (the
    # bwd kernel keeps several live; 4 MB/block is the admission line)
    supported=lambda info, c: (8 <= c["block_r"] <= 1024
                               and c["block_r"] * info["h"] * 4 <= (1 << 22)),
    bench=_tuning_bench,
)


# -- reference / fallback -----------------------------------------------------


def _reference(x, res, w, b, eps):
    """Exactly the layer_norm op-kernel math over ``x + res`` (same
    primitives, same order — bit-identical to norm(residual + y))."""
    a = x + res
    af = a.astype(jnp.float32) if a.dtype != jnp.float32 else a
    mean = jnp.mean(af, axis=-1, keepdims=True)
    var = jnp.var(af, axis=-1, keepdims=True)
    y = (af - mean) * lax.rsqrt(var + eps)
    y = y * w + b
    return y.astype(x.dtype)


# -- pallas kernels -----------------------------------------------------------


def _fwd_kernel(x_ref, r_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *,
                eps, dt):
    # the add happens in the INPUT dtype ``dt`` (bf16 rounds), exactly
    # like the unfused norm(x + res) path — only the statistics are
    # f32. ``dt`` is passed statically because interpret mode presents
    # bf16 refs as f32 (losslessly, so the cast recovers input dtype)
    a = (x_ref[:].astype(dt) + r_ref[:].astype(dt)).astype(jnp.float32)
    mean = jnp.mean(a, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(a - mean), axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    xhat = (a - mean) * rstd
    y = xhat * w_ref[0].astype(jnp.float32) + b_ref[0].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _bwd_kernel(x_ref, r_ref, w_ref, mean_ref, rstd_ref, dy_ref, da_ref,
                dwp_ref, dbp_ref, *, nrows, block_r, dt):
    """One (row-tile) program: d_input rows + partial dw/db sums.

    Tail tiles carry padding rows whose content is undefined — the
    row-validity mask zeroes their contribution to the dw/db partials
    (da writes to padding rows are dropped by the masked block store).
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    # input-dtype add, matching the fwd kernel and the unfused path
    # (static ``dt``; see the fwd kernel on interpret-mode refs)
    a = (x_ref[:].astype(dt) + r_ref[:].astype(dt)).astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    w = w_ref[0].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    xhat = (a - mean) * rstd
    wdy = dy * w
    c1 = jnp.mean(wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    da = rstd * (wdy - c1 - xhat * c2)
    da_ref[:] = da.astype(da_ref.dtype)
    # mask padding rows out of the cross-row reductions
    row = i * block_r + lax.broadcasted_iota(jnp.int32, dy.shape, 0)
    valid = row < nrows
    dy_m = jnp.where(valid, dy, 0.0)
    dwp_ref[0] = jnp.sum(dy_m * jnp.where(valid, xhat, 0.0), axis=0)
    dbp_ref[0] = jnp.sum(dy_m, axis=0)


def _pallas_fwd(x2, r2, w, b, eps, interpret=False, block_r=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, h = x2.shape
    if block_r is None:
        block_r = _schedule_block_rows(rows, h, x2.dtype)
    grid = (pl.cdiv(rows, block_r),)
    row_spec = pl.BlockSpec((block_r, h), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, h), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((block_r, 1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, dt=x2.dtype),
        grid=grid,
        in_specs=[row_spec, row_spec, vec_spec, vec_spec],
        out_specs=[row_spec, col_spec, col_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, h), x2.dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, r2, w.reshape(1, h), b.reshape(1, h))
    return y, mean, rstd


def _pallas_bwd(x2, r2, w, mean, rstd, dy2, interpret=False, block_r=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, h = x2.shape
    if block_r is None:
        block_r = _schedule_block_rows(rows, h, x2.dtype)
    ntiles = pl.cdiv(rows, block_r)
    row_spec = pl.BlockSpec((block_r, h), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, h), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((block_r, 1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    part_spec = pl.BlockSpec((1, h), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    da, dwp, dbp = pl.pallas_call(
        functools.partial(_bwd_kernel, nrows=rows, block_r=block_r,
                          dt=x2.dtype),
        grid=(ntiles,),
        in_specs=[row_spec, row_spec, vec_spec, col_spec, col_spec,
                  row_spec],
        out_specs=[row_spec, part_spec, part_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, h), x2.dtype),
            jax.ShapeDtypeStruct((ntiles, h), jnp.float32),
            jax.ShapeDtypeStruct((ntiles, h), jnp.float32),
        ],
        interpret=interpret,
    )(x2, r2, w.reshape(1, h), mean, rstd, dy2)
    return da, dwp.sum(axis=0), dbp.sum(axis=0)


# -- custom-vjp wiring --------------------------------------------------------


def _supported(x, w, b) -> bool:
    if not on_tpu_platform():
        return False
    if str(x.dtype) not in ("float32", "bfloat16"):
        return False
    h = x.shape[-1]
    return (h % _LANES == 0 and h <= _MAX_H
            and w.shape == (h,) and b.shape == (h,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _ln_res(x, res, w, b, eps):
    if _supported(x, w, b):
        x2 = x.reshape(-1, x.shape[-1])
        y, _, _ = _pallas_fwd(x2, res.reshape(x2.shape), w, b, eps)
        return y.reshape(x.shape)
    return _reference(x, res, w, b, eps)


def _ln_res_fwd(x, res, w, b, eps):
    if _supported(x, w, b):
        x2 = x.reshape(-1, x.shape[-1])
        r2 = res.reshape(x2.shape)
        y, mean, rstd = _pallas_fwd(x2, r2, w, b, eps)
        return y.reshape(x.shape), (x, res, w, b, mean, rstd)
    return _reference(x, res, w, b, eps), (x, res, w, b, None, None)


def _ln_res_bwd(eps, saved, g):
    x, res, w, b, mean, rstd = saved
    if mean is not None:  # pallas path
        h = x.shape[-1]
        da, dw, db = _pallas_bwd(
            x.reshape(-1, h), res.reshape(-1, h), w, mean, rstd,
            g.reshape(-1, h))
        da = da.reshape(x.shape)
        return da, da, dw.astype(w.dtype), db.astype(b.dtype)
    _, vjp = jax.vjp(lambda x, r, w, b: _reference(x, r, w, b, eps),
                     x, res, w, b)
    return vjp(g)


_ln_res.defvjp(_ln_res_fwd, _ln_res_bwd)


def layernorm_residual(x, residual, weight, bias, epsilon=1e-5):
    """Fused ``LayerNorm(x + residual)`` over the last dimension.

    Accepts Tensors (autograd-tracked through the framework's op tape)
    or raw arrays. ``weight``/``bias`` are the LayerNorm affine params
    ``[H]``. Pallas on TPU for lane-aligned ``H``; jnp fallback with the
    identical primitive sequence elsewhere.
    """
    from ...framework.tensor import Tensor

    eps = float(epsilon)
    if isinstance(x, Tensor) or isinstance(residual, Tensor):
        from ...framework.autograd import apply_op

        tensors = [
            t if isinstance(t, Tensor) else Tensor._from_array(jnp.asarray(t))
            for t in (x, residual, weight, bias)
        ]
        return apply_op(
            "fused_layernorm_residual",
            lambda x, r, w, b: _ln_res(x, r, w, b, eps), tensors, {})
    return _ln_res(jnp.asarray(x), jnp.asarray(residual),
                   jnp.asarray(weight), jnp.asarray(bias), eps)
