"""Pallas TPU kernels for hot ops.

Reference parity: the role of hand-written CUDA kernels in
paddle/fluid/operators/fused/ (multihead_matmul_op.cu — BERT fused
attention) and operators/jit/ (runtime-codegen CPU kernels) — here as
Pallas kernels compiled through Mosaic for the TPU's MXU/VMEM.
"""
from .conv_bn_relu import conv_bn_relu  # noqa: F401
from .flash_attention import flash_attention  # noqa: F401
from .int8_matmul import int8_matmul  # noqa: F401
from .layernorm_residual import layernorm_residual  # noqa: F401
from .optimizer_update import fused_momentum_update  # noqa: F401
