"""Fused max-pool backward pallas kernel.

Reference parity: the backward of pool2d (paddle/fluid/operators/pool_op.cc
MaxPool2dGradFunctor — CUDA walks each window and routes the gradient to
the first max position). XLA lowers the same vjp to select_and_scatter,
which on TPU costs ~2.6 ms/step at the ResNet-50 stem shape (measured by
zero-backward ablation, [128,64,112,112] batch 128): the select scan and
the scatter run as separate HBM passes.

This kernel fuses the whole backward into ONE HBM pass: read x, y, dy
once, write dx once. Mosaic constraints shape the implementation:

- strided slices/reshape-interleaves are unsupported on the LANE (W)
  axis, so all stride-s W motion runs on the MXU as matmuls against
  one-hot selection matrices built from iota (exact for bf16 operands;
  ``Precision.HIGHEST`` — bf16x3, reconstructing all 24 mantissa bits —
  for f32, keeping the x == max equality comparison faithful);
- the SUBLANE (H) axis supports split/merge reshapes, so H de-striding is
  a reshape+index and H re-striding is a zero-interleave (stack+reshape).

Tie handling is first-max-wins over row-major window taps — the identical
subgradient to select_and_scatter's ge-select and the reference CUDA
kernel. Grid: rows of the collapsed [N*C] axis; each program holds full
spatial planes in VMEM (stem shape: ~1 MB per 8-row block in f32).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..._internal_tuning import register_schedule, resolve_schedule
from ._platform import on_tpu_platform

__all__ = ["max_pool2d_backward", "max_pool_backward_supported"]


def _row_elems(h, w, oh, ow, ph, pw):
    """The kernel's rough f32 working set per [N*C] row (module
    docstring: padded planes + half-width planes + coarse planes)."""
    hp, wp = h + 2 * ph, w + 2 * pw
    return 3 * hp * wp + 6 * hp * ow + 6 * oh * ow + 2 * h * w


def _default_block_rows(r, h, w, oh, ow, ph, pw):
    """The historical policy: start at 8 rows, halve until the block
    fits ~2 MB AND divides the collapsed [N*C] axis — the schedule
    space's byte-identical default point."""
    elems = _row_elems(h, w, oh, ow, ph, pw)
    br = 8
    while br > 1 and br * elems * 4 > (2 << 20):
        br //= 2
    while r % br:
        br //= 2
    return br


def _schedule_block_rows(r, h, w, oh, ow, ph, pw, dtype) -> int:
    params = resolve_schedule("pool_backward", r=int(r), h=int(h),
                              w=int(w), oh=int(oh), ow=int(ow),
                              ph=int(ph), pw=int(pw), dtype=str(dtype))
    return int(params["block_rows"])


def _tuning_bench(info):
    import numpy as np
    from jax import lax

    r, h, w = int(info["r"]), int(info["h"]), int(info["w"])
    oh, ow = int(info["oh"]), int(info["ow"])
    # a 2x2/2 pool reproduces the (h, w) -> (oh, ow) geometry the shape
    # bucket describes when oh = h//2; bench shapes should respect that
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1, r, h, w).astype("f4"))
    y = lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, 2, 2),
                          (1, 1, 2, 2), [(0, 0)] * 4)
    dy = jnp.asarray(rng.randn(*y.shape).astype("f4"))
    interpret = not on_tpu_platform()

    def builder(params):
        br = int(params["block_rows"])

        def run():
            jax.block_until_ready(max_pool2d_backward(
                x, y, dy, kernel=(2, 2), stride=(2, 2), padding=(0, 0),
                interpret=interpret, block_rows=br))

        return run

    return builder


register_schedule(
    name="pool_backward",
    version=1,
    params={"block_rows": (1, 2, 4, 8, 16)},
    default=lambda info: {"block_rows": _default_block_rows(
        info["r"], info["h"], info["w"], info["oh"], info["ow"],
        info["ph"], info["pw"])},
    # must divide the collapsed row axis exactly (the grid floor-divides)
    # and keep the block within 2x the historical ~2 MB VMEM line
    supported=lambda info, c: (
        info["r"] % c["block_rows"] == 0
        and c["block_rows"] * _row_elems(
            info["h"], info["w"], info["oh"], info["ow"],
            info["ph"], info["pw"]) * 4 <= (4 << 20)),
    bench=_tuning_bench,
)


def _onehot(rows, cols, row_of_col_fn, dtype):
    """M[r, c] = 1 where r == row_of_col_fn(c) — built from 2D iota."""
    r = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
    return (r == row_of_col_fn(c)).astype(dtype)


def _matmul(a, b, precision):
    return jax.lax.dot_general(
        a, b, (((a.ndim - 1,), (0,)), ((), ())),
        precision=precision, preferred_element_type=jnp.float32,
    )


def _pool_bwd_kernel(x_ref, y_ref, dy_ref, dx_ref, *, kh, kw, sh, sw,
                     ph, pw, oh, ow, h, w, precision):
    # all in-kernel compute runs in f32: Mosaic rejects bf16 sublane
    # stack/reshape, and f32 is exact for bf16-origin values (the matmul
    # precision still follows the input dtype — DEFAULT rounds operands
    # to bf16, lossless for bf16 data)
    dt = jnp.float32
    x = x_ref[...].astype(dt)       # [R, H, W]
    y = y_ref[...].astype(dt)       # [R, OH, OW]
    dy = dy_ref[...].astype(dt)
    r = x.shape[0]
    hp, wp = h + 2 * ph, w + 2 * pw
    hpe = hp + (-hp) % sh           # padded-H rounded up to the stride
    # pad x with a huge finite negative so padded cells never match the
    # window max — NOT -inf (the one-hot matmuls would turn -inf * 0
    # into NaN) and bf16-representable (f32 min overflows to -inf when
    # the MXU rounds operands to bf16)
    neg = jnp.asarray(-1.0e38, dt)
    xp = jnp.pad(x, ((0, 0), (ph, hpe - h - ph), (pw, pw)),
                 constant_values=neg)

    # W de-stride on the MXU: X_dj[r, i, wj] = xp[r, i, sw*wj + dj],
    # then split H phases ONCE per dj (sublane reshape): ph_q holds rows
    # q, q+sh, ... — every (di, dj) tap is then a cheap static slice
    phases = []                     # phases[dj][q] : [R, HPE/sh, OW]
    for dj in range(kw):
        g = _onehot(wp, ow, lambda c, dj=dj: sw * c + dj, dt)
        xc = _matmul(xp, g, precision).astype(dt)        # [R, HPE, OW]
        split = xc.reshape(r, hpe // sh, sh, ow)
        phases.append([split[:, :, q, :] for q in range(sh)])

    # first-max-wins selection per tap, row-major over (di, dj); the
    # per-tap gradient stays on the COARSE [OH, OW] grid (no relayouts
    # inside the loop)
    taken = jnp.zeros((r, oh, ow), jnp.bool_)
    coarse = [[None] * kw for _ in range(kh)]
    for di in range(kh):
        q, off = di % sh, di // sh
        for dj in range(kw):
            xw = jax.lax.slice(
                phases[dj][q], (0, off, 0), (r, off + oh, ow))
            sel = jnp.logical_and(xw == y, jnp.logical_not(taken))
            taken = jnp.logical_or(taken, sel)
            coarse[di][dj] = jnp.where(sel, dy, jnp.asarray(0, dt))

    # H re-stride: merge taps sharing a phase (shifted adds on the coarse
    # grid), then ONE interleave per dj; W re-stride on the MXU
    dxw = []
    nrow = hpe // sh
    for dj in range(kw):
        combs = []
        for q in range(sh):
            acc = jnp.zeros((r, nrow, ow), dt)
            for di in range(q, kh, sh):
                off = di // sh
                acc = acc + jnp.pad(
                    coarse[di][dj],
                    ((0, 0), (off, nrow - oh - off), (0, 0)))
            combs.append(acc)
        inter = jnp.stack(combs, axis=2).reshape(r, hpe, ow)
        dxw.append(inter)
    cat = jnp.concatenate(dxw, axis=2)                  # [R, HPE, kw*OW]
    es = []
    for dj in range(kw):
        rr = jax.lax.broadcasted_iota(jnp.int32, (ow, wp), 0)
        cc = jax.lax.broadcasted_iota(jnp.int32, (ow, wp), 1)
        es.append((cc == sw * rr + dj).astype(dt))
    e = jnp.concatenate(es, axis=0)                     # [kw*OW, WP]
    dxp = _matmul(cat, e, precision)                    # [R, HPE, WP]
    dx_ref[...] = dxp[:, ph:ph + h, pw:pw + w].astype(dx_ref.dtype)


def max_pool2d_backward(x, y, dy, *, kernel, stride, padding,
                        interpret=False, block_rows=None):
    """dx for max pooling: x [N,C,H,W], y/dy [N,C,OH,OW] -> dx like x.

    First-max-wins tie semantics, matching XLA select_and_scatter (and the
    reference CUDA MaxPool2dGradFunctor). The rows-per-program schedule
    resolves through the autotuner OUTSIDE the jitted impl (it is a
    static argument, so a tuned swap retraces instead of reusing the
    old grid).
    """
    ph, pw = padding
    n, c, h, w = x.shape
    oh, ow = y.shape[2], y.shape[3]
    if block_rows is None:
        block_rows = _schedule_block_rows(n * c, h, w, oh, ow, ph, pw,
                                          x.dtype)
    return _max_pool2d_backward(x, y, dy, kernel=tuple(kernel),
                                stride=tuple(stride),
                                padding=tuple(padding),
                                interpret=interpret,
                                block_rows=int(block_rows))


@functools.partial(
    jax.jit, static_argnames=("kernel", "stride", "padding", "interpret",
                              "block_rows"))
def _max_pool2d_backward(x, y, dy, *, kernel, stride, padding,
                         interpret, block_rows):
    kh, kw = kernel
    sh, sw = stride
    ph, pw = padding
    n, c, h, w = x.shape
    oh, ow = y.shape[2], y.shape[3]
    r = n * c
    br = block_rows
    precision = (jax.lax.Precision.DEFAULT
                 if x.dtype == jnp.bfloat16
                 else jax.lax.Precision.HIGHEST)
    xr = x.reshape(r, h, w)
    yr = y.reshape(r, oh, ow)
    dyr = dy.reshape(r, oh, ow)
    kern = functools.partial(
        _pool_bwd_kernel, kh=kh, kw=kw, sh=sh, sw=sw, ph=ph, pw=pw,
        oh=oh, ow=ow, h=h, w=w, precision=precision,
    )
    dx = pl.pallas_call(
        kern,
        grid=(r // br,),
        in_specs=[
            pl.BlockSpec((br, h, w), lambda i: (i, 0, 0)),
            pl.BlockSpec((br, oh, ow), lambda i: (i, 0, 0)),
            pl.BlockSpec((br, oh, ow), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((br, h, w), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((r, h, w), x.dtype),
        interpret=interpret,
    )(xr, yr, dyr)
    return dx.reshape(n, c, h, w)


def max_pool_backward_supported(x_shape, dtype, ks, st, p, ceil_extra,
                                data_format):
    """Gate for the pallas path: TPU backend, NCHW 4D floating input,
    symmetric padding (no ceil_mode tail), spatial dims known."""
    if not on_tpu_platform():
        return False
    if data_format != "NCHW" or len(x_shape) != 4:
        return False
    if ceil_extra != (0, 0):
        return False
    if not jnp.issubdtype(dtype, jnp.floating):
        return False
    # window must actually cover the input (standard pooling geometry)
    return all(int(d) > 0 for d in x_shape)
