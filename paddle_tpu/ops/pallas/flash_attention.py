"""Flash attention (TPU pallas kernel) with in-kernel dropout + backward.

Reference parity: operators/fused/multihead_matmul_op.cu fuses BERT
attention into one CUDA kernel; this is the TPU equivalent with the
flash-attention online-softmax construction so the [L, L] score matrix
never materializes in HBM — only [BQ, BK] tiles live in VMEM.

Design (per /opt/skills/guides/pallas_guide.md):
- grid = (B*H, L/BQ): one program per query tile per head.
- K/V for the head stay as VMEM blocks; the kernel walks K-tiles with a
  fori_loop, keeping running max m, denominator l, and an f32 accumulator
  in VMEM scratch (MXU matmuls via jnp.dot with
  preferred_element_type=f32).
- causal masking prunes fully-masked K-tiles by bounding the loop.
- dropout runs INSIDE the kernel via the per-core TPU PRNG: each
  (bh, q-tile, k-tile) re-seeds with pltpu.prng_seed(seed, bh, qi, ki)
  so forward and backward regenerate bit-identical masks in any grid
  order — no [B, H, L, L] mask ever touches HBM.
- backward: two pallas kernels (dQ over q-tiles; dK/dV over k-tiles)
  using the saved per-row logsumexp, recomputing probability tiles on
  the fly (standard FlashAttention backward).
- bias gradient: exact on the jnp fallback path and on the pallas path
  with dropout == 0. On the pallas path with dropout > 0 the bias is
  treated as NON-TRAINABLE (gradient is zeros) — attention masks in
  every reference model derive from integer inputs and carry no
  gradient; use dropout=0.0 for a trainable attention bias.

Falls back to a pure-jnp path off-TPU (CPU tests) and for dtypes/shapes
the kernel does not support; the fallback implements dropout from the
same integer seed via jax.random, so its recompute backward sees the
same mask.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..._internal_tuning import register_schedule, resolve_schedule
from ._platform import on_tpu_platform

__all__ = ["flash_attention"]

_NEG_INF = -1e30
_BLOCK = 256  # default q/k tile (the historical hardcoded geometry)


def _schedule_blocks(b, h, lq, lk, d, dtype) -> tuple:
    """(block_q, block_k, unroll) through the autotuner; the default
    point is the historical (256, 256, unroll=1) — byte-identical when
    untuned. ``_effective_blocks`` still applies downstream, so a tuned
    block that does not divide the sequence degrades to the 128 base
    tile exactly as the defaults always have."""
    params = resolve_schedule("flash_attention", b=int(b), h=int(h),
                              lq=int(lq), lk=int(lk), d=int(d),
                              dtype=str(dtype))
    return (int(params["block_q"]), int(params["block_k"]),
            max(1, int(params.get("unroll", 1))))


def _flash_vmem_ok(info, c) -> bool:
    # per-program residents (tiled fwd): q/o tiles [BQ, D] + whole-head
    # K/V [LK, D] (2 bytes each at bf16-min) + the f32 [BQ, BK] score
    # tile; keep under ~12 MB of the 16 MB core budget
    d, lk = int(info["d"]), int(info["lk"])
    tiles = 2 * (2 * c["block_q"] * d + 2 * lk * d)
    score = 4 * c["block_q"] * c["block_k"]
    return (c["block_q"] % 128 == 0 and c["block_k"] % 128 == 0
            and c.get("unroll", 1) in (1, 2, 4)
            and tiles + score <= 12 * (1 << 20))


def _tuning_bench(info):
    b, h = int(info["b"]), int(info["h"])
    lq, lk, d = int(info["lq"]), int(info["lk"]), int(info["d"])
    dtype = str(info.get("dtype", "float32"))
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, lq, d), jnp.float32).astype(dtype)
    k = jax.random.normal(kk, (b, h, lk, d), jnp.float32).astype(dtype)
    v = jax.random.normal(kv, (b, h, lk, d), jnp.float32).astype(dtype)
    scale = float(d) ** -0.5

    def builder(params):
        bq, bk = int(params["block_q"]), int(params["block_k"])
        unroll = max(1, int(params.get("unroll", 1)))
        fn = jax.jit(lambda q, k, v: _pallas_fwd(
            q, k, v, None, jnp.int32(0), True, scale, 0.0,
            block_q=bq, block_k=bk, unroll=unroll)[0])

        def run():
            jax.block_until_ready(fn(q, k, v))

        return run

    return builder


register_schedule(
    name="flash_attention",
    version=1,
    params={"block_q": (128, 256, 512),
            "block_k": (128, 256, 512),
            "unroll": (1, 2)},
    default=lambda info: {"block_q": _BLOCK, "block_k": _BLOCK,
                          "unroll": 1},
    supported=_flash_vmem_ok,
    bench=_tuning_bench,
)


def _drop_threshold(rate: float) -> jnp.ndarray:
    """uint32 cutoff: drop where random bits < rate * 2**32."""
    return jnp.uint32(min(int(rate * 2**32), 2**32 - 1))


def _seed_tile(pltpu, seed_ref, bh, qi, ki, num_q, num_k):
    """Re-seed the per-core PRNG for one (bh, qi, ki) tile. The TPU
    accepts at most two seed values, so the tile coordinates fold into
    one unique int32; fwd and both bwd kernels call this with the same
    arguments, giving bit-identical masks in any grid order."""
    tile_id = (bh * num_q + qi) * num_k + ki
    pltpu.prng_seed(seed_ref[0], tile_id)


def _plain_attention(q, k, v, bias, causal, scale, rate=0.0, seed=None):
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        iq = jnp.arange(lq)[:, None] + (lk - lq)
        ik = jnp.arange(lk)[None, :]
        scores = jnp.where(iq >= ik, scores, _NEG_INF)
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    w = jax.nn.softmax(scores, axis=-1)
    if rate > 0.0:
        # mask derived deterministically from the integer seed so the
        # recompute-based backward regenerates the identical mask
        key = jax.random.PRNGKey(seed)
        keep = jax.random.bernoulli(key, 1.0 - rate, w.shape)
        w = jnp.where(keep, w / (1.0 - rate), 0.0)
    w = w.astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


# -- forward kernel -----------------------------------------------------------


def _fwd_core(q_ref, k_ref, v_ref, bias_ref, seed_ref, o_ref, lse_ref, *,
              scale, causal, block_k, seq_k, num_q, rate, unroll=1):
    """One (batch*head, q-tile) program.
      q_ref: [1, BQ, D]; k_ref/v_ref: [1, LK, D]; bias_ref: [1, 1, BQ, LK]
      seed_ref: [1] int32 (SMEM); o_ref: [1, BQ, D]; lse_ref: [1, BQ, 1]
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q = q_ref[0]                                      # [BQ, D] native dtype
    bq = q.shape[0]
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    q_start = qi * bq

    num_k = seq_k // block_k
    if causal:
        # K-tiles strictly after this Q-tile's last row are fully masked
        num_k_live = jnp.minimum(
            num_k, (q_start + bq + block_k - 1) // block_k
        )
    else:
        num_k_live = num_k

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)
    if rate > 0.0:
        thr = _drop_threshold(rate)
        inv_keep = 1.0 / (1.0 - rate)

    def body(ki, carry):
        m, l, acc = carry
        k_start = ki * block_k
        kt = k_ref[0, pl.ds(k_start, block_k), :]
        vt = v_ref[0, pl.ds(k_start, block_k), :]
        # native-dtype (bf16 under AMP) MXU matmul with f32 accumulate
        s = jnp.dot(q, kt.T, preferred_element_type=jnp.float32) * scale
        if causal:
            iq = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            ik = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(iq >= ik, s, _NEG_INF)
        if bias_ref is not None:
            s = s + bias_ref[0, 0, :, pl.ds(k_start, block_k)].astype(
                jnp.float32
            )
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        # the softmax denominator uses the UNdropped p; dropout scales the
        # normalized weights, which distributes onto the accumulator only
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        if rate > 0.0:
            _seed_tile(pltpu, seed_ref, bh, qi, ki, num_q, num_k)
            bits = pltpu.bitcast(
                pltpu.prng_random_bits(p.shape), jnp.uint32
            )
            p_acc = jnp.where(bits >= thr, p * inv_keep, 0.0)
        else:
            p_acc = p
        acc_new = acc * corr + jnp.dot(
            p_acc.astype(vt.dtype), vt, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k_live, body, (m0, l0, acc0),
                                  unroll=unroll)
    lsafe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / lsafe).astype(o_ref.dtype)
    # per-row logsumexp for the backward recompute
    lse_ref[0] = m + jnp.log(lsafe)


def _bdot(a, b_arr, ta=False, tb=True):
    """Batched head matmul [H, M, K] x [H, N, K]^T -> [H, M, N] (f32
    accumulate). One dot_general over all heads: Mosaic pipelines the
    per-head MXU passes without fori_loop serialization."""
    ca = 1 if ta else 2
    cb = 2 if tb else 1
    return jax.lax.dot_general(
        a, b_arr, (((ca,), (cb,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )


def _fwd_small_core(q_ref, k_ref, v_ref, bias_ref, seed_ref, o_ref,
                    lse_ref, *, scale, causal, num_heads, rate):
    """Short-sequence forward: the whole sequence fits one tile, so one
    program per BATCH item computes all heads at once with batched
    dot_generals — 12x fewer programs than the (b*h, q-tile) grid, big
    vectorized VPU ops, and the [L, L] bias is DMA'd once per batch.
    Kernel-launch/DMA overhead dominates this regime, not VMEM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bi = pl.program_id(0)
    q = q_ref[0]                                      # [H, LQ, D]
    kt = k_ref[0]                                     # [H, LK, D]
    vt = v_ref[0]                                     # [H, LK, D]
    s = _bdot(q, kt) * scale                          # [H, LQ, LK] f32
    if causal:
        iq = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        ik = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(iq >= ik, s, _NEG_INF)
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)       # [1|H, LQ, LK]
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    lsafe = jnp.where(l == 0.0, 1.0, l)
    if rate > 0.0:
        thr = _drop_threshold(rate)
        inv_keep = 1.0 / (1.0 - rate)
        # one draw covers all heads: tile_id folds (bi, h=0..H) into the
        # same id space as the (b*h)-grid kernels' single-tile case
        _seed_tile(pltpu, seed_ref, bi * num_heads, 0, 0, 1, 1)
        bits = pltpu.bitcast(pltpu.prng_random_bits(p.shape), jnp.uint32)
        p_acc = jnp.where(bits >= thr, p * inv_keep, 0.0)
    else:
        p_acc = p
    o = _bdot(p_acc.astype(vt.dtype), vt, tb=False)   # [H, LQ, D]
    o_ref[0] = (o / lsafe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(lsafe)


def _bwd_small_core(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    bias_ref, seed_ref, dq_ref, dk_ref, dv_ref, *, scale,
                    causal, num_heads, rate):
    """Short-sequence backward companion of _fwd_small_core: one program
    per batch item, all heads batched, dQ/dK/dV in one pass. Regenerates
    the forward's dropout mask (same seed tile id, same [H, LQ, LK]
    draw)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bi = pl.program_id(0)
    q = q_ref[0]                                      # [H, LQ, D]
    kt = k_ref[0]
    vt = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]                                  # [H, LQ, 1]
    delta = delta_ref[0]
    s = _bdot(q, kt) * scale                          # [H, LQ, LK]
    if causal:
        iq = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        ik = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(iq >= ik, s, _NEG_INF)
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)
    p = jnp.exp(s - lse)
    dpd = _bdot(do, vt)                               # [H, LQ, LK]
    if rate > 0.0:
        thr = _drop_threshold(rate)
        inv_keep = 1.0 / (1.0 - rate)
        _seed_tile(pltpu, seed_ref, bi * num_heads, 0, 0, 1, 1)
        bits = pltpu.bitcast(pltpu.prng_random_bits(p.shape), jnp.uint32)
        keep = bits >= thr
        p_v = jnp.where(keep, p * inv_keep, 0.0)
        dp = jnp.where(keep, dpd * inv_keep, 0.0)
    else:
        p_v = p
        dp = dpd
    dv_ref[0] = _bdot(
        p_v.astype(do.dtype), do, ta=True, tb=False
    ).astype(dv_ref.dtype)
    ds = p * (dp - delta)
    dq_ref[0] = (_bdot(ds.astype(kt.dtype), kt, tb=False) * scale
                 ).astype(dq_ref.dtype)
    dk_ref[0] = (_bdot(ds.astype(q.dtype), q, ta=True, tb=False) * scale
                 ).astype(dk_ref.dtype)


def _small_bias_arg(bias, b, h, lq, lk, pl, pltpu):
    if bias.shape[1] == 1:
        arr = jnp.broadcast_to(bias, (b, 1, lq, lk))
        spec = pl.BlockSpec((1, 1, lq, lk), lambda bi: (bi, 0, 0, 0),
                            memory_space=pltpu.VMEM)
    else:
        arr = bias
        spec = pl.BlockSpec((1, h, lq, lk), lambda bi: (bi, 0, 0, 0),
                            memory_space=pltpu.VMEM)
    return arr, spec


def _pallas_fwd_small(q, k, v, bias, seed, causal, scale, rate):
    """Whole-sequence-per-tile forward over grid (b,)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    has_bias = bias is not None
    has_drop = rate > 0.0
    tile = lambda l: pl.BlockSpec((1, h, l, d), lambda bi: (bi, 0, 0, 0),
                                  memory_space=pltpu.VMEM)
    specs = [tile(lq), tile(lk), tile(lk)]
    args = [q, k, v]
    if has_bias:
        arr, spec = _small_bias_arg(bias, b, h, lq, lk, pl, pltpu)
        specs.append(spec)
        args.append(arr)
    if has_drop:
        specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(jnp.asarray(seed, jnp.int32).reshape(1))

    def kernel(*refs):
        n_in = 3 + (1 if has_bias else 0) + (1 if has_drop else 0)
        ins, outs = list(refs[:n_in]), refs[n_in:]
        i = 3
        bias_ref = ins[i] if has_bias else None
        i += 1 if has_bias else 0
        seed_ref = ins[i] if has_drop else None
        return _fwd_small_core(ins[0], ins[1], ins[2], bias_ref, seed_ref,
                               *outs, scale=scale, causal=causal,
                               num_heads=h, rate=rate)

    out, lse = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=specs,
        out_specs=[
            tile(lq),
            pl.BlockSpec((1, h, lq, 1), lambda bi: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, lq, 1), jnp.float32),
        ],
    )(*args)
    return out, lse


def _bwd_small_fits_vmem(h, lq, lk, d, budget=6 << 20):
    """The one-pass backward holds ALL heads of one batch item in VMEM:
    7 bf16 [h,l,d] operand/result tiles plus 3 f32 [h,lq,lk] score-sized
    intermediates. The compiler's scoped-vmem stack roughly doubles the
    estimate (in/out buffering), so gate at ~6 MB against the 16 MB core
    limit — at h=12,d=64 this admits L=128 (3.7 MB) and correctly sends
    L>=256 (12+ MB, observed 18.5 MB scoped OOM) to the tiled kernels."""
    tiles = 7 * h * max(lq, lk) * d * 2
    scores = 3 * h * lq * lk * 4
    return tiles + scores <= budget


def _pallas_bwd_small(q, k, v, bias, seed, causal, scale, rate, lse, g,
                      delta):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    has_bias = bias is not None
    has_drop = rate > 0.0
    tile = lambda l: pl.BlockSpec((1, h, l, d), lambda bi: (bi, 0, 0, 0),
                                  memory_space=pltpu.VMEM)
    col = pl.BlockSpec((1, h, lq, 1), lambda bi: (bi, 0, 0, 0),
                       memory_space=pltpu.VMEM)
    specs = [tile(lq), tile(lk), tile(lk), tile(lq), col, col]
    args = [q, k, v, g, lse, delta]
    if has_bias:
        arr, spec = _small_bias_arg(bias, b, h, lq, lk, pl, pltpu)
        specs.append(spec)
        args.append(arr)
    if has_drop:
        specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(jnp.asarray(seed, jnp.int32).reshape(1))

    def kernel(*refs):
        n_in = 6 + (1 if has_bias else 0) + (1 if has_drop else 0)
        ins, outs = list(refs[:n_in]), refs[n_in:]
        i = 6
        bias_ref = ins[i] if has_bias else None
        i += 1 if has_bias else 0
        seed_ref = ins[i] if has_drop else None
        return _bwd_small_core(*ins[:6], bias_ref, seed_ref, *outs,
                               scale=scale, causal=causal, num_heads=h,
                               rate=rate)

    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=specs,
        out_specs=[tile(lq), tile(lk), tile(lk)],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, lk, d), v.dtype),
        ],
    )(*args)
    return dq, dk, dv


def _adapt(core, has_bias, has_drop, **kw):
    """Bind a kernel core whose optional refs may be absent."""

    def kernel(*refs):
        n_in = 3 + (1 if has_bias else 0) + (1 if has_drop else 0)
        ins = list(refs[:n_in])
        outs = refs[n_in:]
        i = 3
        bias_ref = ins[i] if has_bias else None
        i += 1 if has_bias else 0
        seed_ref = ins[i] if has_drop else None
        return core(ins[0], ins[1], ins[2], bias_ref, seed_ref, *outs, **kw)

    return kernel


def _bias_spec(bias, b, h, lq, lk, block_q, pl, pltpu):
    """BlockSpec + reshaped operand for bias [B, 1|H, LQ, LK] -> per
    (bh, qi) tile [1, 1, BQ, LK]."""
    if bias.shape[1] == 1:
        arr = jnp.broadcast_to(bias, (b, 1, lq, lk))
        spec = pl.BlockSpec(
            (1, 1, block_q, lk), lambda bh, qi: (bh // h, 0, qi, 0),
            memory_space=pltpu.VMEM,
        )
    else:
        arr = bias.reshape(b * h, 1, lq, lk)
        spec = pl.BlockSpec(
            (1, 1, block_q, lk), lambda bh, qi: (bh, 0, qi, 0),
            memory_space=pltpu.VMEM,
        )
    return arr, spec


def _effective_blocks(lq, lk, block_q, block_k):
    """Tile sizes the kernels actually use. The grids FLOOR-divide seq
    by block, so a 128-multiple that is not a block multiple (L=384,
    640, ...) must shrink to the 128 base tile or its tail rows are
    silently dropped (_supported gates on L % 128 == 0)."""
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    if lq % block_q:
        block_q = 128
    if lk % block_k:
        block_k = 128
    return block_q, block_k


def _use_small_path(h, lq, lk, d, block_q, block_k):
    """One dispatch predicate for BOTH forward and backward small
    kernels. They must agree whenever dropout is on: the small kernels
    seed the PRNG per batch item while the tiled ones re-seed per head,
    so a small-forward/tiled-backward split would regenerate a DIFFERENT
    mask for every head but the first — silently wrong gradients."""
    # the backward's VMEM bound gates BOTH directions: with dropout the
    # masks must pair, and without it the small backward would still OOM
    # scoped VMEM at shapes the forward alone could handle
    return (lq <= block_q and lk <= block_k
            and _bwd_small_fits_vmem(h, lq, lk, d))


def _pallas_fwd(q, k, v, bias, seed, causal, scale, rate,
                block_q=256, block_k=256, unroll=1):
    """Returns (out, lse): lse is the per-row logsumexp [B*H, LQ], f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q, block_k = _effective_blocks(lq, lk, block_q, block_k)
    if _use_small_path(h, lq, lk, d, block_q, block_k):
        out, lse = _pallas_fwd_small(q, k, v, bias, seed, causal, scale,
                                     rate)
        return out, lse.reshape(b * h, lq, 1)
    qf = q.reshape(b * h, lq, d)
    kf = k.reshape(b * h, lk, d)
    vf = v.reshape(b * h, lk, d)
    grid = (b * h, lq // block_q)
    has_bias = bias is not None
    has_drop = rate > 0.0

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [qf, kf, vf]
    if has_bias:
        arr, spec = _bias_spec(bias, b, h, lq, lk, block_q, pl, pltpu)
        in_specs.append(spec)
        args.append(arr)
    if has_drop:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(jnp.asarray(seed, jnp.int32).reshape(1))

    kernel = _adapt(_fwd_core, has_bias, has_drop, scale=scale,
                    causal=causal, block_k=block_k, seq_k=lk,
                    num_q=lq // block_q, rate=rate, unroll=unroll)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, lq, 1), jnp.float32),
        ],
    )(*args)
    return out.reshape(b, h, lq, d), lse


# -- backward kernels ---------------------------------------------------------


def _dq_core(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
             seed_ref, dq_ref, *, scale, causal, block_k, seq_k, num_q,
             rate, unroll=1):
    """dQ program per (bh, q-tile): walk K-tiles, recompute P from the
    saved logsumexp, regenerate the identical dropout mask per tile."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q = q_ref[0]                                      # [BQ, D]
    do = do_ref[0]                                    # [BQ, D]
    lse = lse_ref[0]                                  # [BQ, 1]
    delta = delta_ref[0]                              # [BQ, 1]
    bq = q.shape[0]
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    q_start = qi * bq

    num_k = seq_k // block_k
    if causal:
        num_k_live = jnp.minimum(
            num_k, (q_start + bq + block_k - 1) // block_k
        )
    else:
        num_k_live = num_k
    if rate > 0.0:
        thr = _drop_threshold(rate)
        inv_keep = 1.0 / (1.0 - rate)

    def body(ki, dq_acc):
        k_start = ki * block_k
        kt = k_ref[0, pl.ds(k_start, block_k), :]
        vt = v_ref[0, pl.ds(k_start, block_k), :]
        s = jnp.dot(q, kt.T, preferred_element_type=jnp.float32) * scale
        if causal:
            iq = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            ik = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(iq >= ik, s, _NEG_INF)
        if bias_ref is not None:
            s = s + bias_ref[0, 0, :, pl.ds(k_start, block_k)].astype(
                jnp.float32
            )
        p = jnp.exp(s - lse)                           # normalized probs
        dpd = jnp.dot(do, vt.T, preferred_element_type=jnp.float32)
        if rate > 0.0:
            _seed_tile(pltpu, seed_ref, bh, qi, ki, num_k=num_k,
                       num_q=num_q)
            bits = pltpu.bitcast(
                pltpu.prng_random_bits(p.shape), jnp.uint32
            )
            dp = jnp.where(bits >= thr, dpd * inv_keep, 0.0)
        else:
            dp = dpd
        ds = p * (dp - delta)                          # [BQ, BK]
        return dq_acc + jnp.dot(
            ds.astype(kt.dtype), kt, preferred_element_type=jnp.float32
        )

    dq0 = jnp.zeros((bq, q.shape[1]), jnp.float32)
    dq = jax.lax.fori_loop(0, num_k_live, body, dq0, unroll=unroll)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_core(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
              seed_ref, dk_ref, dv_ref, *, scale, causal, block_q, seq_q,
              num_k, rate, unroll=1):
    """dK/dV program per (bh, k-tile): walk Q-tiles. The dropout re-seed
    uses the same (seed, bh, qi, ki) tuple as the forward, so the mask
    for each (qi, ki) tile is bit-identical despite the transposed
    iteration order."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kt = k_ref[0]                                     # [BK, D]
    vt = v_ref[0]                                     # [BK, D]
    bk = kt.shape[0]
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    k_start = ki * bk

    num_q = seq_q // block_q
    # causal: Q-tiles entirely above this K-tile see none of it
    qi_start = (k_start // block_q) if causal else 0
    if rate > 0.0:
        thr = _drop_threshold(rate)
        inv_keep = 1.0 / (1.0 - rate)

    def body(qi, carry):
        dk_acc, dv_acc = carry
        q_start = qi * block_q
        qt = q_ref[0, pl.ds(q_start, block_q), :]
        do = do_ref[0, pl.ds(q_start, block_q), :]
        lse = lse_ref[0, pl.ds(q_start, block_q), :]
        delta = delta_ref[0, pl.ds(q_start, block_q), :]
        s = jnp.dot(qt, kt.T, preferred_element_type=jnp.float32) * scale
        if causal:
            iq = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            ik = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(iq >= ik, s, _NEG_INF)
        if bias_ref is not None:
            s = s + bias_ref[0, 0, pl.ds(q_start, block_q), :].astype(
                jnp.float32
            )
        p = jnp.exp(s - lse)                           # [BQ, BK]
        dpd = jnp.dot(do, vt.T, preferred_element_type=jnp.float32)
        if rate > 0.0:
            _seed_tile(pltpu, seed_ref, bh, qi, ki, num_q, num_k)
            bits = pltpu.bitcast(
                pltpu.prng_random_bits(p.shape), jnp.uint32
            )
            keep = bits >= thr
            p_v = jnp.where(keep, p * inv_keep, 0.0)
            dp = jnp.where(keep, dpd * inv_keep, 0.0)
        else:
            p_v = p
            dp = dpd
        dv_new = dv_acc + jnp.dot(
            p_v.T.astype(do.dtype), do, preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk_new = dk_acc + jnp.dot(
            ds.T.astype(qt.dtype), qt, preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    dk0 = jnp.zeros((bk, kt.shape[1]), jnp.float32)
    dv0 = jnp.zeros((bk, vt.shape[1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(qi_start, num_q, body, (dk0, dv0),
                               unroll=unroll)
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pallas_bwd(q, k, v, bias, seed, causal, scale, rate, out, lse, g,
                block_q=256, block_k=256, unroll=1):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q, block_k = _effective_blocks(lq, lk, block_q, block_k)
    qf = q.reshape(b * h, lq, d)
    kf = k.reshape(b * h, lk, d)
    vf = v.reshape(b * h, lk, d)
    gf = g.reshape(b * h, lq, d)
    # D_i = rowsum(dO * O): cheap, fuses into the surrounding XLA program
    delta = jnp.sum(
        gf.astype(jnp.float32) * out.reshape(b * h, lq, d).astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # [B*H, LQ, 1]
    if _use_small_path(h, lq, lk, d, block_q, block_k):
        # short-sequence regime: one program per batch item (all heads)
        # beats two tiled passes (launch + DMA overhead dominates there);
        # the predicate is SHARED with the forward so dropout seeding
        # schemes always pair
        return _pallas_bwd_small(
            q, k, v, bias, seed, causal, scale, rate,
            lse.reshape(b, h, lq, 1), g, delta.reshape(b, h, lq, 1))
    has_bias = bias is not None
    has_drop = rate > 0.0

    whole = lambda l: pl.BlockSpec((1, l, d), lambda bh, i: (bh, 0, 0),
                                   memory_space=pltpu.VMEM)
    row = lambda blk: pl.BlockSpec((1, blk, 1), lambda bh, i: (bh, i, 0),
                                   memory_space=pltpu.VMEM)
    whole_row = lambda l: pl.BlockSpec((1, l, 1), lambda bh, i: (bh, 0, 0),
                                       memory_space=pltpu.VMEM)

    # -- dQ: grid over q-tiles
    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                     memory_space=pltpu.VMEM),
        whole(lk), whole(lk),
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                     memory_space=pltpu.VMEM),
        row(block_q), row(block_q),
    ]
    dq_args = [qf, kf, vf, gf, lse, delta]
    if has_bias:
        arr, spec = _bias_spec(bias, b, h, lq, lk, block_q, pl, pltpu)
        dq_specs.append(spec)
        dq_args.append(arr)
    if has_drop:
        dq_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dq_args.append(jnp.asarray(seed, jnp.int32).reshape(1))

    def dq_kernel(*refs):
        n_in = 6 + (1 if has_bias else 0) + (1 if has_drop else 0)
        ins, outs = list(refs[:n_in]), refs[n_in:]
        i = 6
        bias_ref = ins[i] if has_bias else None
        i += 1 if has_bias else 0
        seed_ref = ins[i] if has_drop else None
        return _dq_core(*ins[:6], bias_ref, seed_ref, *outs, scale=scale,
                        causal=causal, block_k=block_k, seq_k=lk,
                        num_q=lq // block_q, rate=rate, unroll=unroll)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, lq // block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
    )(*dq_args)

    # -- dK/dV: grid over k-tiles
    dkv_specs = [
        whole(lq),
        pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0),
                     memory_space=pltpu.VMEM),
        whole(lq), whole_row(lq), whole_row(lq),
    ]
    dkv_args = [qf, kf, vf, gf, lse, delta]
    if has_bias:
        # column-slice of the bias per k-tile: [1, 1, LQ, BK]
        if bias.shape[1] == 1:
            arr = jnp.broadcast_to(bias, (b, 1, lq, lk))
            spec = pl.BlockSpec(
                (1, 1, lq, block_k), lambda bh, ki: (bh // h, 0, 0, ki),
                memory_space=pltpu.VMEM,
            )
        else:
            arr = bias.reshape(b * h, 1, lq, lk)
            spec = pl.BlockSpec(
                (1, 1, lq, block_k), lambda bh, ki: (bh, 0, 0, ki),
                memory_space=pltpu.VMEM,
            )
        dkv_specs.append(spec)
        dkv_args.append(arr)
    if has_drop:
        dkv_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dkv_args.append(jnp.asarray(seed, jnp.int32).reshape(1))

    def dkv_kernel(*refs):
        n_in = 6 + (1 if has_bias else 0) + (1 if has_drop else 0)
        ins, outs = list(refs[:n_in]), refs[n_in:]
        i = 6
        bias_ref = ins[i] if has_bias else None
        i += 1 if has_bias else 0
        seed_ref = ins[i] if has_drop else None
        return _dkv_core(*ins[:6], bias_ref, seed_ref, *outs, scale=scale,
                         causal=causal, block_q=block_q, seq_q=lq,
                         num_k=lk // block_k, rate=rate, unroll=unroll)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, lk // block_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, lk, d), v.dtype),
        ],
    )(*dkv_args)
    shape4 = lambda a, l: a.reshape(b, h, l, d)
    return shape4(dq, lq), shape4(dk, lk), shape4(dv, lk)


# -- custom-vjp wiring --------------------------------------------------------


def _supported(q, k, v, bias):
    if not on_tpu_platform():
        return False
    b, h, lq, d = q.shape
    lk = k.shape[2]
    if d % 128 != 0 and d not in (64,):  # lane dim should tile well
        if d % 8 != 0:
            return False
    if lq % 128 != 0 or lk % 128 != 0:
        return False
    return True


def _sched_for(q, k):
    b, h, lq, d = q.shape
    return _schedule_blocks(b, h, lq, k.shape[2], d, q.dtype)


# ``sched`` (block_q, block_k, unroll) is a NONDIFF STATIC argument,
# resolved ONCE by flash_attention() before the custom_vjp: forward and
# backward must tile identically — the dropout PRNG re-seeds per
# (q-tile, k-tile), so a background tuned swap-in landing between the
# eager forward and its deferred backward would otherwise regenerate
# different masks (silently wrong gradients).
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, seed, causal, scale, rate, bias_grad=True,
           sched=(_BLOCK, _BLOCK, 1), bias=None):
    if _supported(q, k, v, bias):
        bq, bk, unroll = sched
        out, _ = _pallas_fwd(q, k, v, bias, seed, causal, scale, rate,
                             block_q=bq, block_k=bk, unroll=unroll)
        return out
    return _plain_attention(q, k, v, bias, causal, scale, rate, seed)


def _flash_fwd(q, k, v, seed, causal, scale, rate, bias_grad=True,
               sched=(_BLOCK, _BLOCK, 1), bias=None):
    if _supported(q, k, v, bias):
        bq, bk, unroll = sched
        out, lse = _pallas_fwd(q, k, v, bias, seed, causal, scale, rate,
                               block_q=bq, block_k=bk, unroll=unroll)
        return out, (q, k, v, bias, seed, out, lse)
    out = _plain_attention(q, k, v, bias, causal, scale, rate, seed)
    return out, (q, k, v, bias, seed, None, None)


def _flash_bwd(causal, scale, rate, bias_grad, sched, res, g):
    q, k, v, bias, seed, out, lse = res
    dseed = np.zeros((), dtype=jax.dtypes.float0)
    if out is not None:  # pallas path
        bq, bk, unroll = sched  # the forward's exact tiling, statically
        dq, dk, dv = _pallas_bwd(
            q, k, v, bias, seed, causal, scale, rate, out, lse, g,
            block_q=bq, block_k=bk, unroll=unroll
        )
        if bias is None:
            return dq, dk, dv, dseed, None
        if not bias_grad or rate > 0.0:
            # bias_grad=False: caller declared the bias non-trainable
            # (eager attention masks) — zeros beat the recompute below,
            # which eager mode would otherwise execute just to discard.
            # rate>0: see module docstring — bias is non-trainable under
            # in-kernel dropout (jnp cannot reproduce the TPU PRNG mask)
            return dq, dk, dv, dseed, jnp.zeros_like(bias)
        # exact dbias via recompute (DCE'd by XLA when bias carries no
        # gradient, which is the case for every reference attention mask)
        def fwd(bias):
            return _plain_attention(q, k, v, bias, causal, scale)

        _, vjp = jax.vjp(fwd, bias)
        (dbias,) = vjp(g)
        return dq, dk, dv, dseed, dbias

    # fallback path: recompute with the same seed -> identical mask
    if bias is None:
        _, vjp = jax.vjp(
            lambda q, k, v: _plain_attention(
                q, k, v, None, causal, scale, rate, seed),
            q, k, v,
        )
        dq, dk, dv = vjp(g)
        return dq, dk, dv, dseed, None
    if not bias_grad:
        _, vjp = jax.vjp(
            lambda q, k, v: _plain_attention(
                q, k, v, bias, causal, scale, rate, seed),
            q, k, v,
        )
        dq, dk, dv = vjp(g)
        return dq, dk, dv, dseed, jnp.zeros_like(bias)

    _, vjp = jax.vjp(
        lambda q, k, v, b: _plain_attention(
            q, k, v, b, causal, scale, rate, seed),
        q, k, v, bias,
    )
    dq, dk, dv, dbias = vjp(g)
    return dq, dk, dv, dseed, dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, bias=None, causal=False, scale=None,
                    dropout_rate=0.0, dropout_key=None):
    """Fused attention over [B, H, L, D] operands.

    On TPU with tile-aligned shapes, runs the pallas flash kernel
    (forward AND backward; attention-probability dropout runs inside the
    kernel via the TPU PRNG). Otherwise falls back to the fused-by-XLA
    jnp path. Accepts Tensors or arrays; additive bias broadcastable to
    [B, H, LQ, LK].

    ``dropout_rate`` drops attention probabilities (upscale-in-train).
    ``dropout_key`` supplies the jax PRNG key; when None, the global
    generator (framework/random.py) is split — inside a compiled train
    step this is the functionalized per-step key, so masks differ per
    step.
    """
    from ...framework.tensor import Tensor

    unwrap = lambda t: t._array if isinstance(t, Tensor) else t
    wrap = isinstance(q, Tensor)
    qa, ka, va = unwrap(q), unwrap(k), unwrap(v)
    ba = unwrap(bias) if bias is not None else None
    if scale is None:
        scale = float(qa.shape[-1]) ** -0.5
    rate = float(dropout_rate)
    if rate > 0.0:
        if dropout_key is None:
            from ...framework import random as _random

            dropout_key = _random.split_key()
        seed = jax.random.bits(dropout_key, (), "uint32").astype(jnp.int32)
    else:
        seed = jnp.int32(0)

    # bias_grad=False when the bias is declared non-trainable: the eager
    # backward then returns cheap zeros instead of executing the exact
    # dbias recompute (which materializes [B, H, LQ, LK] scores) just to
    # discard it. Trainable biases require dropout_rate == 0 on the
    # pallas path (module docstring).
    bias_grad = not (isinstance(bias, Tensor) and bias.stop_gradient)
    if (bias is not None and bias_grad and rate > 0.0
            and isinstance(bias, Tensor)):
        raise ValueError(
            "flash_attention: a trainable bias (stop_gradient=False) "
            "cannot be combined with dropout_rate > 0 — the in-kernel "
            "TPU dropout mask is not reproducible for the bias gradient. "
            "Set bias.stop_gradient = True or use dropout_rate=0.0."
        )

    # resolve the schedule ONCE, here, so the custom_vjp's forward and
    # deferred backward share the exact same static tiling (a background
    # tuned swap-in between the two can then never split them); off-TPU
    # the kernels never run — skip resolution, keep the path tuner-free
    sched = _sched_for(qa, ka) if on_tpu_platform() else (_BLOCK, _BLOCK, 1)
    if wrap:
        from ...framework.autograd import apply_op

        tensors = [q, k, v] + ([bias] if bias is not None else [])
        tensors = [
            t if isinstance(t, Tensor) else Tensor._from_array(jnp.asarray(t))
            for t in tensors
        ]
        if bias is not None:
            fn = lambda q, k, v, b: _flash(q, k, v, seed, causal, scale,
                                           rate, bias_grad, sched, b)
        else:
            fn = lambda q, k, v: _flash(q, k, v, seed, causal, scale,
                                        rate, True, sched)
        return apply_op("flash_attention", fn, tensors, {})
    if ba is not None:
        return _flash(qa, ka, va, seed, causal, scale, rate, True, sched,
                      ba)
    return _flash(qa, ka, va, seed, causal, scale, rate, True, sched)
