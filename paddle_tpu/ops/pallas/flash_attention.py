"""Flash attention (TPU pallas kernel).

Reference parity: operators/fused/multihead_matmul_op.cu fuses BERT
attention into one CUDA kernel; this is the TPU equivalent with the
flash-attention online-softmax construction so the [L, L] score matrix
never materializes in HBM — only [BQ, BK] tiles live in VMEM.

Design (per /opt/skills/guides/pallas_guide.md):
- grid = (B*H, L/BQ): one program per query tile per head.
- K/V for the head stay as VMEM blocks; the kernel walks K-tiles with a
  fori_loop, keeping running max m, denominator l, and an f32 accumulator
  in VMEM scratch (MXU matmuls via jnp.dot with
  preferred_element_type=f32).
- causal masking prunes fully-masked K-tiles by bounding the loop.
- backward: custom_vjp with a recompute-based jnp backward (XLA fuses it
  well at moderate L; a pallas backward kernel is a planned upgrade for
  long-context training).

Falls back to a pure-jnp path off-TPU (CPU tests) and for dtypes/shapes
the kernel does not support.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _plain_attention(q, k, v, bias, causal, scale):
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        iq = jnp.arange(lq)[:, None] + (lk - lq)
        ik = jnp.arange(lk)[None, :]
        scores = jnp.where(iq >= ik, scores, _NEG_INF)
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    w = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, scale, causal,
                block_k, seq_k):
    """One (batch*head, q-tile) program. Shapes (leading block dims of 1
    squeezed by indexing):
      q_ref: [1, BQ, D]; k_ref/v_ref: [1, LK, D]; bias_ref: [1, 1, BQ, LK]
      o_ref: [1, BQ, D]
    """
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale          # [BQ, D]
    bq = q.shape[0]
    qi = pl.program_id(1)
    q_start = qi * bq

    num_k = seq_k // block_k
    if causal:
        # K-tiles strictly after this Q-tile's last row are fully masked
        num_k_live = jnp.minimum(
            num_k, (q_start + bq + block_k - 1) // block_k
        )
    else:
        num_k_live = num_k

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)

    def body(ki, carry):
        m, l, acc = carry
        k_start = ki * block_k
        kt = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        vt = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kt.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            iq = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            ik = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(iq >= ik, s, _NEG_INF)
        if bias_ref is not None:
            s = s + bias_ref[0, 0, :, pl.ds(k_start, block_k)].astype(
                jnp.float32
            )
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.dot(
            p.astype(vt.dtype), vt, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k_live, body, (m0, l0, acc0))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _pallas_fwd(q, k, v, bias, causal, scale, block_q=256, block_k=256):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    qf = q.reshape(b * h, lq, d)
    kf = k.reshape(b * h, lk, d)
    vf = v.reshape(b * h, lk, d)
    grid = (b * h, lq // block_q)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, lk, d), lambda bh, qi: (bh, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [qf, kf, vf]
    if bias is not None:
        # bias [B, 1 or H, LQ, LK] -> per (bh, qi) tile [1,1,BQ,LK]
        if bias.shape[1] == 1:
            bias_bh = jnp.broadcast_to(
                bias, (b, 1, lq, lk)
            ).reshape(b, 1, lq, lk)
            # index by batch only
            spec = pl.BlockSpec(
                (1, 1, block_q, lk),
                lambda bh, qi: (bh // h, 0, qi, 0),
                memory_space=pltpu.VMEM,
            )
        else:
            bias_bh = bias.reshape(b * h, 1, lq, lk)
            spec = pl.BlockSpec(
                (1, 1, block_q, lk),
                lambda bh, qi: (bh, 0, qi, 0),
                memory_space=pltpu.VMEM,
            )
        in_specs.append(spec)
        args.append(bias_bh)
        kernel = functools.partial(
            _fwd_kernel, scale=scale, causal=causal,
            block_k=block_k, seq_k=lk,
        )
    else:
        kernel = functools.partial(
            _fwd_kernel_nobias, scale=scale, causal=causal,
            block_k=block_k, seq_k=lk,
        )

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
    )(*args)
    return out.reshape(b, h, lq, d)


def _fwd_kernel_nobias(q_ref, k_ref, v_ref, o_ref, **kw):
    _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, **kw)


def _supported(q, k, v, bias):
    if jax.devices()[0].platform not in ("tpu",):
        return False
    b, h, lq, d = q.shape
    lk = k.shape[2]
    if d % 128 != 0 and d not in (64,):  # lane dim should tile well
        if d % 8 != 0:
            return False
    if lq % 128 != 0 or lk % 128 != 0:
        return False
    return True


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale, bias=None):
    if _supported(q, k, v, bias):
        return _pallas_fwd(q, k, v, bias, causal, scale)
    return _plain_attention(q, k, v, bias, causal, scale)


def _flash_fwd(q, k, v, causal, scale, bias=None):
    out = _flash(q, k, v, causal, scale, bias)
    return out, (q, k, v, bias)


def _flash_bwd(causal, scale, res, g):
    """Recompute-based backward (jnp; XLA fuses)."""
    q, k, v, bias = res
    if bias is None:
        _, vjp = jax.vjp(
            lambda q, k, v: _plain_attention(q, k, v, None, causal, scale),
            q, k, v,
        )
        dq, dk, dv = vjp(g)
        return dq, dk, dv, None

    def fwd(q, k, v, bias):
        return _plain_attention(q, k, v, bias, causal, scale)

    _, vjp = jax.vjp(fwd, q, k, v, bias)
    dq, dk, dv, dbias = vjp(g)
    return dq, dk, dv, dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, bias=None, causal=False, scale=None):
    """Fused attention over [B, H, L, D] operands.

    On TPU with tile-aligned shapes, runs the pallas flash kernel;
    otherwise falls back to the fused-by-XLA jnp path. Accepts Tensors or
    arrays; additive bias broadcastable to [B, H, LQ, LK].
    """
    from ...framework.tensor import Tensor

    unwrap = lambda t: t._array if isinstance(t, Tensor) else t
    wrap = isinstance(q, Tensor)
    qa, ka, va = unwrap(q), unwrap(k), unwrap(v)
    ba = unwrap(bias) if bias is not None else None
    if scale is None:
        scale = float(qa.shape[-1]) ** -0.5

    if wrap:
        from ...framework.autograd import apply_op

        tensors = [q, k, v] + ([bias] if bias is not None else [])
        tensors = [
            t if isinstance(t, Tensor) else Tensor._from_array(jnp.asarray(t))
            for t in tensors
        ]
        if bias is not None:
            fn = lambda q, k, v, b: _flash(q, k, v, causal, scale, b)
        else:
            fn = lambda q, k, v: _flash(q, k, v, causal, scale)
        return apply_op("flash_attention", fn, tensors, {})
    if ba is not None:
        return _flash(qa, ka, va, causal, scale, ba)
    return _flash(qa, ka, va, causal, scale)
