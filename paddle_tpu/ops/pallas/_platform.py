"""Shared platform gate for pallas kernel dispatch.

Every pallas kernel's ``*_supported`` predicate must agree on which jax
backends count as "TPU" — the local ``tpu`` platform and the remote-TPU
plugin ``axon`` (the same convention framework/random.py uses for the
rbg PRNG choice). One predicate here keeps the gates from drifting:
pool_backward.py admitted ('tpu', 'axon') while flash attention admitted
only 'tpu' until this was factored out.
"""
from __future__ import annotations

import jax

TPU_PLATFORMS = ("tpu", "axon")


def on_tpu_platform() -> bool:
    """True when the default jax backend is a (possibly remote) TPU."""
    try:
        return jax.devices()[0].platform in TPU_PLATFORMS
    except Exception:
        return False
