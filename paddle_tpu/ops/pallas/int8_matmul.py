"""Int8 matmul (TPU pallas kernel): int8 × int8 → int32 on the MXU.

The deployable int8 inference programs `slim.ptq.save_int8_model` emits
carry REAL int8 weights and quantized activations; their matmul/mul ops
(`matmul_int8`/`mul_int8` in ops/quantize_kernels.py) contract the two
int8 operands into int32 accumulators and only then apply the combined
dequantization scale — the MXU reads a quarter of the HBM bytes an f32
matmul would and accumulates exactly (int8·int8 products fit int32 with
headroom: 2^7 · 2^7 · K ≤ 2^31 for any practical K), so the int8 path
has ZERO accumulation error relative to the jnp fallback.

Kernel design per /opt/skills/guides/pallas_guide.md: the grid walks
``[TILE_M, K] × [K, TILE_N]`` VMEM blocks (int8 min tile is (32, 128),
so M pads to 32 and K/N pad to 128 — zero padding is exact for an
integer matmul), and every contraction runs through
``jnp.dot(..., preferred_element_type=jnp.int32)``. Off-TPU (and for
shapes the kernel does not admit) the jnp fallback computes the
IDENTICAL ``lax.dot_general`` with int8 inputs and int32
preferred-element-type, so ``FLAGS_use_int8_matmul`` never changes
numerics — the same flag discipline as the PR-10 fused kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..._internal_tuning import register_schedule, resolve_schedule
from ._platform import on_tpu_platform

__all__ = ["int8_matmul"]

_LANES = 128      # last-dim tile (every dtype)
_SUBLANES = 32    # int8 second-to-last-dim minimum tile
_TILE = 256       # default M/N tile (the historical hardcoded geometry)


def _schedule_tiles(pm, pk, pn) -> tuple:
    """(tile_m, tile_n) through the autotuner; default point is the
    historical ``min(dim, 256)`` pair — byte-identical when untuned."""
    params = resolve_schedule("int8_matmul", m=int(pm), k=int(pk),
                              n=int(pn), dtype="int8")
    return (max(_SUBLANES, min(int(params["tile_m"]), pm)),
            max(_LANES, min(int(params["tile_n"]), pn)))


def _bucket(info):
    # raw-shape tune() keys and padded-dim resolve() keys must collapse
    # into one bucket: clamp dims to their tile floors first
    from ...tuning.schedule import aligned_bucket

    return aligned_bucket({"m": _SUBLANES, "k": _LANES,
                           "n": _LANES})(info)


def _int8_vmem_ok(info, c) -> bool:
    # residents per program: int8 [tile_m, K] + int8 [K, tile_n]
    # + int32 [tile_m, tile_n]; keep the sum under ~12 MB of the 16 MB
    # core budget (the compiler's in/out buffering needs headroom)
    k = int(info["k"])
    b = (c["tile_m"] * k + k * c["tile_n"]
         + 4 * c["tile_m"] * c["tile_n"])
    return (c["tile_m"] % _SUBLANES == 0 and c["tile_n"] % _LANES == 0
            and b <= 12 * (1 << 20))


def _tuning_bench(info):
    import numpy as np

    m, k, n = int(info["m"]), int(info["k"]), int(info["n"])
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(-128, 128, (m, k)), jnp.int8)
    w = jnp.asarray(rng.randint(-128, 128, (k, n)), jnp.int8)
    interpret = not on_tpu_platform()

    def builder(params):
        tiles = (max(_SUBLANES, min(int(params["tile_m"]), m)),
                 max(_LANES, min(int(params["tile_n"]), n)))
        fn = jax.jit(lambda x, w: _pallas_matmul(
            x, w, interpret=interpret, tiles=tiles))

        def run():
            jax.block_until_ready(fn(x, w))

        return run

    return builder


register_schedule(
    name="int8_matmul",
    version=1,
    params={"tile_m": (32, 64, 128, 256, 512),
            "tile_n": (128, 256, 512)},
    # tile floors keep the default point valid for RAW shapes too (the
    # dispatch path always passes padded dims, where max() is a no-op)
    default=lambda info: {"tile_m": max(_SUBLANES,
                                        min(int(info["m"]), _TILE)),
                          "tile_n": max(_LANES,
                                        min(int(info["n"]), _TILE))},
    supported=_int8_vmem_ok,
    bench=_tuning_bench,
    bucket=_bucket,
)


def _jnp_matmul(x, w):
    """Fallback path: one dot_general, int8 inputs, int32 accumulation —
    the exact contraction the kernel tiles (identical expression)."""
    return jax.lax.dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


def _supported(x, w) -> bool:
    # the kernel handles the 2D core; callers flatten batch dims first
    # (ops/quantize_kernels.py does). Tiny operands are not worth the
    # pallas dispatch.
    return (x.ndim == 2 and w.ndim == 2 and x.shape[1] == w.shape[0]
            and str(x.dtype) == "int8" and str(w.dtype) == "int8"
            and x.shape[0] * w.shape[1] >= _SUBLANES * _LANES)


def _pad_to(a, rows, cols):
    r, c = a.shape
    if r == rows and c == cols:
        return a
    return jnp.pad(a, ((0, rows - r), (0, cols - c)))


def _pallas_matmul(x, w, interpret=False, tiles=None):
    """Tiled int8 matmul: grid over [M/TM, N/TN], K resident per block."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x.shape
    _, n = w.shape
    # zero padding is exact: padded rows/cols contribute 0 to int32 sums
    pm = ((m + _SUBLANES - 1) // _SUBLANES) * _SUBLANES
    pk = ((k + _LANES - 1) // _LANES) * _LANES
    pn = ((n + _LANES - 1) // _LANES) * _LANES
    xp = _pad_to(x, pm, pk)
    wp = _pad_to(w, pk, pn)
    # block geometry: full-K stripes; M/N tiles sized so the three VMEM
    # residents (int8 x-block + int8 w-block + int32 out-block) stay far
    # under the ~16 MB budget even at large K. Tuned per device_kind
    # through the schedule cache; default = the historical 256/256.
    tile_m, tile_n = tiles if tiles is not None else _schedule_tiles(
        pm, pk, pn)

    def kernel(x_ref, w_ref, o_ref):
        o_ref[:] = jnp.dot(x_ref[:], w_ref[:],
                           preferred_element_type=jnp.int32)

    out = pl.pallas_call(
        kernel,
        grid=(pl.cdiv(pm, tile_m), pl.cdiv(pn, tile_n)),
        in_specs=[
            pl.BlockSpec((tile_m, pk), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((pk, tile_n), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_m, tile_n), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((pm, pn), jnp.int32),
        interpret=interpret,
    )(xp, wp)
    return out[:m, :n]


def int8_matmul(x, w):
    """``x [M, K] int8 @ w [K, N] int8 -> [M, N] int32``.

    Dispatches to the pallas kernel on TPU when
    ``FLAGS_use_int8_matmul`` admits it; elsewhere the jnp fallback runs
    the identical int32-accumulating contraction (integer math — the
    two paths are bit-equal, asserted by tests and the quant smoke).
    """
    from ...flags import flag

    x = jnp.asarray(x)
    w = jnp.asarray(w)
    if flag("use_int8_matmul") and on_tpu_platform() and _supported(x, w):
        return _pallas_matmul(x, w)
    return _jnp_matmul(x, w)
