"""Skip-gram word2vec.

Reference parity: tests/book/test_word2vec.py and the dist_word2vec.py
dist-test fixture (CBOW with shared embedding + softmax head).
"""
from __future__ import annotations

from .. import ops
from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layers import Embedding, Linear


class Word2Vec(Layer):
    """CBOW: predict middle word from N context words."""

    def __init__(self, vocab_size, embed_dim=32, context=4):
        super().__init__()
        self.embedding = Embedding(vocab_size, embed_dim)
        self.fc = Linear(embed_dim, vocab_size)
        self.context = context

    def forward(self, context_ids):
        # context_ids: [B, context]
        emb = self.embedding(context_ids)  # [B, C, E]
        hidden = ops.mean(emb, axis=1)
        return self.fc(hidden)
