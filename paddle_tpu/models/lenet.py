"""LeNet-5 for MNIST.

Reference parity: the `dist_mnist.py` / `test_recognize_digits.py` fixture
model (python/paddle/fluid/tests/unittests/dist_mnist.py cnn_model;
incubate/hapi/vision/models/lenet.py).
"""
from __future__ import annotations

from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layers import Conv2D, Flatten, Linear, MaxPool2D, Sequential


class LeNet(Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1),
            _Act("relu"),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0),
            _Act("relu"),
            MaxPool2D(2, 2),
        )
        self.fc = Sequential(
            Flatten(),
            Linear(400, 120),
            _Act("relu"),
            Linear(120, 84),
            _Act("relu"),
            Linear(84, num_classes),
        )

    def forward(self, x):
        return self.fc(self.features(x))


class _Act(Layer):
    def __init__(self, name):
        super().__init__()
        self._fn = getattr(F, name)

    def forward(self, x):
        return self._fn(x)
