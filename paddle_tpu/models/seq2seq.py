"""Transformer sequence-to-sequence (machine translation).

Reference parity: the dist_transformer.py test fixture and
tests/book/test_machine_translation.py — an encoder-decoder translation
model with greedy and beam-search decoding (beam via the
beam_search/beam_search_decode op pair, ops/beam_search.py).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .. import ops
from ..framework.tensor import Tensor
from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layers import Dropout, Embedding, Linear
from ..nn.transformer import Transformer

__all__ = ["TransformerSeq2Seq"]


def _positional_encoding(max_len, d_model):
    pos = np.arange(max_len)[:, None]
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    enc = np.where(i % 2 == 0, np.sin(angle), np.cos(angle))
    return enc.astype(np.float32)


class TransformerSeq2Seq(Layer):
    """Encoder-decoder MT model over the nn.Transformer stack.

    pad_id tokens are masked out of attention; the decoder uses the
    standard causal mask. ``beam_search`` follows the reference's
    beam_search + beam_search_decode op contract.
    """

    def __init__(self, src_vocab, tgt_vocab, d_model=128, nhead=4,
                 num_layers=2, dim_feedforward=256, dropout=0.1,
                 max_len=256, bos_id=0, eos_id=1, pad_id=2):
        super().__init__()
        self.bos_id, self.eos_id, self.pad_id = bos_id, eos_id, pad_id
        self.d_model = d_model
        # the x*sqrt(d) transformer convention assumes N(0, 1/sqrt(d))
        # embedding init (net unit variance); paddle's Embedding default
        # N(0,1) would saturate attention after the scale
        from ..nn import initializer as I

        emb_init = I.Normal(0.0, d_model ** -0.5)
        self.src_emb = Embedding(src_vocab, d_model, weight_attr=emb_init)
        self.tgt_emb = Embedding(tgt_vocab, d_model, weight_attr=emb_init)
        self.register_buffer(
            "pos_enc", Tensor(_positional_encoding(max_len, d_model))
        )
        self.dropout = Dropout(dropout)
        self.core = Transformer(
            d_model=d_model, nhead=nhead, num_encoder_layers=num_layers,
            num_decoder_layers=num_layers, dim_feedforward=dim_feedforward,
            dropout=dropout,
        )
        self.out_proj = Linear(d_model, tgt_vocab)

    # -- pieces --------------------------------------------------------------
    def _embed(self, emb, ids):
        seq_len = ids.shape[1]
        x = emb(ids) * float(np.sqrt(self.d_model))
        pos = ops.slice(self.pos_enc, [0], [0], [seq_len])
        return self.dropout(ops.add(x, ops.unsqueeze(pos, 0)))

    def _pad_mask(self, ids):
        # [B, L] -> additive [B, 1, 1, L]
        m = ops.cast(
            ops.not_equal(ids, ops.full_like(ids, self.pad_id)), "float32"
        )
        return ops.scale(ops.subtract(ops.full([], 1.0),
                                      ops.unsqueeze(m, [1, 2])), -1e9)

    def encode(self, src_ids):
        return self.core.encoder(
            self._embed(self.src_emb, src_ids), self._pad_mask(src_ids)
        )

    def decode_logits(self, memory, memory_mask, tgt_ids):
        t = tgt_ids.shape[1]
        causal = Transformer.generate_square_subsequent_mask(t)
        out = self.core.decoder(
            self._embed(self.tgt_emb, tgt_ids), memory,
            tgt_mask=causal, memory_mask=memory_mask,
        )
        return self.out_proj(out)

    def forward(self, src_ids, tgt_ids):
        """Teacher-forced training logits [B, T, V]."""
        memory = self.encode(src_ids)
        return self.decode_logits(memory, self._pad_mask(src_ids), tgt_ids)

    # -- decoding -------------------------------------------------------------
    def greedy_decode(self, src_ids, max_len=20, stop_at_eos=False):
        """Greedy decoding (book test_machine_translation's decode loop),
        delegated to the shared :func:`generation.sampling.decode_loop`
        — one decode-loop implementation in the codebase.
        ``stop_at_eos`` ends early once every row has emitted EOS
        (off by default: the book loop always runs ``max_len - 1``
        steps)."""
        from ..generation.sampling import decode_loop

        b = src_ids.shape[0]
        memory = self.encode(src_ids)
        src_mask = self._pad_mask(src_ids)
        ys = ops.full([b, 1], self.bos_id, "int64")
        return decode_loop(
            lambda ys_: self.decode_logits(memory, src_mask, ys_)[:, -1],
            ys, max_len, eos_id=self.eos_id if stop_at_eos else None)

    def beam_search(self, src_ids, beam_size=4, max_len=20):
        """Beam-search decoding over the beam_search op pair.

        Returns (sequences [T, B, beam], scores [B, beam]) — best
        hypothesis at argmax score, backtracked by beam_search_decode.
        """
        from ..ops.registry import kernel

        b = src_ids.shape[0]
        memory = self.encode(src_ids)
        src_mask = self._pad_mask(src_ids)
        mem = memory._array if isinstance(memory, Tensor) else memory
        # expand memory over beams: [B*K, L, D]
        k = int(beam_size)
        mem_k = jnp.repeat(mem, k, axis=0)
        mask_k = jnp.repeat(
            src_mask._array if isinstance(src_mask, Tensor) else src_mask,
            k, axis=0,
        )
        scores = jnp.zeros((b, k), jnp.float32)
        ys = jnp.full((b * k, 1), self.bos_id, jnp.int32)
        parents_hist, tokens_hist = [], []
        for t in range(max_len - 1):
            logits = self.decode_logits(
                Tensor._from_array(mem_k), Tensor._from_array(mask_k),
                Tensor._from_array(ys),
            )
            arr = logits._array if isinstance(logits, Tensor) else logits
            logp = jnp.log(jnp.maximum(
                F.softmax(Tensor._from_array(arr[:, -1]))._array, 1e-9
            )).reshape(b, k, -1)
            scores, parent, token = kernel("beam_search_step")(
                logp, scores, beam_size=k, first_step=(t == 0)
            )
            parents_hist.append(parent)
            tokens_hist.append(token)
            # reorder beams and append tokens
            flat_parent = (
                parent + jnp.arange(b)[:, None] * k
            ).reshape(-1)
            ys = ys[flat_parent]
            ys = jnp.concatenate(
                [ys, token.reshape(-1, 1).astype(jnp.int32)], axis=1
            )
        seqs, final = kernel("beam_search_decode")(
            jnp.stack(parents_hist), jnp.stack(tokens_hist), scores
        )
        return seqs, final
