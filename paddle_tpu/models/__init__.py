"""Model zoo.

Reference parity: the models used by the reference's tests and hapi
(python/paddle/incubate/hapi/vision/models/, tests/book/, the dist-test
fixtures dist_mnist.py / dist_se_resnext.py / dist_transformer.py).
Flagship = BERT (the BASELINE.md headline metric is BERT-base
tokens/sec/chip).
"""
from .lenet import LeNet  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig,
    BertModel,
    BertForPretraining,
    BertPretrainingCriterion,
    bert_base_config,
    bert_tiny_config,
    bert_sharding_rules,
    bert_pipeline_stages,
    ernie_base_config,
    ErnieModel,
    ErnieForPretraining,
    knowledge_masking,
    BertEmbeddingStage,
    BertEncoderStage,
    BertHeadStage,
)
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152  # noqa: F401
from .word2vec import Word2Vec  # noqa: F401
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenet import (  # noqa: F401
    MobileNetV1,
    MobileNetV2,
    mobilenet_v1,
    mobilenet_v2,
)
from .seq2seq import TransformerSeq2Seq  # noqa: F401
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTForCausalLM,
    GPTModel,
    gpt_tiny_config,
    load_gpt_model,
    save_gpt_model,
    truncated_draft,
)
from .se_resnext import (  # noqa: F401
    SEResNeXt,
    se_resnext50_32x4d,
    se_resnext101_32x4d,
)
