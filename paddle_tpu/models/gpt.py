"""GPT-style decoder-only causal language model.

The autoregressive counterpart of ``models/bert.py``: the same
``nn/transformer.py`` building blocks, assembled pre-norm and
decoder-only (``TransformerDecoderLayer(with_cross_attention=False)``),
with the LM head weight-tied to the token embedding.

Designed for the generation stack (``paddle_tpu/generation/``): the
forward takes an optional list of per-layer :class:`nn.StaticCache`
entries and then runs the INCREMENTAL attention path — functional
ring-buffer K/V writes, shapes static across steps — so one jitted
decode step serves the whole life of every sequence.

``attention_window`` gives the model sliding-window attention (each
token sees at most the last W tokens). Serving sets it to the KV-cache
capacity, which is exactly what a ring cache of that capacity computes —
the full forward and the cached decode agree numerically even after the
ring wraps (golden-tested).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import ops
from ..framework.tensor import Tensor
from ..nn.layer_base import Layer
from ..nn.layers import Dropout, Embedding, LayerList, LayerNorm
from ..nn.transformer import TransformerDecoderLayer, causal_mask
from .bert import _init_bert_weights

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt_tiny_config",
           "save_gpt_model", "load_gpt_model", "truncated_draft"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 1024
    initializer_range: float = 0.02
    bos_token_id: int = 0
    eos_token_id: int = 1
    pad_token_id: int = 2
    # sliding-window attention width (None = full causal). The serving
    # engine sets this to the KV-cache capacity so the compiled full
    # forward and the O(1) ring-cache decode compute the same function.
    attention_window: int | None = None


def gpt_tiny_config() -> GPTConfig:
    """For tests / smokes: 2 layers, 64 hidden."""
    return GPTConfig(
        vocab_size=211, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=128, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )


class GPTModel(Layer):
    """Embeddings + pre-norm decoder-only stack + final LayerNorm."""

    def __init__(self, cfg: GPTConfig | None = None, **kwargs):
        super().__init__()
        self.config = cfg or GPTConfig(**kwargs)
        cfg = self.config
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = Embedding(
            cfg.max_position_embeddings, cfg.hidden_size
        )
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.layers = LayerList([
            TransformerDecoderLayer(
                cfg.hidden_size, cfg.num_attention_heads,
                cfg.intermediate_size, dropout=cfg.hidden_dropout_prob,
                activation=cfg.hidden_act,
                attn_dropout=cfg.attention_probs_dropout_prob,
                act_dropout=0.0, normalize_before=True,
                with_cross_attention=False,
            )
            for _ in range(cfg.num_hidden_layers)
        ])
        self.norm_f = LayerNorm(cfg.hidden_size)
        _init_bert_weights(self, cfg.initializer_range)

    @staticmethod
    def _wrap(x, dtype=None):
        if isinstance(x, Tensor):
            return x
        arr = jnp.asarray(x)
        if dtype is not None:
            arr = arr.astype(dtype)
        return Tensor._from_array(arr)

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                caches=None):
        """Hidden states ``[B, T, H]``; with ``caches`` (a list of
        per-layer ``StaticCache``) also the updated caches."""
        input_ids = self._wrap(input_ids)
        t = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.expand(
                ops.unsqueeze(ops.arange(t, dtype="int64"), 0),
                [input_ids.shape[0], t],
            )
        else:
            position_ids = self._wrap(position_ids)
        if attention_mask is None:
            attention_mask = causal_mask(
                t, window=self.config.attention_window)
        else:
            attention_mask = self._wrap(attention_mask)
        x = self.dropout(
            self.word_embeddings(input_ids)
            + self.position_embeddings(position_ids)
        )
        new_caches = []
        for i, layer in enumerate(self.layers):
            if caches is None:
                x = layer(x, tgt_mask=attention_mask)
            else:
                x, c = layer(x, tgt_mask=attention_mask, cache=caches[i])
                new_caches.append(c)
        x = self.norm_f(x)
        return x if caches is None else (x, new_caches)


class GPTForCausalLM(Layer):
    """GPTModel + weight-tied LM head: logits over the vocabulary."""

    def __init__(self, cfg: GPTConfig | None = None, **kwargs):
        super().__init__()
        self.gpt = GPTModel(cfg, **kwargs)
        self.config = self.gpt.config

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                caches=None):
        out = self.gpt(input_ids, position_ids, attention_mask, caches)
        hidden = out[0] if caches is not None else out
        logits = ops.matmul(hidden, self.gpt.word_embeddings.weight,
                            transpose_y=True)
        return logits if caches is None else (logits, out[1])

    # -- generation-engine contract ------------------------------------------

    def cache_spec(self):
        """(num_layers, num_heads, head_dim) for KV-cache allocation."""
        cfg = self.config
        return (cfg.num_hidden_layers, cfg.num_attention_heads,
                cfg.hidden_size // cfg.num_attention_heads)


# ---------------------------------------------------------------------------
# persistence + draft construction (serving fleets)
# ---------------------------------------------------------------------------


def save_gpt_model(model: "GPTForCausalLM", dirname):
    """Persist a causal LM as ``config.json`` + ``model.pdparams`` —
    the unit a generation backend process boots from
    (``python -m paddle_tpu.serving.backend --kind generate --gpt-dir
    DIR``), and the shape a draft-model directory takes
    (``--draft-dir``)."""
    import dataclasses
    import json
    import os

    from ..framework.serialization import save

    os.makedirs(dirname, exist_ok=True)
    cfg = dataclasses.asdict(model.config)
    with open(os.path.join(dirname, "config.json"), "w") as f:
        json.dump(cfg, f, indent=1, sort_keys=True)
    save(model.state_dict(), os.path.join(dirname, "model.pdparams"))
    return dirname


def load_gpt_model(dirname) -> "GPTForCausalLM":
    """Rebuild a :func:`save_gpt_model` directory into a ready
    :class:`GPTForCausalLM` (eval mode)."""
    import json
    import os

    from ..framework.serialization import load

    with open(os.path.join(dirname, "config.json")) as f:
        cfg = GPTConfig(**json.load(f))
    model = GPTForCausalLM(cfg)
    model.set_state_dict(load(os.path.join(dirname, "model.pdparams")))
    model.eval()
    return model


def truncated_draft(model: "GPTForCausalLM",
                    num_layers: int = 1) -> "GPTForCausalLM":
    """A layer-skip draft for speculative decoding: the target's
    embeddings, FIRST ``num_layers`` decoder layers, final norm, and
    (tied) LM head, copied into a shallower GPT.

    Because the residual stream is dominated by the embedding path, the
    truncated stack's argmax agrees with the full model's far more
    often than chance — a distillation-free draft in the
    self-speculative-decoding spirit, and the default draft the bench
    and smoke use. For production the draft is any separately trained
    small GPT sharing the vocab (``--draft-dir``).
    """
    import dataclasses

    cfg = dataclasses.replace(model.config,
                              num_hidden_layers=int(num_layers))
    draft = GPTForCausalLM(cfg)
    src = model.state_dict()
    own = draft.state_dict()
    draft.set_state_dict({
        k: src[k] for k, v in own.items()
        if k in src and tuple(src[k].shape) == tuple(v.shape)})
    draft.eval()
    return draft
