"""ResNet family.

Reference parity: incubate/hapi/vision/models/resnet.py (+ the
dist_se_resnext.py test fixture); BASELINE.md's ResNet-50 images/sec/chip
metric runs on this model.

TPU note: ``data_format`` selects the activation layout end-to-end.
"NCHW" is the paddle-default API surface; "NHWC" keeps activations in the
channels-last layout the TPU vector units natively tile (lane dim = C),
which removes the relayout copies XLA otherwise inserts around every conv
— the same reason the reference's cudnn path prefers NHWC tensor cores
(/root/reference/paddle/fluid/operators/conv_cudnn_op.cu.cc exhaustive-
search layouts). Weights stay OIHW in both modes.
"""
from __future__ import annotations

from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layers import (
    AdaptiveAvgPool2D,
    BatchNorm2D,
    Conv2D,
    Linear,
    MaxPool2D,
    Sequential,
    fused_conv_bn_relu,
)


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, data_format="NCHW"):
        super().__init__()
        df = dict(data_format=data_format)
        self.conv1 = Conv2D(inplanes, planes, 3, stride=stride, padding=1, bias_attr=False, **df)
        self.bn1 = BatchNorm2D(planes, **df)
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False, **df)
        self.bn2 = BatchNorm2D(planes, **df)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        # conv->bn->relu triples route through the fused pallas kernel
        # (FLAGS_use_fused_conv_bn); bn2 feeds the residual add, not a
        # relu, so it stays on the unfused path
        out = fused_conv_bn_relu(self.conv1, self.bn1, x)
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return F.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, data_format="NCHW"):
        super().__init__()
        df = dict(data_format=data_format)
        self.conv1 = Conv2D(inplanes, planes, 1, bias_attr=False, **df)
        self.bn1 = BatchNorm2D(planes, **df)
        self.conv2 = Conv2D(planes, planes, 3, stride=stride, padding=1, bias_attr=False, **df)
        self.bn2 = BatchNorm2D(planes, **df)
        self.conv3 = Conv2D(planes, planes * self.expansion, 1, bias_attr=False, **df)
        self.bn3 = BatchNorm2D(planes * self.expansion, **df)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        # 2 of the 3 convs per bottleneck carry a bn+relu epilogue —
        # both fuse; bn3 feeds the residual add and stays unfused
        out = fused_conv_bn_relu(self.conv1, self.bn1, x)
        out = fused_conv_bn_relu(self.conv2, self.bn2, out)
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return F.relu(out + identity)


class ResNet(Layer):
    def __init__(self, block, depth_cfg, num_classes=1000, with_pool=True,
                 data_format="NCHW"):
        super().__init__()
        self.inplanes = 64
        self.data_format = data_format
        df = dict(data_format=data_format)
        self.conv1 = Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False, **df)
        self.bn1 = BatchNorm2D(64, **df)
        self.maxpool = MaxPool2D(kernel_size=3, stride=2, padding=1, **df)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1), **df)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        df = dict(data_format=self.data_format)
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1,
                       stride=stride, bias_attr=False, **df),
                BatchNorm2D(planes * block.expansion, **df),
            )
        layers = [block(self.inplanes, planes, stride, downsample, **df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, **df))
        return Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(fused_conv_bn_relu(self.conv1, self.bn1, x))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from .. import ops

            x = ops.flatten(x, 1)
            x = self.fc(x)
        return x


def resnet18(**kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], **kw)


def resnet34(**kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], **kw)


def resnet50(**kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], **kw)


def resnet101(**kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], **kw)


def resnet152(**kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], **kw)
