"""MobileNet V1/V2.

Reference parity: python/paddle/incubate/hapi/vision/models/
mobilenetv1.py / mobilenetv2.py — the depthwise-separable model zoo
entries (also the reference's light inference demo models).
"""
from __future__ import annotations

from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layers import (
    AdaptiveAvgPool2D,
    BatchNorm2D,
    Conv2D,
    Linear,
    Sequential,
)

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


class _ConvBNReLU(Layer):
    def __init__(self, in_c, out_c, k=3, stride=1, groups=1, relu6=False):
        super().__init__()
        self.conv = Conv2D(in_c, out_c, k, stride=stride,
                           padding=(k - 1) // 2, groups=groups,
                           bias_attr=False)
        self.bn = BatchNorm2D(out_c)
        self._relu6 = relu6

    def forward(self, x):
        x = self.bn(self.conv(x))
        return F.relu6(x) if self._relu6 else F.relu(x)


class _DepthwiseSeparable(Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.dw = _ConvBNReLU(in_c, in_c, 3, stride, groups=in_c)
        self.pw = _ConvBNReLU(in_c, out_c, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(Layer):
    """hapi/vision/models/mobilenetv1.py."""

    def __init__(self, num_classes=1000, scale=1.0):
        super().__init__()
        s = lambda c: max(int(c * scale), 8)
        cfg = [
            (s(32), s(64), 1), (s(64), s(128), 2), (s(128), s(128), 1),
            (s(128), s(256), 2), (s(256), s(256), 1), (s(256), s(512), 2),
            *[(s(512), s(512), 1)] * 5,
            (s(512), s(1024), 2), (s(1024), s(1024), 1),
        ]
        self.stem = _ConvBNReLU(3, s(32), 3, stride=2)
        self.blocks = Sequential(
            *[_DepthwiseSeparable(i, o, st) for i, o, st in cfg]
        )
        self.pool = AdaptiveAvgPool2D((1, 1))
        self.fc = Linear(s(1024), num_classes)

    def forward(self, x):
        from .. import ops

        x = self.pool(self.blocks(self.stem(x)))
        return self.fc(ops.flatten(x, start_axis=1))


class _InvertedResidual(Layer):
    def __init__(self, in_c, out_c, stride, expand):
        super().__init__()
        hidden = in_c * expand
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand != 1:
            layers.append(_ConvBNReLU(in_c, hidden, 1, relu6=True))
        layers.append(
            _ConvBNReLU(hidden, hidden, 3, stride, groups=hidden, relu6=True)
        )
        self.body = Sequential(*layers)
        self.project = Conv2D(hidden, out_c, 1, bias_attr=False)
        self.project_bn = BatchNorm2D(out_c)

    def forward(self, x):
        y = self.project_bn(self.project(self.body(x)))
        return x + y if self.use_res else y


class MobileNetV2(Layer):
    """hapi/vision/models/mobilenetv2.py."""

    def __init__(self, num_classes=1000, scale=1.0):
        super().__init__()
        s = lambda c: max(int(c * scale), 8)
        # (expand, out, repeats, stride)
        cfg = [
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        self.stem = _ConvBNReLU(3, s(32), 3, stride=2, relu6=True)
        blocks = []
        in_c = s(32)
        for t, c, n, st in cfg:
            for i in range(n):
                blocks.append(
                    _InvertedResidual(in_c, s(c), st if i == 0 else 1, t)
                )
                in_c = s(c)
        self.blocks = Sequential(*blocks)
        self.head = _ConvBNReLU(in_c, s(1280), 1, relu6=True)
        self.pool = AdaptiveAvgPool2D((1, 1))
        self.fc = Linear(s(1280), num_classes)

    def forward(self, x):
        from .. import ops

        x = self.pool(self.head(self.blocks(self.stem(x))))
        return self.fc(ops.flatten(x, start_axis=1))


def mobilenet_v1(**kw):
    return MobileNetV1(**kw)


def mobilenet_v2(**kw):
    return MobileNetV2(**kw)
