"""VGG family.

Reference parity: python/paddle/incubate/hapi/vision/models/vgg.py —
the stacked-conv classifier used in the reference's vision model zoo
and book tests (tests/book/test_image_classification.py uses a
VGG-style net).
"""
from __future__ import annotations

from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layers import (
    AdaptiveAvgPool2D,
    BatchNorm2D,
    Conv2D,
    Dropout,
    Linear,
    MaxPool2D,
    Sequential,
)

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19"]

_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


def _make_features(cfg, batch_norm):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(MaxPool2D(2, 2))
            continue
        layers.append(Conv2D(in_c, v, 3, padding=1))
        if batch_norm:
            layers.append(BatchNorm2D(v))
        layers.append(_ReLU())
        in_c = v
    return Sequential(*layers)


class _ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class VGG(Layer):
    """hapi/vision/models/vgg.py VGG."""

    def __init__(self, cfg="D", num_classes=1000, batch_norm=False,
                 dropout=0.5):
        super().__init__()
        self.features = _make_features(_CFGS[cfg], batch_norm)
        self.avgpool = AdaptiveAvgPool2D((7, 7))
        self.classifier = Sequential(
            Linear(512 * 7 * 7, 4096), _ReLU(), Dropout(dropout),
            Linear(4096, 4096), _ReLU(), Dropout(dropout),
            Linear(4096, num_classes),
        )

    def forward(self, x):
        x = self.avgpool(self.features(x))
        from .. import ops

        x = ops.flatten(x, start_axis=1)
        return self.classifier(x)


def vgg11(**kw):
    return VGG("A", **kw)


def vgg13(**kw):
    return VGG("B", **kw)


def vgg16(**kw):
    return VGG("D", **kw)


def vgg19(**kw):
    return VGG("E", **kw)
