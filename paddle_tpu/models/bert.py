"""BERT / ERNIE-style transformer encoder for pretraining.

Reference parity: the reference ships the transformer layer stack
(python/paddle/nn/layer/transformer.py:67,385) and BERT-shaped fused
attention (operators/fused/multihead_matmul_op.cu); the full model matches
the ERNIE/BERT configs the reference's ecosystem trains. BASELINE.md's
headline metric (BERT-base tokens/sec/chip) is measured on this model.

TPU-native design decisions:
- bf16-first: matmul-heavy blocks run in bfloat16 under AMP; master
  weights stay fp32.
- sharding-aware: activations carry GSPMD constraints (dp on batch, sp on
  sequence); ``bert_sharding_rules()`` gives megatron TP partitioning of
  qkv/out/ffn weights + vocab-parallel embedding. With both, XLA emits the
  same collective schedule megatron implements by hand.
- attention dispatches to the pallas flash kernel on TPU for long
  sequences (ops/pallas), falling back to the jnp path elsewhere.
"""
from __future__ import annotations

from dataclasses import dataclass

from jax.sharding import PartitionSpec as P

from .. import ops
from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layers import Dropout, Embedding, LayerList, LayerNorm, Linear
from ..nn.transformer import TransformerEncoder, TransformerEncoderLayer
from ..parallel.sharding import ShardingRules, with_sharding_constraint


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    pad_token_id: int = 0
    # dispatch attention to the pallas flash kernel (ops/pallas); dropout
    # runs inside the kernel via the TPU PRNG
    use_flash_attention: bool = False
    # sequence-parallel attention over the sp mesh axis: "none" | "ring"
    # (parallel/ring_attention.py) | "ulysses" (parallel/ulysses.py).
    # Requires attention_probs_dropout_prob == 0.
    sp_attention: str = "none"


def bert_base_config() -> BertConfig:
    return BertConfig()


def bert_tiny_config() -> BertConfig:
    """For tests / dryruns: 2 layers, 128 hidden."""
    return BertConfig(
        vocab_size=1024, hidden_size=128, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=512,
        max_position_embeddings=128, type_vocab_size=2,
    )


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = Embedding(
            cfg.max_position_embeddings, cfg.hidden_size
        )
        self.token_type_embeddings = Embedding(
            cfg.type_vocab_size, cfg.hidden_size
        )
        self.layer_norm = LayerNorm(cfg.hidden_size)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        if position_ids is None:
            seq_len = input_ids.shape[1]
            position_ids = ops.expand(
                ops.unsqueeze(ops.arange(seq_len, dtype="int64"), 0),
                [input_ids.shape[0], seq_len],
            )
        if token_type_ids is None:
            token_type_ids = ops.zeros_like(input_ids)
        emb = (
            self.word_embeddings(input_ids)
            + self.position_embeddings(position_ids)
            + self.token_type_embeddings(token_type_ids)
        )
        emb = self.layer_norm(emb)
        emb = self.dropout(emb)
        # batch on dp, sequence on sp, hidden replicated
        return with_sharding_constraint(emb, P("dp", "sp", None))


class BertPooler(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, hidden_states):
        return F.tanh(self.dense(hidden_states[:, 0]))


def _init_bert_weights(model, initializer_range):
    """Truncated-normal(σ=initializer_range) init of all linear/embedding
    weights, zeros for biases — the standard BERT scheme."""
    import numpy as np

    from ..framework.random import default_generator
    import jax

    for name, p in model.named_parameters():
        if "norm" in name:  # layer_norm/norm1/norm2 scales stay 1, biases 0
            continue
        if p._array.ndim >= 2 and ("weight" in name.split(".")[-1]):
            key = default_generator().split()
            arr = (
                jax.random.truncated_normal(
                    key, -2.0, 2.0, p._array.shape, "float32"
                )
                * initializer_range
            )
            p._array = arr.astype(p._array.dtype)
        elif name.endswith("bias"):
            p._array = p._array * 0


class _BertStage(Layer):
    """One pipeline stage: k consecutive encoder layers, (x, mask) -> x."""

    def __init__(self, layers):
        super().__init__()
        self.layers = LayerList(layers)

    def forward(self, x, mask):
        for layer in self.layers:
            x = layer(x, mask)
        return x


class BertModel(Layer):
    def __init__(self, cfg: BertConfig | None = None, pipeline_stages=1,
                 num_microbatches=1, **kwargs):
        super().__init__()
        self.config = cfg or BertConfig(**kwargs)
        cfg = self.config
        self.embeddings = BertEmbeddings(cfg)

        def make_layer():
            return TransformerEncoderLayer(
                cfg.hidden_size,
                cfg.num_attention_heads,
                cfg.intermediate_size,
                dropout=cfg.hidden_dropout_prob,
                activation=cfg.hidden_act,
                attn_dropout=cfg.attention_probs_dropout_prob,
                act_dropout=0.0,
                use_flash_attention=cfg.use_flash_attention,
                sp_attention=cfg.sp_attention,
            )

        self._pipelined = pipeline_stages > 1
        if self._pipelined:
            # pp mode: encoder layers grouped into GPipe stages
            # (PipelineOptimizer equivalent, fluid/optimizer.py:4431)
            from ..parallel.pipeline import GPipe

            assert cfg.num_hidden_layers % pipeline_stages == 0
            per = cfg.num_hidden_layers // pipeline_stages
            stages = [
                _BertStage([make_layer() for _ in range(per)])
                for _ in range(pipeline_stages)
            ]
            self.encoder = GPipe(stages, num_microbatches=num_microbatches)
        else:
            self.encoder = TransformerEncoder(
                make_layer(), cfg.num_hidden_layers
            )
        self.pooler = BertPooler(cfg)
        _init_bert_weights(self, cfg.initializer_range)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is None:
            attention_mask = ops.cast(
                ops.not_equal(input_ids, ops.full_like(input_ids, self.config.pad_token_id)),
                "float32",
            )
        # [B, L] -> additive [B, 1, 1, L]
        ext = ops.unsqueeze(attention_mask, [1, 2])
        ext = (1.0 - ext) * -1e4
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        seq = self.encoder(emb, ext)
        seq = with_sharding_constraint(seq, P("dp", "sp", None))
        pooled = self.pooler(seq)
        return seq, pooled


class BertLMPredictionHead(Layer):
    """MLM head with tied input embedding weights (standard BERT)."""

    def __init__(self, cfg: BertConfig, embedding_weights=None):
        super().__init__()
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.activation = getattr(F, cfg.hidden_act)
        self.layer_norm = LayerNorm(cfg.hidden_size)
        self.decoder_weight = embedding_weights  # tied [V, H] parameter
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True
        )

    def forward(self, hidden_states, masked_positions=None):
        if masked_positions is not None:
            # gather the masked token positions: [B, L, H] -> [N, H]
            b, l, h = hidden_states.shape
            flat = ops.reshape(hidden_states, [b * l, h])
            hidden_states = ops.gather(flat, masked_positions)
        x = self.layer_norm(self.activation(self.transform(hidden_states)))
        logits = ops.matmul(x, self.decoder_weight, transpose_y=True)
        return logits + self.decoder_bias


class BertForPretraining(Layer):
    """MLM + next-sentence-prediction pretraining model."""

    def __init__(self, cfg: BertConfig | None = None, pipeline_stages=1,
                 num_microbatches=1, **kwargs):
        super().__init__()
        self.bert = BertModel(
            cfg, pipeline_stages=pipeline_stages,
            num_microbatches=num_microbatches, **kwargs
        )
        cfg = self.bert.config
        self.cls = BertLMPredictionHead(
            cfg, self.bert.embeddings.word_embeddings.weight
        )
        self.seq_relationship = Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, masked_positions=None):
        seq, pooled = self.bert(
            input_ids, token_type_ids, position_ids, attention_mask
        )
        prediction_scores = self.cls(seq, masked_positions)
        seq_relationship_score = self.seq_relationship(pooled)
        return prediction_scores, seq_relationship_score


class BertPretrainingCriterion(Layer):
    """MLM + NSP loss (softmax_with_cross_entropy on both heads)."""

    def __init__(self, vocab_size):
        super().__init__()
        self.vocab_size = vocab_size

    def forward(self, prediction_scores, seq_relationship_score,
                masked_lm_labels, next_sentence_labels, masked_lm_scale=1.0):
        mlm = F.cross_entropy(
            ops.reshape(prediction_scores, [-1, self.vocab_size]),
            ops.reshape(masked_lm_labels, [-1]),
        )
        nsp = F.cross_entropy(
            seq_relationship_score, ops.reshape(next_sentence_labels, [-1])
        )
        return mlm.mean() / masked_lm_scale + nsp.mean()


class BertEmbeddingStage(Layer):
    """Heterogeneous-pipeline first stage: embeddings + leading encoder
    layers; (input_ids, token_type_ids) -> (hidden, additive_mask)."""

    def __init__(self, cfg: BertConfig, layers):
        super().__init__()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.layers = LayerList(layers)
        _init_bert_weights(self, cfg.initializer_range)

    def forward(self, input_ids, token_type_ids):
        mask = ops.cast(
            ops.not_equal(
                input_ids, ops.full_like(input_ids, self.config.pad_token_id)
            ),
            "float32",
        )
        ext = (1.0 - ops.unsqueeze(mask, [1, 2])) * -1e4
        x = self.embeddings(input_ids, token_type_ids)
        for layer in self.layers:
            x = layer(x, ext)
        return x, ext


class BertEncoderStage(Layer):
    """Middle stage: k encoder layers, (hidden, mask) -> (hidden, mask)."""

    def __init__(self, cfg: BertConfig, layers):
        super().__init__()
        self.layers = LayerList(layers)
        _init_bert_weights(self, cfg.initializer_range)

    def forward(self, x, mask):
        for layer in self.layers:
            x = layer(x, mask)
        return x, mask


class BertHeadStage(Layer):
    """Last stage: trailing encoder layers + pooler + MLM/NSP heads.

    The MLM decoder weight is intentionally *untied* from the embedding
    (which lives on the first stage's devices): a cross-stage weight tie
    would need an extra all-gather per microbatch; untying matches what
    the reference's pipeline can express (params live in exactly one
    section's scope, pipeline_trainer.cc:122 CopyParameters).
    """

    def __init__(self, cfg: BertConfig, layers):
        super().__init__()
        self.layers = LayerList(layers)
        self.pooler = BertPooler(cfg)
        self.cls = BertLMPredictionHead(
            cfg,
            self.create_parameter([cfg.vocab_size, cfg.hidden_size]),
        )
        self.seq_relationship = Linear(cfg.hidden_size, 2)
        _init_bert_weights(self, cfg.initializer_range)

    def forward(self, x, mask):
        for layer in self.layers:
            x = layer(x, mask)
        pooled = self.pooler(x)
        prediction_scores = self.cls(x)
        seq_relationship_score = self.seq_relationship(pooled)
        return prediction_scores, seq_relationship_score


def bert_pipeline_stages(cfg: BertConfig, n_stages: int):
    """Split a BERT pretraining model into n heterogeneous pipeline stages
    (embedding-first, head-last) for parallel.PipelineParallel.

    Encoder layers are distributed as evenly as possible; the first stage
    additionally carries the embeddings, the last the pooler + MLM/NSP
    heads (PipelineOptimizer's per-device program split,
    fluid/optimizer.py:4431, with sections of *different* structure).
    """
    assert n_stages >= 2, "need at least an embedding and a head stage"

    def make_layer():
        return TransformerEncoderLayer(
            cfg.hidden_size,
            cfg.num_attention_heads,
            cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob,
            activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob,
            act_dropout=0.0,
            use_flash_attention=cfg.use_flash_attention,
            sp_attention=cfg.sp_attention,
        )

    n_layers = cfg.num_hidden_layers
    counts = [
        n_layers // n_stages + (1 if i < n_layers % n_stages else 0)
        for i in range(n_stages)
    ]
    stages = []
    for i, k in enumerate(counts):
        layers = [make_layer() for _ in range(k)]
        if i == 0:
            stages.append(BertEmbeddingStage(cfg, layers))
        elif i == n_stages - 1:
            stages.append(BertHeadStage(cfg, layers))
        else:
            stages.append(BertEncoderStage(cfg, layers))
    return stages


def bert_sharding_rules() -> ShardingRules:
    """Megatron-style TP partition of BERT weights over the tp axis.

    Column-parallel: q/k/v projections and FFN up-projection (output dim
    split). Row-parallel: attention output and FFN down-projection (input
    dim split). Vocab-parallel embedding + tied MLM decoder. Linear weights
    are stored [in, out].

    Includes the pipelined variants: GPipe stacks stage params on a
    leading axis (name mangled with ``__``), sharded pp × tp.
    """
    return ShardingRules([
        # pipelined (stacked) encoder weights: [stage, ...] — pp × tp
        (r"stacked__.*self_attn__(q|k|v)_proj__weight$", P("pp", None, "tp")),
        (r"stacked__.*self_attn__(q|k|v)_proj__bias$", P("pp", "tp")),
        (r"stacked__.*self_attn__out_proj__weight$", P("pp", "tp", None)),
        (r"stacked__.*linear1__weight$", P("pp", None, "tp")),
        (r"stacked__.*linear1__bias$", P("pp", "tp")),
        (r"stacked__.*linear2__weight$", P("pp", "tp", None)),
        (r"stacked__", P("pp")),
        # unpipelined encoder weights
        (r"\.self_attn\.(q|k|v)_proj\.weight$", P(None, "tp")),
        (r"\.self_attn\.(q|k|v)_proj\.bias$", P("tp")),
        (r"\.self_attn\.out_proj\.weight$", P("tp", None)),
        (r"\.linear1\.weight$", P(None, "tp")),
        (r"\.linear1\.bias$", P("tp")),
        (r"\.linear2\.weight$", P("tp", None)),
        (r"word_embeddings\.weight$", P("tp", None)),
    ])


# -- ERNIE (BASELINE.md row 4) ------------------------------------------------


def ernie_base_config() -> BertConfig:
    """ERNIE 1.0 base hyperparameters. Architecturally ERNIE 1.0 IS the
    BERT encoder (12L/768H/12 heads, relu activation in the original
    release) — what differs is the pretraining DATA strategy
    (entity/phrase-level knowledge masking), which lives in the input
    pipeline, not the model graph."""
    return BertConfig(hidden_act="relu", vocab_size=18000)


class ErnieModel(BertModel):
    """ERNIE 1.0 encoder = BertModel with the ERNIE config defaults."""

    def __init__(self, cfg: BertConfig | None = None, **kwargs):
        super().__init__(cfg or ernie_base_config(), **kwargs)


class ErnieForPretraining(BertForPretraining):
    """MLM(+NSP) pretraining head over ErnieModel; pair with
    knowledge_masking() for the ERNIE masking recipe."""

    def __init__(self, cfg: BertConfig | None = None, **kwargs):
        super().__init__(cfg or ernie_base_config(), **kwargs)


def knowledge_masking(ids, spans, mask_id, key, mask_prob=0.15):
    """ERNIE's entity/phrase-level masking: whole spans are masked
    together (vs BERT's independent subword masking).

    ids [B, L] int; spans [B, L] int span-ids (tokens sharing a span id
    belong to one entity/phrase; 0 = single-token span). Returns
    (masked_ids, mask_positions_bool [B, L]).
    """
    import jax
    import jax.numpy as jnp

    b, l = ids.shape
    # decide per SPAN, then broadcast the decision to every member token
    span_key = jnp.where(spans > 0, spans, l + jnp.arange(l)[None, :])
    draw = jax.random.uniform(key, (b, l))
    # a span is masked iff its FIRST token drew < mask_prob
    first = jnp.concatenate(
        [jnp.ones((b, 1), bool), span_key[:, 1:] != span_key[:, :-1]],
        axis=1,
    )
    span_draw = jnp.where(first, draw, 1.0)
    # propagate the span head's decision rightward across the span
    def scan_fn(carry, xs):
        is_first, d = xs
        m = jnp.where(is_first, d < mask_prob, carry)
        return m, m

    _, masked_t = jax.lax.scan(
        scan_fn, jnp.zeros(b, bool),
        (first.T, draw.T),
    )
    mask = masked_t.T
    return jnp.where(mask, mask_id, ids), mask
