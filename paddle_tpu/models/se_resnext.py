"""SE-ResNeXt.

Reference parity: the dist_se_resnext.py fixture
(python/paddle/fluid/tests/unittests/dist_se_resnext.py) — the
squeeze-and-excitation ResNeXt the reference uses to exercise its
distributed training paths.
"""
from __future__ import annotations

from .. import ops
from ..nn import functional as F
from ..nn.layer_base import Layer
from ..nn.layers import (
    AdaptiveAvgPool2D,
    BatchNorm2D,
    Conv2D,
    Linear,
    MaxPool2D,
    Sequential,
)

__all__ = ["SEResNeXt", "se_resnext50_32x4d", "se_resnext101_32x4d"]


class _ConvBN(Layer):
    def __init__(self, in_c, out_c, k, stride=1, groups=1, act=True):
        super().__init__()
        self.conv = Conv2D(in_c, out_c, k, stride=stride,
                           padding=(k - 1) // 2, groups=groups,
                           bias_attr=False)
        self.bn = BatchNorm2D(out_c)
        self._act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return F.relu(x) if self._act else x


class _SEBlock(Layer):
    """Squeeze-and-excitation gate (dist_se_resnext.py squeeze_excitation)."""

    def __init__(self, channels, reduction=16):
        super().__init__()
        self.pool = AdaptiveAvgPool2D((1, 1))
        self.fc1 = Linear(channels, channels // reduction)
        self.fc2 = Linear(channels // reduction, channels)

    def forward(self, x):
        s = ops.flatten(self.pool(x), start_axis=1)
        s = F.sigmoid(self.fc2(F.relu(self.fc1(s))))
        return ops.multiply(x, ops.reshape(s, [x.shape[0], x.shape[1], 1, 1]))


class _SEResNeXtBottleneck(Layer):
    expansion = 2

    def __init__(self, in_c, planes, stride=1, cardinality=32,
                 downsample=None, reduction=16):
        super().__init__()
        out_c = planes * self.expansion
        self.conv1 = _ConvBN(in_c, planes, 1)
        self.conv2 = _ConvBN(planes, planes, 3, stride=stride,
                             groups=cardinality)
        self.conv3 = _ConvBN(planes, out_c, 1, act=False)
        self.se = _SEBlock(out_c, reduction)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.se(self.conv3(self.conv2(self.conv1(x))))
        return F.relu(ops.add(out, identity))


class SEResNeXt(Layer):
    def __init__(self, layers=(3, 4, 6, 3), cardinality=32, base_width=4,
                 num_classes=1000):
        super().__init__()
        self.cardinality = cardinality
        width = cardinality * base_width  # 128 for 32x4d
        self.stem = _ConvBN(3, 64, 7, stride=2)
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        self.in_c = 64
        stages = []
        planes = width
        for i, n in enumerate(layers):
            stride = 1 if i == 0 else 2
            stages.append(self._make_stage(planes, n, stride))
            planes *= 2
        self.stages = Sequential(*stages)
        self.pool = AdaptiveAvgPool2D((1, 1))
        self.fc = Linear(self.in_c, num_classes)

    def _make_stage(self, planes, blocks, stride):
        out_c = planes * _SEResNeXtBottleneck.expansion
        downsample = None
        if stride != 1 or self.in_c != out_c:
            downsample = _ConvBN(self.in_c, out_c, 1, stride=stride,
                                 act=False)
        layers = [_SEResNeXtBottleneck(
            self.in_c, planes, stride, self.cardinality, downsample
        )]
        self.in_c = out_c
        for _ in range(blocks - 1):
            layers.append(_SEResNeXtBottleneck(
                self.in_c, planes, 1, self.cardinality
            ))
        return Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.stem(x))
        x = self.pool(self.stages(x))
        return self.fc(ops.flatten(x, start_axis=1))


def se_resnext50_32x4d(**kw):
    return SEResNeXt((3, 4, 6, 3), **kw)


def se_resnext101_32x4d(**kw):
    return SEResNeXt((3, 4, 23, 3), **kw)
