"""Framework-aware AST lint: the recurring review findings as rules.

Each rule encodes a bug class that review passes kept re-finding by hand
(ISSUE 13 motivation — PR 11's trace-time flag read, PR 12's unlocked
counter increments and weak-type signature re-keying):

- ``stale-flag-read`` (GL001): a ``flag("...")``/``FLAGS_*``/environ read
  lexically inside a function that is traced by ``jax.jit`` (directly,
  via decorator/partial, or by being built inside ``_build_pure`` /
  ``_trace_*`` builders that hand the closure to the CompiledStore). The
  read happens ONCE at trace time and bakes the branch into the compiled
  program — ``set_flags`` afterwards silently changes nothing.
- ``unlocked-shared-mutation`` (GL002): augmented assignment on a
  ``self.*`` counter in a class that also spawns threads or serves HTTP,
  outside any ``with <lock>`` block. Interleaved read-modify-write drops
  increments — and autoscalers size fleets on these counters.
- ``host-sync-in-hot-path`` (GL003): ``.item()`` / ``float()`` /
  ``bool()`` / ``int()`` / ``np.asarray()`` on a traced value inside a
  decode/dispatch loop — each one is a device->host sync that serializes
  the dispatch pipeline.
- ``weak-type-capture`` (GL004): a bare Python int/float literal turned
  into a device value inside a traced function without a pinned dtype
  (``jnp.asarray(0)``): the weak-typed scalar promotes (int32->int64
  under x64) and re-keys every compiled-signature cache it touches.
- ``cache-pull-in-hot-loop`` (GL005): host materialization of a device
  CACHE array (``np.asarray(self._kv)``-style whole-cache pulls,
  ``.numpy()``/``.tolist()``/``.copy()`` on kv/cache/slab-named values)
  inside a decode/dispatch loop — each iteration allocates and copies
  the entire cache to host, turning an O(1)-per-token step into
  O(cache) per token (ISSUE 14; the memory planner budgets the cache
  as RESIDENT device state, not a per-token host round trip).

This module is pure ``ast`` — no jax import — so ``tools/graphlint.py``
runs in CI without touching an accelerator runtime.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import List, Optional

__all__ = ["LintFinding", "lint_rules", "lint_source", "lint_file",
           "lint_paths", "RULES"]


@dataclass
class LintFinding:
    rule: str       # slug, e.g. "stale-flag-read"
    rule_id: str    # short id, e.g. "GL001"
    path: str
    line: int
    col: int
    func: str       # enclosing function qualname ("<module>" at top level)
    message: str
    hint: str

    def __str__(self):
        return (f"{self.path}:{self.line}:{self.col}: {self.rule_id} "
                f"[{self.rule}] in {self.func}: {self.message}\n"
                f"    fix: {self.hint}")


# rule slug -> (id, one-line description, fix hint)
RULES = {
    "stale-flag-read": (
        "GL001",
        "FLAGS read at trace time inside a jitted function",
        "read the flag once at construction/build time and close over the "
        "value; a trace-time read bakes the current value into the "
        "compiled program and goes stale after set_flags",
    ),
    "unlocked-shared-mutation": (
        "GL002",
        "unsynchronized augmented assignment on shared instance state in "
        "a threaded/serving class",
        "guard the read-modify-write with the object's lock (with "
        "self._lock:); concurrent += interleaves and drops updates",
    ),
    "host-sync-in-hot-path": (
        "GL003",
        "device->host sync (.item()/float()/np.asarray) inside a "
        "decode/dispatch loop",
        "keep the value on device (jnp ops / lax.cond) or sync once per "
        "batch outside the loop; each sync stalls the dispatch pipeline",
    ),
    "weak-type-capture": (
        "GL004",
        "python numeric literal becomes a weak-typed device scalar "
        "inside a traced function",
        "pin the dtype (jnp.asarray(0, jnp.int32)); weak scalars promote "
        "under x64 and re-key compiled-signature caches",
    ),
    "cache-pull-in-hot-loop": (
        "GL005",
        "whole-cache host materialization (np.asarray/.numpy()/.tolist()/"
        ".copy() of a kv/cache/slab value) inside a decode/dispatch loop",
        "keep the cache on device (functional index updates) and pull "
        "only the per-step slice once per loop exit; a per-token "
        "whole-cache pull allocates and copies O(cache) bytes per token",
    ),
}


def lint_rules():
    """{slug: (id, description, hint)} for docs/CLI."""
    return dict(RULES)


# -- AST plumbing ------------------------------------------------------------

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
# callables whose function-valued arguments are traced by XLA
_JIT_CALLS = {"jit", "pjit", "pmap"}
# a nested function built inside one of these is handed to jax.jit by its
# builder (TrainStepFn._build_pure, executor _trace_block, ...)
_TRACED_BUILDER_PREFIXES = ("_build_pure", "_trace_")
_LOCKISH = ("lock", "mutex", "cond", "cv", "sem")
_THREADY_MARKERS = {
    "Thread", "ThreadPoolExecutor", "ThreadingHTTPServer", "HTTPServer",
    "BaseHTTPRequestHandler", "serve_forever", "start_new_thread", "Timer",
    "threading", "socketserver",
}
_HOT_NAME_MARKERS = ("decode", "dispatch")
# dotted-name tokens that mark a value as a device CACHE (GL005): the
# arrays whose per-token host materialization is O(cache) per token
_CACHE_NAME_MARKERS = ("cache", "kv", "slab", "planes")
# host-materializing zero-arg methods (GL005): each allocates a fresh
# host copy of the receiver
_MATERIALIZE_METHODS = ("numpy", "tolist", "copy")


def _dotted(node) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_callable(node) -> bool:
    d = _dotted(node)
    if d is None:
        return False
    leaf = d.rsplit(".", 1)[-1]
    return leaf in _JIT_CALLS


def _numeric_literal(node) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return True
    return (isinstance(node, ast.UnaryOp)
            and isinstance(node.op, (ast.USub, ast.UAdd))
            and _numeric_literal(node.operand))


class _Index:
    """Parent links + per-function qualnames + the traced-function set."""

    def __init__(self, tree):
        self.parent = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        self.funcs = [n for n in ast.walk(tree) if isinstance(n, _FUNC_NODES)]
        self.qualname = {f: self._qual(f) for f in self.funcs}
        self.traced = self._traced_set(tree)

    def _ancestors(self, node):
        while node in self.parent:
            node = self.parent[node]
            yield node

    def _qual(self, fn):
        parts = [fn.name]
        for anc in self._ancestors(fn):
            if isinstance(anc, _FUNC_NODES + (ast.ClassDef,)):
                parts.append(anc.name)
        return ".".join(reversed(parts))

    def enclosing_function(self, node):
        for anc in self._ancestors(node):
            if isinstance(anc, _FUNC_NODES):
                return anc
        return None

    def enclosing_class(self, node):
        for anc in self._ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
        return None

    def _traced_set(self, tree):
        roots = set()
        by_name = {}
        for f in self.funcs:
            by_name.setdefault(f.name, []).append(f)
            # (a) decorated with jit (plain or partial(jax.jit, ...))
            for dec in f.decorator_list:
                target = dec
                if isinstance(dec, ast.Call):
                    d = _dotted(dec.func)
                    if d and d.rsplit(".", 1)[-1] == "partial" and dec.args:
                        target = dec.args[0]
                    else:
                        target = dec.func
                if _is_jit_callable(target):
                    roots.add(f)
            # (c) built inside a jit-handing builder (_build_pure etc.)
            enc = self.enclosing_function(f)
            if enc is not None and enc.name.startswith(
                    _TRACED_BUILDER_PREFIXES):
                roots.add(f)
        # (b) passed by name into a jit call: jax.jit(step), pmap(fn, ...)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_jit_callable(node.func):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        roots.update(by_name.get(arg.id, ()))
                    elif isinstance(arg, _FUNC_NODES):
                        roots.add(arg)
        # transitive: anything lexically inside a traced fn traces with it
        traced = set(roots)
        for f in self.funcs:
            if any(a in roots for a in self._ancestors(f)
                   if isinstance(a, _FUNC_NODES)):
                traced.add(f)
        return traced

    def own_nodes(self, fn):
        """fn's body nodes, excluding nested function bodies (each nested
        def reports through its own walk)."""
        out = []
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            out.append(node)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_NODES):
                    continue
                stack.append(child)
        return out

    def under_lock(self, node):
        for anc in self._ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        expr = expr.func
                    d = (_dotted(expr) or "").lower()
                    if any(tok in d for tok in _LOCKISH):
                        return True
        return False

    def in_loop_within(self, node, fn):
        for anc in self._ancestors(node):
            if anc is fn:
                return False
            if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                return True
        return False


# -- the rules ---------------------------------------------------------------

def _emit(findings, rule, path, node, func, message):
    rid, _desc, hint = RULES[rule]
    findings.append(LintFinding(
        rule, rid, path, getattr(node, "lineno", 0),
        getattr(node, "col_offset", 0), func, message, hint))


def _rule_stale_flag_read(idx, path, findings):
    for fn in idx.traced:
        qual = idx.qualname[fn]
        for node in idx.own_nodes(fn):
            if isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                leaf = d.rsplit(".", 1)[-1]
                if leaf in ("flag", "_flag", "get_flags", "getenv"):
                    _emit(findings, "stale-flag-read", path, node, qual,
                          f"{d}(...) runs at trace time inside the jitted "
                          f"function {fn.name!r}; the value is frozen into "
                          "the compiled program")
                elif d.startswith("os.environ"):
                    _emit(findings, "stale-flag-read", path, node, qual,
                          "os.environ read at trace time inside a jitted "
                          "function")
            elif isinstance(node, (ast.Name, ast.Attribute)):
                ident = node.id if isinstance(node, ast.Name) else node.attr
                if ident.startswith("FLAGS_"):
                    _emit(findings, "stale-flag-read", path, node, qual,
                          f"{ident} read at trace time inside the jitted "
                          f"function {fn.name!r}")


def _rule_unlocked_shared_mutation(idx, path, tree, findings):
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        concurrent = False
        for node in ast.walk(cls):
            ident = None
            if isinstance(node, ast.Name):
                ident = node.id
            elif isinstance(node, ast.Attribute):
                ident = node.attr
            if ident in _THREADY_MARKERS:
                concurrent = True
                break
        if not concurrent:
            continue
        for node in ast.walk(cls):
            if not isinstance(node, ast.AugAssign):
                continue
            tgt = node.target
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            fn = idx.enclosing_function(node)
            if fn is None or fn.name in ("__init__", "__new__"):
                continue
            if idx.enclosing_class(fn) is not cls:
                continue  # belongs to a nested class; judged there
            if idx.under_lock(node):
                continue
            _emit(findings, "unlocked-shared-mutation", path, node,
                  idx.qualname[fn],
                  f"self.{tgt.attr} {_augop(node)}= ... mutates shared "
                  f"state of threaded/serving class {cls.name!r} outside "
                  "any lock")


def _augop(node):
    return {
        ast.Add: "+", ast.Sub: "-", ast.Mult: "*", ast.Div: "/",
        ast.FloorDiv: "//", ast.Mod: "%", ast.BitOr: "|", ast.BitAnd: "&",
        ast.BitXor: "^",
    }.get(type(node.op), "?")


def _rule_host_sync_in_hot_path(idx, path, findings):
    for fn in idx.funcs:
        name = fn.name.lower()
        hot = (any(m in name for m in _HOT_NAME_MARKERS)
               or name.endswith("_loop"))
        if not hot:
            continue
        qual = idx.qualname[fn]
        for node in idx.own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            if not idx.in_loop_within(node, fn):
                continue
            sync = None
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                sync = ".item()"
            else:
                d = _dotted(node.func) or ""
                leaf = d.rsplit(".", 1)[-1]
                if d in ("np.asarray", "numpy.asarray", "np.array",
                         "numpy.array"):
                    sync = f"{d}(...)"
                elif (leaf in ("float", "bool", "int") and "." not in d
                        and len(node.args) == 1
                        and isinstance(node.args[0],
                                       (ast.Name, ast.Attribute))):
                    sync = f"{leaf}(...)"
            if sync:
                _emit(findings, "host-sync-in-hot-path", path, node, qual,
                      f"{sync} forces a device->host sync inside the "
                      f"{fn.name!r} loop")


def _rule_weak_type_capture(idx, path, findings):
    for fn in idx.traced:
        qual = idx.qualname[fn]
        for node in idx.own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func) or ""
            leaf = d.rsplit(".", 1)[-1]
            has_dtype = any(kw.arg == "dtype" for kw in node.keywords)
            bad = None
            if leaf in ("asarray", "array") and d.split(".", 1)[0] in (
                    "jnp", "jax"):
                if (len(node.args) == 1 and not has_dtype
                        and _numeric_literal(node.args[0])):
                    bad = node.args[0]
            elif leaf == "full" and d.split(".", 1)[0] in ("jnp", "jax"):
                if (len(node.args) >= 2 and len(node.args) < 3
                        and not has_dtype
                        and _numeric_literal(node.args[1])):
                    bad = node.args[1]
            if bad is not None:
                _emit(findings, "weak-type-capture", path, node, qual,
                      f"{d}(<python literal>) without dtype= inside the "
                      f"traced function {fn.name!r} creates a weak-typed "
                      "scalar")


def _cache_named(node) -> Optional[str]:
    """Dotted name of ``node`` when it names a cache-like value
    (contains a kv/cache/slab token segment-wise), else None. Sees
    through subscripts: ``self._kv[0]`` pulls the same cache."""
    while isinstance(node, ast.Subscript):
        node = node.value
    d = _dotted(node)
    if d is None:
        return None
    lowered = d.lower()
    segments = lowered.replace("self.", "").split(".")
    for seg in segments:
        for tok in _CACHE_NAME_MARKERS:
            if tok in seg:
                return d
    return None


def _rule_cache_pull_in_hot_loop(idx, path, findings):
    for fn in idx.funcs:
        name = fn.name.lower()
        hot = (any(m in name for m in _HOT_NAME_MARKERS)
               or name.endswith("_loop"))
        if not hot:
            continue
        qual = idx.qualname[fn]
        for node in idx.own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            if not idx.in_loop_within(node, fn):
                continue
            pull, target = None, None
            d = _dotted(node.func) or ""
            leaf = d.rsplit(".", 1)[-1]
            # np/numpy only: jnp.asarray of a device array is a free
            # device-side no-op, not a host pull
            if (d.split(".", 1)[0] in ("np", "numpy")
                    and leaf in ("asarray", "array") and node.args):
                target = _cache_named(node.args[0])
                if target:
                    pull = f"{d}({target})"
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MATERIALIZE_METHODS
                    and not node.args):
                target = _cache_named(node.func.value)
                if target:
                    pull = f"{target}.{node.func.attr}()"
            if pull:
                _emit(findings, "cache-pull-in-hot-loop", path, node, qual,
                      f"{pull} materializes the whole cache on host every "
                      f"iteration of the {fn.name!r} loop — O(cache) "
                      "bytes allocated per token")


# -- drivers -----------------------------------------------------------------

def lint_source(source: str, path: str = "<string>") -> List[LintFinding]:
    """Lint one source string. Returns findings (empty when clean)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        f = LintFinding("parse-error", "GL000", path, e.lineno or 0,
                        e.offset or 0, "<module>", f"syntax error: {e.msg}",
                        "fix the syntax error")
        return [f]
    idx = _Index(tree)
    findings: List[LintFinding] = []
    _rule_stale_flag_read(idx, path, findings)
    _rule_unlocked_shared_mutation(idx, path, tree, findings)
    _rule_host_sync_in_hot_path(idx, path, findings)
    _rule_weak_type_capture(idx, path, findings)
    _rule_cache_pull_in_hot_loop(idx, path, findings)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def lint_file(path: str) -> List[LintFinding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path)


def lint_paths(paths) -> List[LintFinding]:
    """Lint every .py file under the given files/directories."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    findings = []
    for fp in files:
        findings.extend(lint_file(fp))
    return findings
