"""Static liveness + peak-HBM planning over the Program IR (Memplan).

On TPU the binding resource is HBM, yet the first sign a program does
not fit used to be an opaque XLA OOM *after* a full compile. This module
makes the footprint a static property of the IR, computed BEFORE any
lowering (the Julia-to-TPU full-compilation and TVM static-cost-model
spirit, PAPERS.md):

- **Liveness intervals.** One forward walk over ``Program``/``Block``/
  ``OpDesc`` (the PR-13 def-before-use machinery, recursing through
  while/cond/scan sub-blocks with max-over-branches semantics) assigns
  every value a ``[def, last_use]`` interval. Shapes come from VarDesc
  declarations refined by ``jax.eval_shape`` of the registry kernels
  over the *resolved* operand specs, so ``-1`` batch dims concretize
  from the run's feed shapes.
- **Peak accounting.** Baseline bytes (feeds + referenced persistables
  + captured constants — the arrays the executor threads into every
  dispatch) plus the live intermediate set per op index yields the
  predicted peak resident bytes, the high-water op, a per-op resident
  curve, and the top-K largest live tensors at the peak. The
  ``__inplace__`` aliasing convention is honored: an in-place optimizer
  update aliases its output onto the input buffer and is never counted
  twice.
- **Donation safety.** The same intervals upgrade PR-13's *syntactic*
  write-conflict pass to a *liveness-aware* verdict: an input declared
  ``__inplace__`` whose buffer is consumed into a differently-named
  output must be DEAD afterwards — any later read (or fetch) of it is a
  use-after-donation and is rejected (:class:`DonationError`). The
  advisor side flags inputs that die at an op with an alias-compatible
  output but no declaration: donation-eligible, undeclared.

``Executor.run`` drives :func:`check_memory_budget` behind
``FLAGS_memory_budget_check`` (off | warn | strict): the predicted peak
is compared against the device HBM capacity from the cost-model peaks
table (``monitor.cost_model.device_peaks()["hbm_bytes"]``, overridable
via ``FLAGS_device_peaks``) before any lower/compile, failing loudly
with the high-water op and top tensors named instead of OOMing
mid-compile. Verdicts cache per program version (same LRU discipline as
the PR-13 verifier cache) so steady-state dispatch pays a dict lookup —
certified by the ``executor_dispatch.memplan`` bench sub-row.

After each real compile the planner is *closed against reality*:
:func:`note_actual` compares the prediction with XLA's own
``memory_analysis`` (argument + output + temp − alias) into a
``plan_accuracy`` ratio on the CostRecord, the ``memplan/plan_accuracy``
gauge, ``/statz``, and ``tools/memplan_smoke.py``'s CI envelope — the
planner is certified, not vibes.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..errors import EnforceNotMet
from .verifier import all_in_names, all_out_names, op_in_names

__all__ = [
    "MemoryFinding", "MemoryPlan", "MemoryBudgetError", "DonationError",
    "plan_memory", "check_memory_budget", "hbm_budget_bytes",
    "note_actual", "accuracy_records", "reset_accuracy_records",
]

_BLOCK_OPS = ("while", "cond", "scan")

#: documented plan-vs-XLA accuracy envelope: predicted/actual must land
#: inside [1/ENVELOPE, ENVELOPE] on the CI smoke programs (README
#: "Memory planning"). 1.25 == the ±25% acceptance target.
ACCURACY_ENVELOPE = 1.25

_DYN = 83  # op_append.py's dynamic-dim placeholder


# ---------------------------------------------------------------------------
# findings / plan / errors
# ---------------------------------------------------------------------------


@dataclass
class MemoryFinding:
    """One planner diagnosis, anchored to (block, op index, var).

    ``severity``: ``"error"`` (donation-unsafe: rejected under the
    budget gate), ``"warning"`` (inconclusive shape: the var was
    excluded from byte counts), or ``"advice"`` (donation-eligible but
    undeclared — the advisor side, never fatal).
    """

    severity: str
    kind: str
    message: str
    block_idx: int = 0
    op_index: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None

    def __str__(self):
        loc = f"block {self.block_idx}"
        if self.op_index is not None:
            loc += f" op #{self.op_index}"
        if self.op_type:
            loc += f" <{self.op_type}>"
        var = f" var {self.var!r}" if self.var else ""
        return f"[{self.kind}] {loc}{var}: {self.message}"


class MemoryBudgetError(EnforceNotMet):
    """Predicted peak HBM exceeds the device budget — raised BEFORE any
    lowering under ``FLAGS_memory_budget_check=strict``, naming the
    high-water op and the top live tensors."""

    code = "MEMORY_BUDGET"

    def __init__(self, message, plan=None, budget_bytes=None):
        self.plan = plan
        self.budget_bytes = budget_bytes
        self.peak_bytes = plan.peak_bytes if plan is not None else None
        self.op_index = plan.peak_op_index if plan is not None else None
        self.op_type = plan.peak_op_type if plan is not None else None
        super().__init__(message)


class DonationError(EnforceNotMet):
    """Liveness-unsafe donation: a declared ``__inplace__``/donated
    buffer is read after it was consumed."""

    code = "DONATION_SAFETY"

    def __init__(self, message, finding: MemoryFinding = None):
        self.finding = finding
        self.op_index = finding.op_index if finding else None
        self.op_type = finding.op_type if finding else None
        self.var = finding.var if finding else None
        super().__init__(message)


class MemoryPlan:
    """Predicted HBM footprint of one (program, feeds, fetches) run.

    - ``peak_bytes`` — predicted peak resident bytes (baseline + live
      intermediates at the high-water op, sub-block peaks included);
    - ``peak_op_index``/``peak_op_type`` — the high-water op in the
      global block (``None`` for an op-less program: peak == baseline);
    - ``baseline_bytes`` — feeds + referenced persistables + captured
      constants (resident for the whole dispatch);
    - ``resident_bytes`` — the per-op resident curve (global block);
    - ``top_tensors`` — ``[(name, bytes, source), ...]`` largest live
      values at the high-water op, largest first;
    - ``findings`` — donation-safety errors, shape warnings, and
      donation advisories (:class:`MemoryFinding`);
    - ``unresolved`` — var names whose shapes could not be concretized
      (excluded from byte counts, surfaced as warnings).
    """

    __slots__ = ("peak_bytes", "peak_op_index", "peak_op_type",
                 "baseline_bytes", "resident_bytes", "top_tensors",
                 "findings", "unresolved", "op_count")

    def __init__(self, peak_bytes, peak_op_index, peak_op_type,
                 baseline_bytes, resident_bytes, top_tensors, findings,
                 unresolved):
        self.peak_bytes = int(peak_bytes)
        self.peak_op_index = peak_op_index
        self.peak_op_type = peak_op_type
        self.baseline_bytes = int(baseline_bytes)
        self.resident_bytes = list(resident_bytes)
        self.top_tensors = list(top_tensors)
        self.findings = list(findings)
        self.unresolved = sorted(unresolved)
        self.op_count = len(self.resident_bytes)

    @property
    def errors(self) -> List[MemoryFinding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def advisories(self) -> List[MemoryFinding]:
        return [f for f in self.findings if f.severity == "advice"]

    @property
    def warnings(self) -> List[MemoryFinding]:
        return [f for f in self.findings if f.severity == "warning"]

    def top_summary(self, k=3) -> str:
        return ", ".join(f"{n} ({_fmt_bytes(b)}, {src})"
                         for n, b, src in self.top_tensors[:k])

    def raise_if_unsafe(self):
        """Raise :class:`DonationError` on the first donation-safety
        error (use-after-donation); a safe plan returns itself."""
        errs = self.errors
        if errs:
            first = errs[0]
            more = (f" (+{len(errs) - 1} more)" if len(errs) > 1 else "")
            raise DonationError(
                f"donation-safety analysis failed: {first}{more}",
                finding=first)
        return self

    def to_dict(self) -> dict:
        return {
            "peak_bytes": self.peak_bytes,
            "peak_op_index": self.peak_op_index,
            "peak_op_type": self.peak_op_type,
            "baseline_bytes": self.baseline_bytes,
            "op_count": self.op_count,
            "top_tensors": [
                {"name": n, "bytes": b, "source": s}
                for n, b, s in self.top_tensors],
            "errors": [str(f) for f in self.errors],
            "advisories": [str(f) for f in self.advisories],
            "unresolved": list(self.unresolved),
        }

    def __repr__(self):
        where = (f"op #{self.peak_op_index} <{self.peak_op_type}>"
                 if self.peak_op_index is not None else "baseline")
        return (f"MemoryPlan(peak={_fmt_bytes(self.peak_bytes)} @ {where}, "
                f"baseline={_fmt_bytes(self.baseline_bytes)}, "
                f"ops={self.op_count}, errors={len(self.errors)})")


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0


# ---------------------------------------------------------------------------
# shape/spec resolution
# ---------------------------------------------------------------------------


def _nbytes(shape, dtype) -> int:
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize \
        if shape is not None else np.dtype(dtype).itemsize


def _declared_spec(block, name, batch_hint):
    """(shape tuple, dtype) from the VarDesc, resolving ``-1`` dims with
    the run's batch hint; None when unresolvable."""
    try:
        var = block.var(name)
    except KeyError:
        return None
    shape = var._meta.get("shape")
    dtype = var._meta.get("dtype", "float32")
    if shape is None:
        return ((), dtype)  # scalar by convention
    out = []
    for d in shape:
        if d in (-1, None):
            if batch_hint is None:
                return None
            d = batch_hint
        out.append(int(d))
    return (tuple(out), dtype)


def _infer_out_specs(program, block, op, env, batch_hint, unresolved):
    """Resolved (shape, dtype) per output slot of ``op`` (None entries
    for outputs whose shape stays unknown). Resolution order: registry
    ``jax.eval_shape`` over the resolved operand specs (exact, and the
    only way ``-1`` dims concretize through the graph), grad-op
    positional mirroring, then the declared VarDesc."""
    out_names = all_out_names(op)

    if op.type in _BLOCK_OPS:
        specs = []
        if op.type == "while":
            n_loop = op.attrs.get("__n_loop__", 0)
            ins = op_in_names(op)[:n_loop]
            for i, name in enumerate(out_names):
                src = env.get(ins[i]) if i < len(ins) else None
                specs.append(src or _declared_spec(block, name, batch_hint))
        elif op.type == "scan":
            n_c = op.attrs.get("__n_carry__", 0)
            ins = op_in_names(op)[:n_c]
            for i, name in enumerate(out_names):
                if i < n_c and i < len(ins) and env.get(ins[i]) is not None:
                    specs.append(env[ins[i]])
                else:
                    specs.append(_declared_spec(block, name, batch_hint))
        else:  # cond
            specs = [_declared_spec(block, n, batch_hint)
                     for n in out_names]
        return specs

    if op.type.startswith("grad::"):
        # grads mirror the forward inputs positionally (backward.py)
        n_in = op.attrs.get("__n_fwd_in__", 0)
        fwd = all_in_names(op)[:n_in]
        specs = []
        for i, name in enumerate(out_names):
            src = env.get(fwd[i]) if i < len(fwd) else None
            specs.append(src or _declared_spec(block, name, batch_hint))
        return specs

    # registry kernel: abstract-eval with the resolved operand specs
    specs = _eval_shape_specs(op, block, env, batch_hint)
    if specs is not None:
        return specs
    out = []
    for name in out_names:
        s = _declared_spec(block, name, batch_hint)
        if s is None and name:
            unresolved.add(name)
        out.append(s)
    return out


def _eval_shape_specs(op, block, env, batch_hint):
    import jax

    from ..ops.registry import _REGISTRY

    opdef = _REGISTRY.get(op.type)
    if opdef is None:
        return None
    in_specs = []
    for n in op_in_names(op):
        s = env.get(n) if n else None
        if s is None and n:
            s = _declared_spec(block, n, batch_hint)
        if s is None:
            return None
        in_specs.append(jax.ShapeDtypeStruct(tuple(s[0]), np.dtype(s[1])))
    attrs = {k: v for k, v in op.attrs.items() if not k.startswith("__")}
    if op.attrs.get("__rng__"):
        attrs["key"] = jax.random.key(0)
    try:
        out = jax.eval_shape(lambda *xs: opdef.fn(*xs, **attrs), *in_specs)
    except Exception:
        return None
    out_specs = list(out) if isinstance(out, (tuple, list)) else [out]
    return [(tuple(int(d) for d in s.shape), str(s.dtype))
            for s in out_specs]


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------


def plan_memory(program, feed_names=(), fetch_names=(), feed_shapes=None,
                top_k=8) -> MemoryPlan:
    """Interval-based liveness analysis of ``program``'s global block.

    ``feed_shapes`` (``{name: shape tuple}``) concretizes ``-1`` batch
    dims; without it, unresolvable vars are excluded from byte counts
    and reported in ``plan.unresolved``. Returns the
    :class:`MemoryPlan`; donation-safety violations are findings on the
    plan (``plan.raise_if_unsafe()`` / the executor gate reject them).
    """
    feed_names = tuple(feed_names or ())
    fetch_names = tuple(
        v if isinstance(v, str) else v.name for v in (fetch_names or ()))
    feed_shapes = dict(feed_shapes or {})
    findings: List[MemoryFinding] = []
    unresolved: set = set()

    if not program.blocks:
        return MemoryPlan(0, None, None, 0, [], [], findings, unresolved)
    root = program.blocks[0]

    persistables, data_vars = set(), set()
    for blk in program.blocks:
        for name, var in blk.vars.items():
            if getattr(var, "persistable", False):
                persistables.add(name)
            if var._meta.get("is_data"):
                data_vars.add(name)
    constants = dict(getattr(program, "_constants", {}) or {})

    # batch hint: the first feed that concretizes a declared -1 dim
    batch_hint = None
    for n in feed_names:
        shape = feed_shapes.get(n)
        decl = None
        try:
            decl = root.var(n)._meta.get("shape")
        except KeyError:
            pass
        if shape is not None and decl:
            for d_decl, d_real in zip(decl, shape):
                if d_decl in (-1, None):
                    batch_hint = int(d_real)
                    break
        if batch_hint is not None:
            break

    # resolved spec env, seeded with everything statically defined
    env: Dict[str, Tuple[tuple, str]] = {}
    for n in feed_names:
        if n in feed_shapes:
            dt = "float32"
            try:
                dt = root.var(n)._meta.get("dtype", "float32")
            except KeyError:
                pass
            env[n] = (tuple(int(d) for d in feed_shapes[n]), dt)
        else:
            s = _declared_spec(root, n, batch_hint)
            if s is not None:
                env[n] = s
            else:
                unresolved.add(n)
    for n in sorted(persistables | data_vars):
        if n in env:
            continue
        s = _declared_spec(root, n, batch_hint)
        if s is not None:
            env[n] = s
        elif n in persistables:
            unresolved.add(n)
    for n, arr in constants.items():
        a = np.asarray(arr)
        env[n] = (tuple(a.shape), str(a.dtype))

    # referenced names across ALL blocks (baseline counts only the
    # persistables/constants the executor actually threads in)
    referenced: set = set()
    for blk in program.blocks:
        for op in blk.ops:
            referenced.update(n for n in all_in_names(op) if n)
            referenced.update(n for n in all_out_names(op) if n)
    referenced.update(fetch_names)

    baseline_names = set(feed_names)
    baseline_names |= {n for n in persistables if n in referenced}
    baseline_names |= {n for n in constants if n in referenced}
    baseline = 0
    for n in sorted(baseline_names):
        s = env.get(n)
        if s is None:
            continue
        baseline += _nbytes(*s)

    ops = list(root.ops)
    n_ops = len(ops)
    def_idx: Dict[str, int] = {}
    last_use: Dict[str, int] = {}
    sub_extra = [0] * n_ops
    alias_discount = [0] * n_ops
    baseline_adjust = [0] * (n_ops + 1)  # donated baseline buffers die
    consumed_at: Dict[str, int] = {}    # var -> op index that donated it

    for i, op in enumerate(ops):
        ins = [n for n in all_in_names(op) if n]
        for n in ins:
            donor_op = consumed_at.get(n)
            if donor_op is not None and donor_op < i:
                findings.append(MemoryFinding(
                    "error", "donated-then-read",
                    f"input {n!r} was donated by op #{donor_op} "
                    f"<{ops[donor_op].type}> (declared __inplace__ into a "
                    "differently-named output); its buffer is consumed — "
                    "reading it here is a use-after-donation",
                    block_idx=0, op_index=i, op_type=op.type, var=n))
            last_use[n] = i

        outs = all_out_names(op)
        out_specs = _infer_out_specs(program, root, op, env, batch_hint,
                                     unresolved)
        outs_set = set(n for n in outs if n)
        for name, spec in zip(outs, out_specs):
            if not name:
                continue
            if spec is not None:
                env[name] = spec
            else:
                unresolved.add(name)
            def_idx.setdefault(name, i)

        # grad:: ops carry the FORWARD op's attrs verbatim (backward.py)
        # including its __inplace__ — the vjp replay aliases nothing, so
        # the inherited declaration must not read as a donation here
        declared = (() if op.type.startswith("grad::")
                    else tuple(op.attrs.get("__inplace__") or ()))
        for v in declared:
            if v not in ins:
                findings.append(MemoryFinding(
                    "error", "inplace-not-an-input",
                    f"__inplace__ declares {v!r} which the op does not "
                    "read; an aliasing declaration must name an input "
                    "whose buffer the op consumes",
                    block_idx=0, op_index=i, op_type=op.type, var=v))
                continue
            if v in outs_set:
                continue  # same-name state chain: one buffer, one name
            # consumed into a differently-named output: the donor's
            # buffer is reused, so donor+recipient count once at op i
            # and the donor is dead afterwards
            consumed_at[v] = i
            s = env.get(v)
            if s is not None:
                alias_discount[i] += _nbytes(*s)
                if v in baseline_names:
                    baseline_adjust[i + 1] -= _nbytes(*s)
                else:
                    last_use[v] = i

        # donation advisor: an intermediate input that dies HERE while an
        # alias-compatible output exists could have donated its buffer
        if len(findings) < 256:
            for v in ins:
                if (v in declared or v in baseline_names
                        or v in fetch_names or v in outs_set):
                    continue
                sv = env.get(v)
                if sv is None:
                    continue
                for w, sw in zip(outs, out_specs):
                    if (w and w != v and sw is not None
                            and sw == sv and w not in declared):
                        # only an advisory if v is genuinely dead after i
                        # — patched below once last uses are final
                        findings.append(MemoryFinding(
                            "advice", "donation-eligible",
                            f"input {v!r} could donate its buffer to "
                            f"output {w!r} (same shape/dtype) via the "
                            "__inplace__ attr if this is its last read",
                            block_idx=0, op_index=i, op_type=op.type,
                            var=v))
                        break

        if op.type in _BLOCK_OPS:
            sub_extra[i] = _subblock_peak(
                program, op, env, batch_hint, unresolved, findings,
                frozenset({0}))

    # fetches stay live to the end of the block
    for n in fetch_names:
        if n in def_idx or n in env:
            last_use[n] = n_ops
        donor_op = consumed_at.get(n)
        if donor_op is not None:
            findings.append(MemoryFinding(
                "error", "donated-then-read",
                f"fetch target {n!r} was donated by op #{donor_op} "
                f"<{ops[donor_op].type}>; fetching a consumed buffer is "
                "a use-after-donation",
                block_idx=0, op_index=donor_op,
                op_type=ops[donor_op].type, var=n))

    # drop advisories whose var turned out to live on past the op
    findings = [
        f for f in findings
        if not (f.kind == "donation-eligible"
                and last_use.get(f.var, -1) != f.op_index)]

    # intermediates: defined by ops, not part of the baseline
    intervals = []
    for name, d in def_idx.items():
        if name in baseline_names:
            continue
        s = env.get(name)
        if s is None:
            continue
        intervals.append((name, d, last_use.get(name, d), _nbytes(*s)))

    resident = []
    peak, peak_i = baseline, None
    base_i = baseline
    for i in range(n_ops):
        base_i += baseline_adjust[i]
        live = base_i - alias_discount[i] + sub_extra[i]
        live += sum(b for (_n, d, lu, b) in intervals if d <= i <= lu)
        resident.append(int(live))
        if live > peak:
            peak, peak_i = live, i

    # top-K live tensors at the high-water op (peak_i None: the peak IS
    # the baseline — weights/feeds that don't fit still get named)
    top = []
    if peak_i is not None:
        for (name, d, lu, b) in intervals:
            if d <= peak_i <= lu:
                top.append((name, b, "intermediate"))
        if sub_extra[peak_i]:
            top.append((f"<{ops[peak_i].type} sub-block peak>",
                        sub_extra[peak_i], "sub-block"))
    for n in sorted(baseline_names):
        s = env.get(n)
        if s is None:
            continue
        src = ("feed" if n in feed_names else
               "constant" if n in constants else "persistable")
        top.append((n, _nbytes(*s), src))
    top.sort(key=lambda t: (-t[1], t[0]))
    top = top[:int(top_k)]

    for n in sorted(unresolved):
        findings.append(MemoryFinding(
            "warning", "unresolved-shape",
            f"shape of {n!r} could not be concretized; it is excluded "
            "from the byte counts (pass feed_shapes= to resolve -1 dims)",
            var=n))

    return MemoryPlan(
        peak, peak_i, ops[peak_i].type if peak_i is not None else None,
        baseline, resident, top, findings, unresolved)


def _subblock_peak(program, op, parent_env, batch_hint, unresolved,
                   findings, visiting):
    """Peak of the EXTRA bytes a control-flow op's sub-block(s) hold
    while the op runs: intermediates defined inside the block (formals
    alias the parent's carry buffers and are not re-counted), recursing
    into nested control flow; ``cond`` takes the max over its branches
    (max-over-branches semantics), ``while`` the max of cond/body."""
    from .passes import _SUBBLOCK_SPEC

    peaks = [0]
    for bkey, fkeys in _SUBBLOCK_SPEC.get(op.type, ()):
        bidx = op.attrs.get(bkey)
        if (not isinstance(bidx, int)
                or not (0 < bidx < len(program.blocks))
                or bidx in visiting):
            continue
        blk = program.blocks[bidx]
        env = dict(parent_env)
        # formals take the specs of the matching carry/seq inputs
        formals = [f for k in fkeys for f in op.attrs.get(k, ())]
        carry_ins = op_in_names(op)
        for j, f in enumerate(formals):
            src = (parent_env.get(carry_ins[j])
                   if j < len(carry_ins) else None)
            if src is None:
                src = _declared_spec(blk, f, batch_hint)
            if src is not None:
                if (op.attrs.get("__seq_formals__")
                        and f in op.attrs["__seq_formals__"]
                        and len(src[0]) > 0):
                    src = (tuple(src[0][1:]), src[1])  # per-step slice
                env[f] = src
        formal_set = set(formals)

        def_i, last_u = {}, {}
        sub_ops = list(blk.ops)
        sub_sub = [0] * len(sub_ops)
        for i, sop in enumerate(sub_ops):
            for n in all_in_names(sop):
                if n:
                    last_u[n] = i
            out_specs = _infer_out_specs(program, blk, sop, env,
                                         batch_hint, unresolved)
            for name, spec in zip(all_out_names(sop), out_specs):
                if not name:
                    continue
                if spec is not None:
                    env[name] = spec
                else:
                    unresolved.add(name)
                def_i.setdefault(name, i)
            if sop.type in _BLOCK_OPS:
                sub_sub[i] = _subblock_peak(
                    program, sop, env, batch_hint, unresolved, findings,
                    visiting | {bidx})
        # block outputs live to the end of the block
        for key in ("__body_outs__", "__carry_outs__", "__y_outs__",
                    "__true_outs__", "__false_outs__"):
            for n in op.attrs.get(key, ()):
                if n in def_i:
                    last_u[n] = len(sub_ops)
        if op.attrs.get("__cond_out__") in def_i:
            last_u[op.attrs["__cond_out__"]] = len(sub_ops)

        intervals = []
        for name, d in def_i.items():
            if name in formal_set or name in parent_env:
                continue  # aliases a buffer the parent already counts
            s = env.get(name)
            if s is None:
                continue
            intervals.append((d, last_u.get(name, d), _nbytes(*s)))
        blk_peak = 0
        for i in range(len(sub_ops)):
            live = sub_sub[i] + sum(
                b for (d, lu, b) in intervals if d <= i <= lu)
            blk_peak = max(blk_peak, live)
        peaks.append(blk_peak)
    return max(peaks)


# ---------------------------------------------------------------------------
# budget gate (the executor admission driver)
# ---------------------------------------------------------------------------


def hbm_budget_bytes() -> int:
    """Device HBM capacity from the cost-model peaks table
    (``FLAGS_device_peaks`` ``hbm_bytes=`` overrides it — the knob the
    strict-rejection tests and derated deployments use)."""
    from ..monitor import cost_model as _cost

    return int(_cost.device_peaks().get("hbm_bytes", 0) or 0)


_CACHE_LIMIT = 64


def check_memory_budget(program, feed_names=(), fetch_names=(),
                        feed_shapes=None, level="warn",
                        budget_bytes=None):
    """Plan ``program``'s footprint and enforce the HBM budget.

    The verdict caches on the program per (version, feeds, fetches,
    shapes, level, budget) with the same LRU discipline as the PR-13
    verifier cache, so ``Executor.run``'s steady state pays one dict
    lookup (bench.py ``executor_dispatch.memplan``). ``strict`` raises
    :class:`MemoryBudgetError` (over budget) or :class:`DonationError`
    (use-after-donation); ``warn`` records the same verdicts as
    ``memory_budget`` flight events and a Python warning, but admits.
    Planner-internal failures NEVER block execution: they cache an
    inconclusive verdict and record the event.

    Returns the :class:`MemoryPlan` (or ``None`` when inconclusive).
    """
    from ..profiler import bump_counter

    fetch_names = tuple(
        v if isinstance(v, str) else v.name for v in (fetch_names or ()))
    feeds = tuple(sorted(feed_names or ()))
    shapes_sig = tuple(sorted(
        (n, tuple(int(d) for d in s))
        for n, s in (feed_shapes or {}).items()))
    budget = int(budget_bytes if budget_bytes is not None
                 else hbm_budget_bytes())
    n_vars = sum(len(b.vars) for b in program.blocks)
    key = (program._version, n_vars, feeds, fetch_names, shapes_sig,
           str(level), budget)
    cache = program.__dict__.setdefault("_memplan_cache", {})
    hit = cache.get(key)
    if hit is not None:
        cache.pop(key, None)
        cache[key] = hit  # LRU refresh
        bump_counter("memplan::cache_hit")
        if isinstance(hit, Exception):
            raise hit.with_traceback(None)
        return None if hit is _INCONCLUSIVE else hit
    bump_counter("memplan::cache_miss")

    try:
        plan = plan_memory(program, feeds, fetch_names, feed_shapes)
    except Exception as e:  # the planner must never take execution down
        _record_verdict(program, "inconclusive",
                        error=f"{type(e).__name__}: {e}")
        _cache_put(cache, key, _INCONCLUSIVE)
        return None

    verdict, exc = "ok", None
    errs = plan.errors
    if errs:
        verdict = "donation_unsafe"
        if str(level) == "strict":
            try:
                plan.raise_if_unsafe()
            except DonationError as e:
                exc = e
    if exc is None and budget > 0 and plan.peak_bytes > budget:
        verdict = "over_budget"
        where = (f"high-water op #{plan.peak_op_index} "
                 f"<{plan.peak_op_type}>" if plan.peak_op_index is not None
                 else "baseline: the feeds/persistables alone don't fit")
        msg = (
            f"predicted peak HBM {_fmt_bytes(plan.peak_bytes)} exceeds "
            f"the device budget {_fmt_bytes(budget)} "
            f"({where}; top live tensors: "
            f"{plan.top_summary()}). Shrink the program, or override "
            "the budget via FLAGS_device_peaks hbm_bytes=...")
        if str(level) == "strict":
            exc = MemoryBudgetError(msg, plan=plan, budget_bytes=budget)

    _record_verdict(program, verdict, plan=plan, budget=budget)
    if exc is not None:
        _cache_put(cache, key, exc)
        raise exc
    if verdict != "ok":
        import warnings

        first = errs[0] if errs else None
        where = (f"at op #{plan.peak_op_index} <{plan.peak_op_type}>"
                 if plan.peak_op_index is not None else "at the baseline")
        warnings.warn(
            f"memory_budget_check={level}: {verdict} — "
            + (str(first) if first is not None else
               f"predicted peak {_fmt_bytes(plan.peak_bytes)} > budget "
               f"{_fmt_bytes(budget)} {where}"),
            RuntimeWarning, stacklevel=3)
    _cache_put(cache, key, plan)
    return plan


_INCONCLUSIVE = object()


def _cache_put(cache, key, value):
    cache[key] = value
    while len(cache) > _CACHE_LIMIT:
        try:
            cache.pop(next(iter(cache)), None)
        except (StopIteration, RuntimeError):
            break


def _record_verdict(program, verdict, plan=None, budget=None, error=None):
    try:  # the black box must never break admission itself
        from ..monitor import flight_recorder as _flight

        tok = getattr(program, "_identity_token", None)
        fields = dict(
            program=f"{tok if tok is not None else id(program)}"
                    f"@v{program._version}",
            verdict=verdict)
        if plan is not None:
            fields.update(
                peak_bytes=plan.peak_bytes,
                baseline_bytes=plan.baseline_bytes,
                peak_op_index=plan.peak_op_index,
                peak_op_type=plan.peak_op_type,
                top=plan.top_summary(3),
                donation_errors=len(plan.errors))
        if budget is not None:
            fields["budget_bytes"] = int(budget)
        if error is not None:
            fields["error"] = str(error)[:300]
        _flight.record_event("memory_budget", **fields)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# accuracy closure (predicted vs XLA memory_analysis)
# ---------------------------------------------------------------------------

_acc_lock = threading.Lock()
_accuracy: dict = {}  # cache_key -> record dict (insertion-ordered)
_ACC_LIMIT = 128


def note_actual(record, plan) -> Optional[float]:
    """Close the loop on one compiled program: compare the plan's
    predicted peak with XLA's ``memory_analysis`` actual (argument +
    output + temp − alias) and ledger the ``plan_accuracy`` ratio —
    onto the CostRecord itself (``/costz``), the
    ``memplan/plan_accuracy`` gauge (``/statz``), and the bounded
    :func:`accuracy_records` table the bench/smoke read. Returns the
    ratio, or ``None`` when either side is unavailable."""
    if record is None or plan is None or record.partial:
        return None
    actual = (record.argument_bytes + record.output_bytes
              + record.temp_bytes - record.alias_bytes)
    if actual <= 0 or plan.peak_bytes <= 0:
        return None
    ratio = plan.peak_bytes / actual
    record.predicted_peak_bytes = int(plan.peak_bytes)
    record.plan_accuracy = ratio
    entry = {
        "cache_key": str(record.key), "label": record.label,
        "predicted_bytes": int(plan.peak_bytes),
        "actual_bytes": int(actual),
        "plan_accuracy": ratio,
    }
    with _acc_lock:
        _accuracy.pop(entry["cache_key"], None)
        _accuracy[entry["cache_key"]] = entry
        while len(_accuracy) > _ACC_LIMIT:
            _accuracy.pop(next(iter(_accuracy)))
    try:
        from ..monitor import registry as _reg

        _reg.gauge("memplan/plan_accuracy").set(ratio)
        from ..monitor import flight_recorder as _flight

        _flight.record_event(
            "plan_accuracy", cache_key=str(record.key),
            predicted_bytes=int(plan.peak_bytes),
            actual_bytes=int(actual), ratio=round(ratio, 4))
    except Exception:
        pass
    return ratio


def accuracy_records() -> List[dict]:
    """Predicted-vs-actual entries, oldest first (bounded)."""
    with _acc_lock:
        return [dict(v) for v in _accuracy.values()]


def reset_accuracy_records():
    with _acc_lock:
        _accuracy.clear()
