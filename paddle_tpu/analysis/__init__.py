"""Static analysis: program-IR verifier + framework-aware source lint.

Two halves (ISSUE 13, in the TVM/compiler-first spirit of PAPERS.md):

- :mod:`verifier` / :mod:`passes` — a pass framework over the static
  Program IR (``static/program.py``). ``verify_program`` (also exposed as
  ``Program.verify``) checks def-before-use, duplicate/undeclared-alias
  writes, kernel dtype consistency, dead ops/vars, and control-flow block
  well-formedness BEFORE the executor lowers the block to XLA — a
  malformed program becomes a structured :class:`VerifyError` naming the
  op index, op type, and variable instead of an opaque trace error.
  ``Executor.run`` verifies automatically behind ``FLAGS_program_verify``
  (the verdict is cached per program version, so steady-state dispatch
  pays one dict lookup — bench.py ``executor_dispatch.program_verify``).
- :mod:`lint` — AST lint rules encoding recurring review findings
  (stale trace-time flag reads, unlocked shared-counter mutation, host
  syncs in decode/dispatch hot loops, weak-typed python-scalar captures,
  per-token cache materialization in decode/dispatch loops).
  CLI: ``tools/graphlint.py``; waivers: ``tools/graphlint_waivers.txt``.
- :mod:`memory` (Memplan, ISSUE 14) — interval-based liveness + peak-HBM
  planning over the same IR: :func:`plan_memory` predicts the peak
  resident bytes, high-water op, and top live tensors of a run BEFORE
  any lowering, honoring the ``__inplace__`` aliasing convention, and
  the liveness-aware donation-safety analysis rejects
  declared-then-read donated buffers. ``Executor.run`` enforces the
  device HBM budget through :func:`check_memory_budget` behind
  ``FLAGS_memory_budget_check``, and every real compile closes the loop
  via :func:`note_actual` (``plan_accuracy`` vs XLA memory_analysis).
- :mod:`optimizer` (IR optimizer, ISSUE 16) — the REWRITE half over the
  same IR: a :class:`PassManager` of fusion passes (conv2d+batch_norm+
  relu, residual-add+layer_norm, dequantized-int8 matmul/mul onto the
  fused registry kernels), generalized constant folding + dead-op
  elimination (the former Predictor-local ``inference/passes.py``
  pipeline), and liveness-driven rematerialization that consults the
  memplan resident curve to fit an over-budget program into HBM.
  ``Executor.run`` and the Predictor drive :func:`optimize_program`
  behind ``FLAGS_ir_opt_level``; every pass verifies pre/post and
  replans memory, reporting per-pass stats to counters and ``/statz``.
"""
from .verifier import (  # noqa: F401
    Finding,
    VerifyError,
    VerifyReport,
    register_pass,
    verifier_passes,
    verify_program,
)
from .lint import (  # noqa: F401
    LintFinding,
    lint_file,
    lint_paths,
    lint_rules,
    lint_source,
)
from .memory import (  # noqa: F401
    DonationError,
    MemoryBudgetError,
    MemoryFinding,
    MemoryPlan,
    accuracy_records,
    check_memory_budget,
    hbm_budget_bytes,
    note_actual,
    plan_memory,
)
from .optimizer import (  # noqa: F401
    OptResult,
    PassManager,
    PassStats,
    measure_pass_deltas,
    optimize_program,
    optimizer_passes,
    optimizer_stats,
    register_opt_pass,
)
from .waivers import Waiver, load_waivers, match_waiver  # noqa: F401

__all__ = [
    "DonationError",
    "MemoryBudgetError",
    "MemoryFinding",
    "MemoryPlan",
    "accuracy_records",
    "check_memory_budget",
    "hbm_budget_bytes",
    "note_actual",
    "plan_memory",
    "OptResult",
    "PassManager",
    "PassStats",
    "measure_pass_deltas",
    "optimize_program",
    "optimizer_passes",
    "optimizer_stats",
    "register_opt_pass",
    "Finding",
    "VerifyError",
    "VerifyReport",
    "register_pass",
    "verifier_passes",
    "verify_program",
    "LintFinding",
    "lint_file",
    "lint_paths",
    "lint_rules",
    "lint_source",
    "Waiver",
    "load_waivers",
    "match_waiver",
]
