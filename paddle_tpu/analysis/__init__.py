"""Static analysis: program-IR verifier + framework-aware source lint.

Two halves (ISSUE 13, in the TVM/compiler-first spirit of PAPERS.md):

- :mod:`verifier` / :mod:`passes` — a pass framework over the static
  Program IR (``static/program.py``). ``verify_program`` (also exposed as
  ``Program.verify``) checks def-before-use, duplicate/undeclared-alias
  writes, kernel dtype consistency, dead ops/vars, and control-flow block
  well-formedness BEFORE the executor lowers the block to XLA — a
  malformed program becomes a structured :class:`VerifyError` naming the
  op index, op type, and variable instead of an opaque trace error.
  ``Executor.run`` verifies automatically behind ``FLAGS_program_verify``
  (the verdict is cached per program version, so steady-state dispatch
  pays one dict lookup — bench.py ``executor_dispatch.program_verify``).
- :mod:`lint` — AST lint rules encoding recurring review findings
  (stale trace-time flag reads, unlocked shared-counter mutation, host
  syncs in decode/dispatch hot loops, weak-typed python-scalar captures).
  CLI: ``tools/graphlint.py``; waivers: ``tools/graphlint_waivers.txt``.
"""
from .verifier import (  # noqa: F401
    Finding,
    VerifyError,
    VerifyReport,
    register_pass,
    verifier_passes,
    verify_program,
)
from .lint import (  # noqa: F401
    LintFinding,
    lint_file,
    lint_paths,
    lint_rules,
    lint_source,
)
from .waivers import Waiver, load_waivers, match_waiver  # noqa: F401

__all__ = [
    "Finding",
    "VerifyError",
    "VerifyReport",
    "register_pass",
    "verifier_passes",
    "verify_program",
    "LintFinding",
    "lint_file",
    "lint_paths",
    "lint_rules",
    "lint_source",
    "Waiver",
    "load_waivers",
    "match_waiver",
]
