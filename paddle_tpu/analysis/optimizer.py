"""Program-IR optimizer: pass manager, fusion rewrites, rematerialization.

Reference parity: inference/analysis/ir_pass_manager.cc + the fuse-pass
half of api/paddle_pass_builder.cc (conv_bn_fuse_pass and friends),
generalized from the Predictor's load-time pipeline to every executed
program. The TVM-spirit middle of the compiler stack: the framework now
*rewrites* its own ``Program/Block/OpDesc`` IR ahead of lowering instead
of only verifying (PR 13) and memory-planning (PR 14) it.

Three families of passes, all registered on the same ordered registry
(the PR-13 ``register_pass`` idiom):

- **Fusion** — pattern-match op chains onto the fused registry kernels
  (``ops/fused_ops.py``): ``conv2d -> batch_norm -> relu`` becomes
  ``fused_conv_bn_relu``, ``elementwise_add -> layer_norm`` over the
  last dim becomes ``fused_layernorm_residual``, and a matmul/mul whose
  operands are ``dequantize_static``-restored int8 tensors becomes
  ``matmul_int8``/``mul_int8``. Fusion is REFUSED whenever an
  eliminated intermediate is fetched, read by any second consumer
  (including a ``grad::`` op or a sub-block), written twice, or
  aliased — a training program with no fusible chain comes back
  byte-identical.

- **Constant folding + dead-op elimination** — generalized from the
  Predictor-local ``inference/passes.py`` pipeline (now a thin shim over
  this module). Folding needs a ``Scope`` (load-time weights) and runs
  ops whose inputs are all statically available ONCE with the real
  kernels; DCE removes side-effect-free ops whose outputs nothing
  reads — ops that write persistables, declare ``__inplace__``, carry
  control-flow sub-blocks, or are ``grad::`` replays are never touched.

- **Rematerialization** (level >= 2) — when the program's planned peak
  (:func:`~paddle_tpu.analysis.plan_memory`) exceeds the device HBM
  budget, recompute cheap flops-light activations (relu/add/layernorm
  class) at their late uses instead of holding them across the
  high-water op: the producer op is duplicated right before the first
  late use writing ``<v>@remat<k>``, late consumers are rewired, and
  the plan is re-run until the program fits (or no candidate helps).

The manager runs ``Program.verify()`` before the pipeline and after
every pass that changed the program, replans memory per pass, and
reports ``ops_rewritten`` / ``bytes_saved`` / wall-time per pass — as
:class:`PassStats`, profiler counters (``ir_opt::<pass>::*``), monitor
registry counters (``ir_opt/<pass>/*``), and the ``/statz`` ``ir_opt``
block. :func:`optimize_program` is the cached clone-and-rewrite entry
``Executor.run`` and the ``Predictor`` drive behind
``FLAGS_ir_opt_level``: unchanged program versions pay one dict lookup
(the verifier-cache discipline), and a pipeline that rewrites nothing
returns the ORIGINAL program object so compile caches see no new
identity.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, NamedTuple, Optional

from .verifier import all_in_names, all_out_names, op_in_names, op_out_names

__all__ = [
    "OptPass", "OptResult", "PassManager", "PassStats",
    "constant_folding", "dead_op_elimination", "fuse_conv_bn_relu",
    "fuse_int8_matmul", "fuse_layernorm_residual", "measure_pass_deltas",
    "optimize_program", "optimizer_passes", "optimizer_stats",
    "register_opt_pass", "rematerialize", "reset_optimizer_stats",
]

_BLOCK_OPS = ("while", "cond", "scan")

#: ops cheap enough to recompute at a late use instead of holding the
#: activation across the high-water op (flops-light, deterministic)
_REMAT_CHEAP_OPS = frozenset({
    "relu", "elementwise_add", "elementwise_sub", "elementwise_mul",
    "layer_norm", "fused_layernorm_residual", "tanh", "sigmoid", "gelu",
    "scale", "cast", "reshape", "transpose",
})

_REMAT_MAX_ROUNDS = 32
_CACHE_LIMIT = 16  # optimized-clone LRU bound per program


class PassStats(NamedTuple):
    """One pass's report: what it rewrote and what that bought."""
    name: str
    ops_rewritten: int
    bytes_saved: int
    wall_ms: float


class OptResult(NamedTuple):
    """:func:`optimize_program` result. ``program`` is the optimized
    clone, or the ORIGINAL object when no pass rewrote anything."""
    program: object
    stats: List[PassStats]
    changed: bool


class OptPass(NamedTuple):
    name: str
    fn: Callable
    min_level: int
    needs_scope: bool


_OPT_PASSES: Dict[str, OptPass] = {}


def register_opt_pass(name: str, min_level: int = 1, needs_scope: bool = False):
    """Decorator: register an optimizer pass ``fn(ctx) -> ops_rewritten``
    (the PR-13 verifier ``register_pass`` idiom, ordered by
    registration). ``min_level`` gates it on ``FLAGS_ir_opt_level``;
    ``needs_scope`` passes are skipped unless the caller supplies a
    Scope (the Predictor's load-time pipeline does, ``Executor.run``
    does not — folding a live training scope would freeze weights)."""

    def deco(fn):
        if name in _OPT_PASSES:
            raise ValueError(f"optimizer pass {name!r} registered twice")
        _OPT_PASSES[name] = OptPass(name, fn, min_level, needs_scope)
        return fn

    return deco


def optimizer_passes() -> list:
    """Registered pass names in pipeline order."""
    return list(_OPT_PASSES)


# ---------------------------------------------------------------------------
# pass context + IR helpers
# ---------------------------------------------------------------------------


class OptContext:
    """Per-pipeline state handed to each pass: the (mutable) program,
    run signature, and lazily-rebuilt use/def maps over the IR."""

    def __init__(self, program, feed_names=(), fetch_names=(), scope=None,
                 feed_shapes=None, level=1):
        self.program = program
        self.feed_names = tuple(feed_names or ())
        self.fetch_names = tuple(
            v if isinstance(v, str) else v.name for v in (fetch_names or ()))
        self.scope = scope
        self.feed_shapes = dict(feed_shapes or {})
        self.level = int(level)

    # -- use/def maps (recomputed per pass: passes mutate the IR) -----------

    def use_counts(self) -> Dict[str, int]:
        """Reads per var name across ALL blocks (sub-block reads of a
        parent var count — fusing it away would break the sub-block)."""
        uses: Dict[str, int] = {}
        for blk in self.program.blocks:
            for op in blk.ops:
                for n in all_in_names(op):
                    if n:
                        uses[n] = uses.get(n, 0) + 1
        return uses

    def writer_counts(self) -> Dict[str, int]:
        writes: Dict[str, int] = {}
        for blk in self.program.blocks:
            for op in blk.ops:
                for n in all_out_names(op):
                    if n:
                        writes[n] = writes.get(n, 0) + 1
        return writes

    def grad_read(self) -> set:
        """Names read by any ``grad::`` op (fusion must not eliminate a
        var the backward replay re-reads)."""
        names = set()
        for blk in self.program.blocks:
            for op in blk.ops:
                if op.type.startswith("grad::"):
                    names.update(n for n in all_in_names(op) if n)
        return names

    def persistables(self) -> set:
        names = set()
        for blk in self.program.blocks:
            for name, var in blk.vars.items():
                if getattr(var, "persistable", False):
                    names.add(name)
        return names

    def bump_version(self):
        p = self.program
        p._version = getattr(p, "_version", 0) + 1


def _var_dtype(block, name):
    try:
        return str(block.var(name)._meta.get("dtype", "float32"))
    except KeyError:
        return None


def _var_shape(block, name):
    try:
        s = block.var(name)._meta.get("shape")
    except KeyError:
        return None
    return None if s is None else tuple(s)


def _single_out(op) -> Optional[str]:
    """The op's sole non-empty output name, or None."""
    outs = [n for n in all_out_names(op) if n]
    return outs[0] if len(outs) == 1 else None


def _writes_between(block, names, lo, hi, skip=()) -> bool:
    """Any op with index in (lo, hi) writing one of ``names``? Fusion
    moves the matched producers down to the chain tail, which is only
    sound if nothing in between redefines their operands. ``skip``
    excludes the chain's own dropped ops from the check."""
    names = set(names)
    for idx in range(lo + 1, hi):
        if idx in skip:
            continue
        if any(n in names for n in all_out_names(block.ops[idx]) if n):
            return True
    return False


class _Chain(NamedTuple):
    """One matched fusion chain: ops to drop, the replacement OpDesc,
    and the index the replacement lands at (the chain tail).
    ``extra_replace`` holds further in-place ``(index, OpDesc)``
    substitutions (the int8 pass's quant-sim -> quantize rewrite)."""
    drop: tuple        # op indices removed from the block
    anchor: int        # index whose op is replaced by ``new_op``
    new_op: object
    new_vars: tuple    # (name, shape, dtype) descs to declare
    extra_replace: tuple = ()


def _apply_chains(ctx, block, chains) -> int:
    """Rewrite non-overlapping matched chains into the block in one
    reconstruction pass. Returns the number of chains applied."""
    if not chains:
        return 0
    claimed: set = set()
    replace: Dict[int, object] = {}
    drop: set = set()
    applied = 0
    for ch in chains:
        span = set(ch.drop) | {ch.anchor} | {i for i, _ in ch.extra_replace}
        if span & claimed:
            continue  # overlapping match: first registration wins
        claimed |= span
        replace[ch.anchor] = ch.new_op
        for idx, op in ch.extra_replace:
            replace[idx] = op
        drop |= set(ch.drop)
        for name, shape, dtype in ch.new_vars:
            if not block.has_var(name):
                block.create_var(name=name,
                                 shape=None if shape is None else list(shape),
                                 dtype=dtype)
        applied += 1
    new_ops = []
    for idx, op in enumerate(block.ops):
        if idx in replace:
            new_ops.append(replace[idx])
        elif idx not in drop:
            new_ops.append(op)
    block.ops[:] = new_ops
    ctx.bump_version()
    return applied


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


# ---------------------------------------------------------------------------
# fusion passes
# ---------------------------------------------------------------------------


def _fusible(ctx, name, uses, writes, grad_read, persist) -> bool:
    """May ``name`` be eliminated as a fused-chain intermediate? Refused
    when it is fetched, read by more than its one chain consumer, read
    by a ``grad::`` replay, persistable, or written more than once."""
    return (name not in ctx.fetch_names
            and name not in ctx.feed_names
            and name not in persist
            and name not in grad_read
            and uses.get(name, 0) == 1
            and writes.get(name, 0) == 1)


@register_opt_pass("fuse_conv_bn_relu")
def fuse_conv_bn_relu(ctx) -> int:
    """``conv2d -> batch_norm -> relu`` => ``fused_conv_bn_relu``.

    The conv must be bias-free (a biased ``static.nn.conv2d`` interposes
    an ``elementwise_add``, breaking adjacency by construction),
    ungrouped and undilated — the fused kernel's own admission rule. The
    ``batch_norm`` stat outputs keep their names and ``__inplace__``
    aliasing, so training-mode running-stat write-back is unchanged.
    """
    from ..static.program import OpDesc

    uses = ctx.use_counts()
    writes = ctx.writer_counts()
    grad_read = ctx.grad_read()
    persist = ctx.persistables()
    block = ctx.program.global_block()

    last_writer: Dict[str, int] = {}
    chains = []
    for j, bn in enumerate(block.ops):
        if bn.type == "batch_norm":
            bn_in = op_in_names(bn)
            bn_out = op_out_names(bn)
            if len(bn_in) == 5 and len(bn_out) == 3:
                chain = _match_conv_bn_relu(
                    ctx, block, j, bn, bn_in, bn_out, last_writer, uses,
                    writes, grad_read, persist, OpDesc)
                if chain is not None:
                    chains.append(chain)
        for n in all_out_names(bn):
            if n:
                last_writer[n] = j
    return _apply_chains(ctx, block, chains)


def _match_conv_bn_relu(ctx, block, j, bn, bn_in, bn_out, last_writer, uses,
                        writes, grad_read, persist, OpDesc):
    conv_out = bn_in[0]
    i = last_writer.get(conv_out)
    if i is None:
        return None
    conv = block.ops[i]
    if conv.type != "conv2d" or _single_out(conv) != conv_out:
        return None
    if int(conv.attrs.get("groups", 1)) != 1:
        return None
    if _pair(conv.attrs.get("dilation", 1)) != (1, 1):
        return None
    if conv.attrs.get("data_format", "NCHW") != bn.attrs.get(
            "data_format", "NCHW"):
        return None
    if not _fusible(ctx, conv_out, uses, writes, grad_read, persist):
        return None
    # the unique consumer of bn's y must be a relu
    bn_y = bn_out[0]
    if not _fusible(ctx, bn_y, uses, writes, grad_read, persist):
        return None
    relu_idx = None
    for k in range(j + 1, len(block.ops)):
        if bn_y in all_in_names(block.ops[k]):
            relu_idx = k
            break
    if relu_idx is None:
        return None
    relu = block.ops[relu_idx]
    if relu.type != "relu" or op_in_names(relu) != [bn_y]:
        return None
    relu_out = _single_out(relu)
    if relu_out is None:
        return None
    conv_in = op_in_names(conv)
    if len(conv_in) != 2:
        return None  # bias-free conv has exactly (x, weight)
    fused_in = [conv_in[0], conv_in[1],
                bn_in[1], bn_in[2], bn_in[3], bn_in[4]]
    # hoisting conv+bn down to the relu's slot: nothing in between may
    # redefine an operand (the dropped bn's own stat writes excepted),
    # and nothing may read the stat outputs before the fused op rewrites
    # them at the anchor
    if _writes_between(block, fused_in, i, relu_idx, skip=(j,)):
        return None
    for idx in range(j + 1, relu_idx):
        if any(n in bn_out[1:] for n in all_in_names(block.ops[idx])):
            return None
    attrs = {
        "stride": conv.attrs.get("stride", 1),
        "padding": conv.attrs.get("padding", 0),
        "momentum": bn.attrs.get("momentum", 0.9),
        "epsilon": bn.attrs.get("epsilon", 1e-5),
        "training": bn.attrs.get("training", True),
        "data_format": bn.attrs.get("data_format", "NCHW"),
    }
    if bn.attrs.get("__inplace__"):
        attrs["__inplace__"] = tuple(bn.attrs["__inplace__"])
    new_op = OpDesc("fused_conv_bn_relu", {"X": list(fused_in)},
                    {"Out": [relu_out, bn_out[1], bn_out[2]]}, attrs)
    return _Chain(drop=(i, j), anchor=relu_idx, new_op=new_op, new_vars=())


@register_opt_pass("fuse_layernorm_residual")
def fuse_layernorm_residual(ctx) -> int:
    """``elementwise_add -> layer_norm`` (last-dim norm, trailing [H]
    affine) => ``fused_layernorm_residual`` — the transformer residual
    idiom. Requires same-shape addends (the kernel's residual contract)
    and a 1-D scale/bias matching the last dim."""
    from ..static.program import OpDesc

    uses = ctx.use_counts()
    writes = ctx.writer_counts()
    grad_read = ctx.grad_read()
    persist = ctx.persistables()
    block = ctx.program.global_block()

    last_writer: Dict[str, int] = {}
    chains = []
    for j, ln in enumerate(block.ops):
        if ln.type == "layer_norm":
            chain = _match_ln_residual(
                ctx, block, j, ln, last_writer, uses, writes, grad_read,
                persist, OpDesc)
            if chain is not None:
                chains.append(chain)
        for n in all_out_names(ln):
            if n:
                last_writer[n] = j
    return _apply_chains(ctx, block, chains)


def _match_ln_residual(ctx, block, j, ln, last_writer, uses, writes,
                       grad_read, persist, OpDesc):
    ln_in = op_in_names(ln)
    if len(ln_in) != 3:  # need the affine pair for the fused kernel
        return None
    t, scale, bias = ln_in
    i = last_writer.get(t)
    if i is None:
        return None
    add = block.ops[i]
    if add.type != "elementwise_add" or _single_out(add) != t:
        return None
    add_in = op_in_names(add)
    if len(add_in) != 2 or not all(add_in):
        return None
    a, b = add_in
    if not _fusible(ctx, t, uses, writes, grad_read, persist):
        return None
    # last-dim normalization only (the kernel's contract)
    sa, sb = _var_shape(block, a), _var_shape(block, b)
    st = _var_shape(block, t)
    if sa is None or sb is None or sa != sb:
        return None  # broadcasting add: not the residual pattern
    bna = int(ln.attrs.get("begin_norm_axis", -1))
    ndim = len(st) if st is not None else len(sa)
    if bna not in (-1, ndim - 1):
        return None
    ss = _var_shape(block, scale)
    if ss is None or len(ss) != 1:
        return None
    h = (st or sa)[-1]
    if h in (-1, None) or ss[0] != h:
        return None
    if _writes_between(block, (a, b, scale, bias), i, j):
        return None
    ln_out = _single_out(ln)
    if ln_out is None:
        return None
    new_op = OpDesc("fused_layernorm_residual", {"X": [a, b, scale, bias]},
                    {"Out": [ln_out]},
                    {"epsilon": ln.attrs.get("epsilon", 1e-5)})
    return _Chain(drop=(i,), anchor=j, new_op=new_op, new_vars=())


@register_opt_pass("fuse_int8_matmul")
def fuse_int8_matmul(ctx) -> int:
    """Dequantized-int8 matmul/mul chains => ``matmul_int8``/``mul_int8``.

    Two admitted activation forms, both with the weight operand restored
    by ``dequantize_static`` from an int8 tensor (the shipped-int8 form
    ``slim/ptq.py`` leaves for ops it could not rewrite itself):

    - activation also ``dequantize_static``-restored from an int8
      tensor: contract the two int8 operands directly;
    - activation behind a ``quant_dequant_static`` sim op: replace the
      simulation with one real ``quantize_static`` (f32 -> int8) and
      contract — exactly the ``rewrite_int8_program`` lowering, now
      available to any imported program at run time.

    The int32 accumulation dequantizes once by the combined scale, so
    results match the f32-of-dequantized chain to float rounding (not
    bit-exact — the goldens use a tight allclose).
    """
    from ..static.program import OpDesc

    uses = ctx.use_counts()
    writes = ctx.writer_counts()
    grad_read = ctx.grad_read()
    persist = ctx.persistables()
    block = ctx.program.global_block()

    last_writer: Dict[str, int] = {}
    chains = []
    for j, mm in enumerate(block.ops):
        if mm.type in ("matmul", "mul"):
            chain = _match_int8(ctx, block, j, mm, last_writer, uses,
                                writes, grad_read, persist, OpDesc)
            if chain is not None:
                chains.append(chain)
        for n in all_out_names(mm):
            if n:
                last_writer[n] = j
    return _apply_chains(ctx, block, chains)


def _dequant_producer(block, last_writer, name):
    """(op index, int8 source, attrs) when ``name`` is written by a
    ``dequantize_static`` of an int8 var; None otherwise."""
    i = last_writer.get(name)
    if i is None:
        return None
    op = block.ops[i]
    if op.type != "dequantize_static" or _single_out(op) != name:
        return None
    src = op_in_names(op)[0]
    if _var_dtype(block, src) != "int8":
        return None
    return i, src, op.attrs


def _match_int8(ctx, block, j, mm, last_writer, uses, writes, grad_read,
                persist, OpDesc):
    ins = op_in_names(mm)
    if len(ins) != 2:
        return None
    a, w = ins
    wside = _dequant_producer(block, last_writer, w)
    if wside is None:
        return None
    iw, w8, wattrs = wside
    if not _fusible(ctx, w, uses, writes, grad_read, persist):
        return None

    drop = [iw]
    new_vars = ()
    extra_replace = ()
    aside = _dequant_producer(block, last_writer, a)
    if aside is not None:
        ia, a8, aattrs = aside
        if not _fusible(ctx, a, uses, writes, grad_read, persist):
            return None
        act_in, scale_x = a8, aattrs.get("scale")
        bl = aattrs.get("bit_length", 8)
        drop.append(ia)
        guard_in = [a8, w8]
        lo = min(ia, iw)
    else:
        i = last_writer.get(a)
        if i is None:
            return None
        qd = block.ops[i]
        if qd.type != "quant_dequant_static" or _single_out(qd) != a:
            return None
        if not _fusible(ctx, a, uses, writes, grad_read, persist):
            return None
        base = op_in_names(qd)[0]
        scale_x = qd.attrs.get("scale")
        bl = qd.attrs.get("bit_length", 8)
        if scale_x is None:
            return None
        q8 = f"{base}@q8"
        if block.has_var(q8) or q8 in writes:
            return None  # name already claimed (e.g. a prior rewrite)
        act_in = q8
        new_vars = ((q8, _var_shape(block, base), "int8"),)
        guard_in = [base, w8]
        lo = min(i, iw)
        # the quant-sim op at ``i`` BECOMES the real quantize (same
        # position, same input, new int8 output)
        quant = OpDesc("quantize_static", {"X": [base]}, {"Out": [q8]},
                       {"scale": float(scale_x), "bit_length": int(bl)})
        extra_replace = ((i, quant),)
    if scale_x is None or wattrs.get("scale") is None:
        return None
    if _writes_between(block, guard_in, lo, j):
        return None

    attrs = {k: v for k, v in mm.attrs.items() if not k.startswith("__")}
    attrs.update(scale_x=float(scale_x), scale_y=float(wattrs["scale"]),
                 bit_length=int(bl),
                 y_bit_length=int(wattrs.get("bit_length", 8)))
    new_op = OpDesc(f"{mm.type}_int8", {"X": [act_in, w8]},
                    dict(mm.outputs), attrs)
    return _Chain(drop=tuple(drop), anchor=j, new_op=new_op,
                  new_vars=new_vars, extra_replace=extra_replace)


# ---------------------------------------------------------------------------
# constant folding + dead-op elimination (generalized inference/passes.py)
# ---------------------------------------------------------------------------


@register_opt_pass("constant_folding", needs_scope=True)
def constant_folding(ctx) -> int:
    """Precompute every top-block op not reachable from a feed.

    An op whose inputs are all load-time constants (scope-resident
    parameters, captured constants, or outputs of already-folded ops)
    runs ONCE here with the real kernels; its outputs become
    scope-resident persistable vars and the op disappears from the
    block. RNG ops, control-flow ops and ``grad::`` replays never fold.
    Scope-gated: only the Predictor's load-time pipeline supplies one
    (folding against a live training scope would freeze weights).
    """
    from ..ops.registry import kernel

    program, scope = ctx.program, ctx.scope
    block = program.global_block()
    consts = dict(getattr(program, "_constants", {}) or {})
    available = set(consts)
    for name in scope.var_names():
        available.add(name)
    feeds = set(ctx.feed_names)

    folded = 0
    keep = []
    for op in block.ops:
        ins = all_in_names(op)
        outs = all_out_names(op)
        foldable = (
            op.type not in _BLOCK_OPS + ("feed", "fetch")
            and not op.type.startswith("grad::")
            and not op.attrs.get("__rng__")
            and all(n in available and n not in feeds for n in ins)
            and any(outs)
        )
        if not foldable:
            keep.append(op)
            continue
        attrs = {k: v for k, v in op.attrs.items() if not k.startswith("__")}
        args = [scope.get(n) if scope.has(n) else consts[n] for n in ins]
        try:
            out = kernel(op.type)(*args, **attrs)
        except Exception:
            keep.append(op)  # kernel refused (e.g. eager-only guard)
            continue
        results = list(out) if isinstance(out, (tuple, list)) else [out]
        for name, value in zip(op_out_names(op), results):
            if not name or value is None:
                continue
            scope.set(name, value)
            if block.has_var(name):
                block.var(name).persistable = True
            available.add(name)
        folded += 1
    if folded:
        block.ops[:] = keep
        ctx.bump_version()
    return folded


@register_opt_pass("dead_op_elimination")
def dead_op_elimination(ctx) -> int:
    """Remove side-effect-free top-block ops whose outputs nothing reads.

    Iterates to a fixpoint so dead chains collapse. Deliberately
    conservative — kept, regardless of use counts: control-flow ops,
    ``grad::`` replays (the level-1 byte-identity promise for training
    programs), ops writing persistables or declaring ``__inplace__``,
    and ops with no outputs. Safe by construction for the default
    executor pipeline; also the Predictor's DCE (where it reduces to
    fetch reachability, since inference programs have none of the
    side-effecting forms).
    """
    fetches = set(ctx.fetch_names)
    persist = ctx.persistables()
    removed_total = 0
    while True:
        uses = ctx.use_counts()
        block = ctx.program.global_block()
        keep = []
        removed = 0
        for op in block.ops:
            outs = [n for n in all_out_names(op) if n]
            side_effecting = (
                op.type in _BLOCK_OPS
                or op.type.startswith("grad::")
                or not outs
                or op.attrs.get("__inplace__")
                or any(n in persist for n in outs)
            )
            live = any(n in fetches or uses.get(n, 0) > 0 for n in outs)
            if side_effecting or live:
                keep.append(op)
            else:
                removed += 1
        if not removed:
            break
        block.ops[:] = keep
        ctx.bump_version()
        removed_total += removed
    return removed_total


# ---------------------------------------------------------------------------
# liveness-driven rematerialization
# ---------------------------------------------------------------------------


@register_opt_pass("rematerialize", min_level=2)
def rematerialize(ctx) -> int:
    """Recompute cheap activations at their late uses when over budget.

    Consults :func:`~paddle_tpu.analysis.plan_memory`'s resident curve:
    while the predicted peak exceeds the device HBM budget
    (:func:`~paddle_tpu.analysis.hbm_budget_bytes`), pick the largest
    intermediate live across the high-water op that (a) a flops-light
    deterministic op produces, (b) is only needed again strictly after
    the peak, and (c) can be recomputed there from operands that are
    statically resident (feeds/persistables/constants) or still live —
    never extending any interval. The producer is duplicated right
    before the first late use writing ``<v>@remat<k>`` and the late
    consumers rewired; replan, repeat until the program fits or no
    candidate reduces the peak. Returns remat ops inserted.
    """
    from .memory import hbm_budget_bytes, plan_memory

    budget = hbm_budget_bytes()
    if not budget:
        return 0
    inserted = 0
    prev_peak = None
    for _ in range(_REMAT_MAX_ROUNDS):
        try:
            plan = plan_memory(ctx.program, ctx.feed_names, ctx.fetch_names,
                               feed_shapes=ctx.feed_shapes, top_k=64)
        except Exception:
            return inserted
        if plan.peak_op_index is None or plan.peak_bytes <= budget:
            break
        if prev_peak is not None and plan.peak_bytes > prev_peak:
            break  # the last insertion made things WORSE: stop digging
        # a plateau is allowed: recomputing one of several equally-sized
        # held activations often just moves the high-water op, and the
        # drop only lands once the last of them is rematerialized
        prev_peak = plan.peak_bytes
        if not _remat_once(ctx, plan, inserted):
            break
        inserted += 1
    return inserted


def _remat_once(ctx, plan, serial) -> bool:
    program = ctx.program
    block = program.global_block()
    ops = block.ops
    peak_i = plan.peak_op_index
    persist = ctx.persistables()
    feeds = set(ctx.feed_names)
    consts = set(getattr(program, "_constants", {}) or {})
    statics = persist | feeds | consts
    for blk in program.blocks:
        for name, var in blk.vars.items():
            if var._meta.get("is_data"):
                statics.add(name)

    def_idx: Dict[str, int] = {}
    last_use: Dict[str, int] = {}
    uses_at: Dict[str, List[int]] = {}
    writers: Dict[str, int] = {}
    for i, op in enumerate(ops):
        for n in all_in_names(op):
            if n:
                last_use[n] = i
                uses_at.setdefault(n, []).append(i)
        for n in all_out_names(op):
            if n:
                def_idx.setdefault(n, i)
                writers[n] = writers.get(n, 0) + 1

    # largest-first over the intermediates live at the high-water op
    for name, _bytes, src in plan.top_tensors:
        if src != "intermediate" or name in statics:
            continue
        if name in ctx.fetch_names or writers.get(name, 0) != 1:
            continue
        d = def_idx.get(name)
        if d is None or d >= peak_i:
            continue
        producer = ops[d]
        if (producer.type not in _REMAT_CHEAP_OPS
                or producer.attrs.get("__rng__")
                or producer.attrs.get("__inplace__")
                or _single_out(producer) != name):
            continue
        all_uses = uses_at.get(name, [])
        late = [u for u in all_uses if u > peak_i]
        # the var must die BEFORE the peak once late uses are rewired
        if not late or any(u == peak_i for u in all_uses):
            continue
        t0 = min(late)
        if any(ops[u].type.startswith("grad::") or ops[u].type in _BLOCK_OPS
               for u in late):
            continue
        # every producer operand must be free to re-read at t0: static,
        # or still live there — never extend an interval
        ok = True
        for x in all_in_names(producer):
            if not x or x in statics:
                continue
            if def_idx.get(x, t0) >= t0 or last_use.get(x, -1) < t0:
                ok = False
                break
            if writers.get(x, 0) != 1:
                ok = False
                break
        if not ok:
            continue
        _insert_remat(ctx, block, name, d, t0, late, serial)
        return True
    return False


def _insert_remat(ctx, block, name, d, t0, late_uses, serial):
    from ..static.program import OpDesc

    producer = block.ops[d]
    new_name = f"{name}@remat{serial}"
    shape = _var_shape(block, name)
    block.create_var(name=new_name,
                     shape=None if shape is None else list(shape),
                     dtype=_var_dtype(block, name) or "float32")
    outputs = {slot: [new_name if n == name else n for n in names]
               for slot, names in producer.outputs.items()}
    attrs = {k: v for k, v in producer.attrs.items() if k != "__inplace__"}
    clone = OpDesc(producer.type, {s: list(n) for s, n in
                                   producer.inputs.items()}, outputs, attrs)
    for u in late_uses:
        op = block.ops[u]
        op.inputs.update({
            slot: [new_name if n == name else n for n in names]
            for slot, names in op.inputs.items()})
    block.ops.insert(t0, clone)
    # if rewiring left the original value with zero readers (its only
    # uses were the late ones), the original producer now computes a
    # dead tensor every step — drop it. d < t0 always, so the freshly
    # inserted clone's index is unaffected by the deletion.
    if not any(name in all_in_names(op)
               for blk in ctx.program.blocks for op in blk.ops):
        del block.ops[d]
    ctx.bump_version()


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------


class PassManager:
    """Ordered pass application over a Program, IN PLACE.

    ``apply`` verifies the program up front, then for every selected
    pass: run it, and when it changed the IR re-verify and replan memory
    (the per-pass verify/replan contract). Per-pass
    :class:`PassStats` land on ``self.stats``, profiler counters and
    the monitor registry. Callers that must not mutate their input go
    through :func:`optimize_program`, which clones first and caches."""

    def __init__(self, passes=None):
        unknown = [p for p in (passes or []) if p not in _OPT_PASSES]
        if unknown:
            from ..errors import NotFoundError

            raise NotFoundError(f"unknown optimizer pass(es): {unknown}")
        self.passes = list(passes) if passes is not None \
            else list(_OPT_PASSES)
        self.stats: List[PassStats] = []

    def apply(self, program, feed_names=(), fetch_names=(), *, level=1,
              scope=None, feed_shapes=None, verify=True) -> List[PassStats]:
        ctx = OptContext(program, feed_names, fetch_names, scope=scope,
                         feed_shapes=feed_shapes, level=level)
        if verify:
            program.verify(feed_names=ctx.feed_names,
                           fetch_list=ctx.fetch_names)
        plan_peak = self._peak(ctx)
        stats = []
        for name in self.passes:
            p = _OPT_PASSES[name]
            if p.min_level > ctx.level:
                continue
            if p.needs_scope and scope is None:
                continue
            t0 = time.perf_counter()
            rewritten = int(p.fn(ctx) or 0)
            wall_ms = (time.perf_counter() - t0) * 1e3
            bytes_saved = 0
            if rewritten:
                if verify:
                    program.verify(feed_names=ctx.feed_names,
                                   fetch_list=ctx.fetch_names)
                new_peak = self._peak(ctx)
                if plan_peak is not None and new_peak is not None:
                    bytes_saved = max(0, plan_peak - new_peak)
                plan_peak = new_peak if new_peak is not None else plan_peak
            st = PassStats(name, rewritten, int(bytes_saved), wall_ms)
            stats.append(st)
            _record_pass(st)
        self.stats = stats
        return stats

    @staticmethod
    def _peak(ctx) -> Optional[int]:
        from .memory import plan_memory

        try:
            plan = plan_memory(ctx.program, ctx.feed_names, ctx.fetch_names,
                               feed_shapes=ctx.feed_shapes)
        except Exception:
            return None
        return int(plan.peak_bytes)


# -- stats plumbing (satellite: registry counters + /statz) ------------------

_TOTALS: Dict[str, Dict[str, float]] = {}


def _record_pass(st: PassStats):
    from .. import profiler
    from ..monitor import registry as _registry

    tot = _TOTALS.setdefault(st.name, {
        "runs": 0, "ops_rewritten": 0, "bytes_saved": 0, "wall_ms": 0.0})
    tot["runs"] += 1
    tot["ops_rewritten"] += st.ops_rewritten
    tot["bytes_saved"] += st.bytes_saved
    tot["wall_ms"] += st.wall_ms
    if st.ops_rewritten:
        profiler.bump_counter(
            f"ir_opt::{st.name}::ops_rewritten", st.ops_rewritten)
        _registry.counter(
            f"ir_opt/{st.name}/ops_rewritten",
            help="ops rewritten by this IR-optimizer pass",
        ).inc(st.ops_rewritten)
    if st.bytes_saved:
        profiler.bump_counter(
            f"ir_opt::{st.name}::bytes_saved", st.bytes_saved)
        _registry.counter(
            f"ir_opt/{st.name}/bytes_saved",
            help="planned peak-HBM bytes saved by this pass",
        ).inc(st.bytes_saved)


def optimizer_stats() -> dict:
    """Cumulative per-pass totals for /statz: ``{pass: {runs,
    ops_rewritten, bytes_saved, wall_ms}}``."""
    return {name: dict(tot) for name, tot in _TOTALS.items()}


def reset_optimizer_stats():
    _TOTALS.clear()


# ---------------------------------------------------------------------------
# the cached clone-and-rewrite entry (Executor.run / Predictor)
# ---------------------------------------------------------------------------


def _flag_level() -> int:
    from ..flags import flag

    try:
        return int(str(flag("ir_opt_level")).strip() or "0")
    except (ValueError, KeyError):
        return 0


def _clone_program(program):
    from ..static import program as _prog_mod
    from ..static.program import OpDesc as _OpDesc

    clone = type(program).from_dict(program.to_dict())
    # OpDesc.to_dict ALIASES the source op's input/output dicts (attrs are
    # copied) — Program.clone's only mutation is an attr flip so it never
    # noticed, but the rewrite passes edit inputs/outputs in place and
    # must not reach back into the original program. Rebuild each op with
    # its own structures.
    for blk in clone.blocks:
        blk.ops = [_OpDesc(op.type,
                           {s: list(ns) for s, ns in op.inputs.items()},
                           {s: list(ns) for s, ns in op.outputs.items()},
                           dict(op.attrs))
                   for op in blk.ops]
    clone._name_counter = dict(getattr(program, "_name_counter", {}))
    # fresh process-unique identity: the executor's compile cache keys on
    # it, and an id()-reuse collision would alias two programs
    clone._identity_token = next(_prog_mod._program_token_counter)
    return clone


def optimize_program(program, feed_names=(), fetch_names=(), *, level=None,
                     feed_shapes=None, scope=None, passes=None) -> OptResult:
    """Optimize ``program`` for a (feeds, fetches) run signature.

    Clones, runs the pass pipeline at ``level`` (``FLAGS_ir_opt_level``
    when None), and returns an :class:`OptResult`. When no pass rewrote
    anything the ORIGINAL program object is returned (``changed=False``)
    so downstream compile caches key on the identity they already know.
    Results cache on the program per (version, n_vars, feeds, fetches,
    level, feed-shape signature) with the verifier-cache LRU discipline
    — an unchanged program version pays one dict lookup per run.
    """
    from .. import profiler

    level = _flag_level() if level is None else int(level)
    if level <= 0:
        return OptResult(program, [], False)
    feeds = tuple(sorted(feed_names or ()))
    fetches = tuple(
        v if isinstance(v, str) else v.name for v in (fetch_names or ()))
    shapes_sig = tuple(sorted(
        (n, tuple(int(d) for d in s))
        for n, s in (feed_shapes or {}).items()))
    n_vars = sum(len(b.vars) for b in program.blocks)
    key = (getattr(program, "_version", 0), n_vars, feeds, fetches,
           level, shapes_sig, bool(scope is not None))
    cache = program.__dict__.setdefault("_ir_opt_cache", {})
    hit = cache.get(key)
    if hit is not None:
        cache.pop(key, None)
        cache[key] = hit  # LRU refresh
        profiler.bump_counter("ir_opt::cache_hit")
        return hit
    profiler.bump_counter("ir_opt::cache_miss")
    clone = _clone_program(program)
    mgr = PassManager(passes)
    # honour FLAGS_program_verify=off: a caller who disabled verification
    # must not get VerifyErrors from the optimizer's internal pre/post
    # checks either (the legacy opaque failure path stays reachable)
    from ..flags import flag as _flag

    verify = str(_flag("program_verify")).strip().lower() not in (
        "", "0", "off", "false", "no")
    stats = mgr.apply(clone, feeds, fetches, level=level, scope=scope,
                      feed_shapes=feed_shapes, verify=verify)
    changed = any(s.ops_rewritten for s in stats)
    result = OptResult(clone if changed else program, stats, changed)
    cache[key] = result
    while len(cache) > _CACHE_LIMIT:
        try:
            cache.pop(next(iter(cache)), None)
        except (StopIteration, RuntimeError):
            break
    return result


# ---------------------------------------------------------------------------
# measured per-op before/after (the opprof closure on the pass pipeline)
# ---------------------------------------------------------------------------


def measure_pass_deltas(program, feed, fetch_names=(), *, level=None,
                        passes=None, scope=None, name=None,
                        warmup=None, repeats=None) -> dict:
    """Replay-profile ``program`` before and after the pass pipeline and
    report MEASURED per-op deltas, not just planned-byte/rewrite counts.

    PassStats says a fusion fired; this says what it bought: per-op-type
    measured µs before vs after (monitor.opprof replay), the per-pass
    rewrite stats, and the whole-program speedup. The conv+bn+relu
    fusion's win, for example, shows up as the ``fused_conv_bn_relu``
    rows costing measurably less than the conv2d+batch_norm+relu rows
    they replaced (tools/opprof_smoke.py asserts exactly that).

    Inputs follow :func:`optimize_program` (feed dict + fetch names);
    the program must be runnable from ``scope`` (run it through the
    Executor once first so parameters are materialized). Both profiles
    land in the opprof store as ``<name>@pre`` / ``<name>@post``.
    """
    from ..monitor import opprof as _opprof

    name = name or f"prog{getattr(program, '_identity_token', id(program))}"
    feeds = tuple(sorted(feed or ()))
    fetches = tuple(
        v if isinstance(v, str) else v.name for v in (fetch_names or ()))
    before = _opprof.profile_program(
        program, feed, fetches, scope=scope, name=f"{name}@pre",
        warmup=warmup, repeats=repeats, with_trace=False, record=False)
    result = optimize_program(
        program, feeds, fetches, level=level, passes=passes, scope=scope,
        feed_shapes={k: tuple(getattr(v, "shape", ()) or ())
                     for k, v in (feed or {}).items()})
    after = _opprof.profile_program(
        result.program, feed, fetches, scope=scope, name=f"{name}@post",
        warmup=warmup, repeats=repeats, with_trace=False, record=False)

    def _by_type(profile):
        agg: Dict[str, Dict[str, float]] = {}
        for row in profile["ops"]:
            if not row.get("replayed"):
                continue
            t = agg.setdefault(row["op_type"], {"time_us": 0.0, "ops": 0})
            t["time_us"] = round(t["time_us"] + row["time_us"], 3)
            t["ops"] += 1
        return agg

    before_by, after_by = _by_type(before), _by_type(after)
    deltas = {}
    for op_type in sorted(set(before_by) | set(after_by)):
        b = before_by.get(op_type, {"time_us": 0.0, "ops": 0})
        a = after_by.get(op_type, {"time_us": 0.0, "ops": 0})
        deltas[op_type] = {
            "before_us": b["time_us"], "after_us": a["time_us"],
            "before_ops": b["ops"], "after_ops": a["ops"],
            "delta_us": round(a["time_us"] - b["time_us"], 3),
        }
    return {
        "name": name,
        "changed": result.changed,
        "passes": [{"name": s.name, "ops_rewritten": s.ops_rewritten,
                    "bytes_saved": s.bytes_saved,
                    "wall_ms": round(s.wall_ms, 3)}
                   for s in result.stats],
        "before_us": before["total_us"],
        "after_us": after["total_us"],
        "speedup": (round(before["total_us"] / after["total_us"], 4)
                    if after["total_us"] else None),
        "deltas": deltas,
    }
